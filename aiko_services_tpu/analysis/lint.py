# Event-loop lint: AST rules for the failure modes that only bite under
# load.
#
# The event engine is cooperative — one blocking call in any handler
# stalls EVERY pipeline in the process — and jit-in-frame or
# publish-under-lock bugs pass every unit test, then melt down at the
# 200-stream rung.  These rules are purely lexical (no imports, no
# execution) so they run on user element files too.
#
# Architecture (ISSUE 18 refactor): every rule is a small class
# registered via @rule — it declares its id, severity, a one-line
# catalog `doc`, an `example` waiver line, and ONLY the match hooks it
# needs.  One `_Walker` pass per module drives all of them, maintaining
# the shared state rules used to recompute for themselves: the
# event/hot scope stack, module lock depth, handler registrations, and
# clock-import aliases.  `rule_catalog()` exposes the table for docs
# and the README-coverage test.
#
# Hot-path marking: a `graft: hot-path` comment on (or directly above)
# a `def` line opts that function into the allocation rule — purely
# lexical, like the waivers, so it works on user element files too.
#
# Waivers: a COMMENT containing `graft: disable=<rule-id>` (or with
# the rule list `all`) suppresses that rule on its statement —
# resolved by statement EXTENT, so a trailing waiver on the first
# physical line of a wrapped call suppresses findings reported on its
# continuation lines (ISSUE 18 satellite).  `graft:
# disable-file=<rule[,rule]>` in a comment waives rules for the whole
# file (deliberate-console CLIs under scripts/ and tools/).  Waiver
# comments are found with the tokenizer, so rule ids inside string
# literals (this file's own messages) never self-waive.  Every waiver
# consumed is recorded in the shared WaiverLog; `--self-check` turns
# unconsumed waiver comments into `lint-stale-waiver` warnings so dead
# exceptions get burned down instead of accreting.

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path

from .findings import ERROR, WARNING, Finding

__all__ = ["lint_file", "lint_paths", "lint_source", "LINT_RULES",
           "LintRule", "WaiverIndex", "WaiverLog", "rule_catalog"]

# block-pool allocator call tails (lint-paged-free): the returned ids
# are the only refcount handle — a discarded result is a leak
_POOL_ALLOC_TAILS = {"alloc_blocks", "alloc_block"}

# device<->host transfer calls applied to KV pool-block rows
# (lint-host-transfer, ISSUE 17): tier crossings are synchronous
# millisecond copies — in a handler they stall every decode round.
# Matched lexically: a transfer-call tail from these modules whose
# first argument's source mentions a pool-row expression.
_TRANSFER_TAILS = {"device_put", "asarray", "array"}
_TRANSFER_MODULES = {"jax", "np", "numpy", "jnp", "jax.numpy"}
_POOL_ROW_TOKENS = ("block_rows", "k_rows", "v_rows", "k_pools",
                    "v_pools")

# wall-epoch clock reads (lint-wall-clock): canonical spellings; call
# targets are CANONICALIZED through the module's actual time/datetime
# import aliases first (_clock_aliases), so `import datetime as dt;
# dt.datetime.now()`, `import time as t; t.time()`, and `from time
# import time; time()` all trip — while an unrelated object attribute
# named .time() does not (no alias resolves it).
_WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "date.today", "datetime.date.today",
}


def _clock_aliases(tree: ast.AST) -> dict:
    """Local names bound to the time/datetime modules (or their
    wall-clock members) by this module's imports: {name: canonical}."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for entry in node.names:
                if entry.name in ("time", "datetime"):
                    aliases[entry.asname or entry.name] = entry.name
        elif isinstance(node, ast.ImportFrom):
            if node.module == "datetime":
                for entry in node.names:
                    if entry.name in ("datetime", "date"):
                        aliases[entry.asname or entry.name] = \
                            f"datetime.{entry.name}"
            elif node.module == "time":
                for entry in node.names:
                    if entry.name == "time":
                        aliases[entry.asname or entry.name] = \
                            "time.time"
    return aliases


def _canonical_clock_target(target: str, aliases: dict) -> str:
    head, sep, rest = target.partition(".")
    canonical = aliases.get(head)
    if canonical is None:
        return target
    return f"{canonical}.{rest}" if sep else canonical


# metric-factory call tails whose labels= dict the label rule inspects
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
# identifier fragments that mark a label VALUE as per-request /
# per-session / per-topic — unbounded by construction.  Purely lexical
# (matched against the value expression's source text), like every
# other rule here.
_UNBOUNDED_LABEL_TOKENS = (
    "topic", "session", "sid", "stream_id", "request_id", "hop_id",
    "hop", "client_id", "trace_id", "span_id", "uuid", "frame_id",
)

# evidence that an accumulation target is bounded or shed within the
# same function: any of these appearing against the SAME receiver text
_BOUND_HINTS = (".pop", ".popleft", ".clear", ".maxlen")

_HOT_MARKER = "graft: hot-path"
# array CONSTRUCTORS (fresh allocation per call).  asarray/array are
# deliberately absent: in a hot loop they are host→device transfers of
# existing buffers, which the round cannot avoid.
_ALLOC_TAILS = {"zeros", "ones", "empty", "full", "zeros_like",
                "ones_like", "full_like", "empty_like", "arange",
                "linspace", "eye"}
_ALLOC_MODULES = {"np", "numpy", "jnp", "jax.numpy"}

_HANDLER_REGISTRARS = {
    "add_timer_handler", "add_oneshot_handler", "add_mailbox_handler",
    "add_queue_handler", "add_flatout_handler",
    # transport-inbound handlers run on the event loop too: a blocking
    # call in a message handler — the peer handshake handlers included
    # (transport/peer.py, ISSUE 6) — stalls every pipeline the same way
    "add_message_handler",
}
_FRAME_METHODS = {"process_frame", "start_stream", "stop_stream"}
_BLOCKING_ATTRS = {
    "result": "concurrent-future .result() blocks until completion",
    "block_until_ready": "device sync blocks the event loop",
    "recv": "blocking socket receive",
    "recvfrom": "blocking socket receive",
    "accept": "blocking socket accept",
    "wait_for_publish": "broker round-trip blocks the event loop",
}


def _is_test_path(path: str) -> bool:
    name = Path(path).name
    parts = Path(path).parts
    return name.startswith("test_") or name == "conftest.py" or \
        "tests" in parts


def _func_tail(node: ast.AST) -> str:
    """Last attribute/name component of a call target ('' when dynamic)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _collect_handlers(tree: ast.AST) -> tuple[set, set]:
    """Names (and lambda node ids) registered as event-engine handlers
    anywhere in the module — including method references like
    self._mailbox_handler."""
    names: set = set()
    lambda_ids: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if _func_tail(node.func) not in _HANDLER_REGISTRARS:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            names.add(target.id)
        elif isinstance(target, ast.Attribute):
            names.add(target.attr)
        elif isinstance(target, ast.Lambda):
            lambda_ids.add(id(target))
    return names, lambda_ids


def _mentions_lock(node: ast.AST) -> bool:
    return "lock" in ast.unparse(node).lower()


# ---------------------------------------------------------------------------
# waivers — comment-scanned, statement-extent resolved

_WAIVER_RE = re.compile(r"graft:\s*disable=([\w\-]+(?:\s*,\s*[\w\-]+)*)")
_FILE_WAIVER_RE = re.compile(
    r"graft:\s*disable-file=([\w\-]+(?:\s*,\s*[\w\-]+)*)")


def _split_rules(spec: str) -> set:
    return {part.strip() for part in spec.split(",") if part.strip()}


class WaiverIndex:
    """Per-file waiver resolution.

    A waiver is a COMMENT carrying `graft: disable=<rules>`; it covers
    the statement whose extent contains the comment's line (plus the
    immediately following line, preserving the comment-above-the-site
    idiom).  `graft: disable-file=<rules>` covers the whole file.
    """

    def __init__(self, source: str, tree: ast.AST | None = None):
        self.lines = source.splitlines()
        # comment text by 1-based line, via the tokenizer so waiver
        # spellings inside string literals never count
        self.comments: dict[int, str] = {}
        try:
            for token in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if token.type == tokenize.COMMENT:
                    self.comments[token.start[0]] = token.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unterminated source (mid-edit files): fall back to raw
            # line text, the pre-tokenizer behavior
            for number, text in enumerate(self.lines, start=1):
                if "#" in text:
                    self.comments[number] = text[text.index("#"):]
        self.waiver_lines: dict[int, set] = {}
        self.file_rules: dict[int, set] = {}
        for number, text in self.comments.items():
            match = _FILE_WAIVER_RE.search(text)
            if match:
                self.file_rules[number] = _split_rules(match.group(1))
                continue
            match = _WAIVER_RE.search(text)
            if match:
                self.waiver_lines[number] = _split_rules(match.group(1))
        # statement extents for multi-line waiver resolution
        self._extents: list[tuple[int, int]] = []
        if tree is None:
            try:
                tree = ast.parse(source)
            except SyntaxError:
                tree = None
        if tree is not None:
            for node in ast.walk(tree):
                if isinstance(node, ast.stmt) and \
                        getattr(node, "end_lineno", None):
                    self._extents.append((node.lineno, node.end_lineno))
            self._extents.sort()

    def _statement_extent(self, lineno: int) -> tuple[int, int]:
        """Innermost statement extent containing `lineno` (the latest-
        starting statement whose span covers it)."""
        best = (lineno, lineno)
        for start, end in self._extents:
            if start > lineno:
                break
            if end >= lineno:
                best = (start, end)
        return best

    def candidate_lines(self, lineno: int):
        start, end = self._statement_extent(lineno)
        seen: set = set()
        for number in (lineno, lineno - 1, start, start - 1, end):
            if number >= 1 and number not in seen:
                seen.add(number)
                yield number

    def match(self, rule: str, lineno: int):
        """The waiver-comment line suppressing `rule` at `lineno`, or
        None.  File-level waivers return their own comment line."""
        for number in self.candidate_lines(lineno):
            rules = self.waiver_lines.get(number)
            if rules and (rule in rules or "all" in rules):
                return number
        for number, rules in self.file_rules.items():
            if rule in rules or "all" in rules:
                return number
        return None


class WaiverLog:
    """Cross-file record of which waiver comments actually suppressed
    something — the lint pass AND the interprocedural effects pass both
    feed it, so `--self-check` can flag dead waivers for burn-down."""

    def __init__(self):
        self.sites: dict[str, dict[int, set]] = {}
        self.used: set = set()

    def register(self, path: str, waivers: WaiverIndex) -> None:
        merged = dict(waivers.waiver_lines)
        merged.update(waivers.file_rules)
        self.sites[path] = merged

    def mark_used(self, path: str, lineno: int) -> None:
        self.used.add((path, lineno))

    def stale_findings(self) -> list:
        findings = []
        for path, lines in sorted(self.sites.items()):
            if _is_test_path(path):
                continue
            for lineno in sorted(lines):
                if (path, lineno) not in self.used:
                    rules = ",".join(sorted(lines[lineno]))
                    findings.append(Finding(
                        "lint-stale-waiver", WARNING, path, lineno,
                        f"waiver `graft: disable={rules}` suppresses "
                        f"nothing (syntactic and effect passes both "
                        f"clean here): remove it so the audit trail "
                        f"stays honest"))
        return findings


# ---------------------------------------------------------------------------
# rule registration

_REGISTRY: list = []


def rule(cls):
    """Register a LintRule subclass; declaration order is table order."""
    _REGISTRY.append(cls())
    return cls


class LintRule:
    """One lint rule: id, severity, catalog line, waiver example, and
    only the match hooks it needs.  Hooks left on the base class are
    never dispatched (the walker buckets rules per hook at import)."""

    id = ""
    severity = ERROR
    doc = ""
    example = ""

    # module-wide hooks (every node in the file)
    def module_call(self, ctx, node):       # pragma: no cover — stub
        raise NotImplementedError

    def module_assert(self, ctx, node):     # pragma: no cover — stub
        raise NotImplementedError

    # event/hot-context hooks (innermost function is an event handler
    # or carries the hot-path marker; scope says which)
    def context_call(self, ctx, scope, node):   # pragma: no cover
        raise NotImplementedError

    def context_assign(self, ctx, scope, node):  # pragma: no cover
        raise NotImplementedError

    def context_expr(self, ctx, scope, node):   # pragma: no cover
        raise NotImplementedError


class _Scope:
    """One function frame on the walker's scope stack.  Nested defs get
    their OWN scope (a nested thread target may legitimately block;
    nested registered handlers qualify on their own name), so context
    rules never leak into inner functions."""

    __slots__ = ("name", "event", "hot", "_source")

    def __init__(self, name: str, event: bool, hot: bool,
                 node: ast.AST | None = None):
        self.name = name
        self.event = event
        self.hot = hot
        self._source = ""
        if (event or hot) and node is not None:
            try:
                self._source = ast.unparse(node)
            except Exception:   # pragma: no cover — unparse is total
                self._source = ""

    @property
    def active(self) -> bool:
        return self.event or self.hot

    def receiver_bounded(self, receiver: str) -> bool:
        """True when the enclosing function visibly bounds or sheds the
        accumulation target: pops/clears it, checks len() against it,
        deletes entries — or the target is a LOCAL the function itself
        created (a per-call list dies with the call; the rule is about
        state that outlives the handler).  Purely lexical, like the
        waivers."""
        if "." not in receiver and "[" not in receiver and (
                f"{receiver} = " in self._source
                or f"{receiver}: " in self._source):
            return True
        return any(f"{receiver}{hint}" in self._source
                   for hint in _BOUND_HINTS) \
            or f"len({receiver})" in self._source \
            or f"del {receiver}" in self._source

    def cache_exempt(self, receiver: str) -> bool:
        """lint-unbounded-cache exemptions beyond receiver_bounded:
        per-stream scratch space (stream.variables — torn down with
        the stream, the sanctioned keyed-state home for elements) is
        bounded by stream lifetime, not by code in this function."""
        return receiver.endswith("stream.variables") or \
            self.receiver_bounded(receiver)


# ---------------------------------------------------------------------------
# the rules, in catalog order


@rule
class BlockingCallRule(LintRule):
    id = "lint-blocking-call"
    doc = ("time.sleep / .result() / .block_until_ready() / blocking "
           "socket ops reached from an event-loop context (frame "
           "methods and every add_*_handler registration) — one "
           "blocking call stalls every pipeline in the process")
    example = "future.result()  # graft: disable=lint-blocking-call"

    def context_call(self, ctx, scope, node):
        if not scope.event:
            return
        tail = _func_tail(node.func)
        target = ast.unparse(node.func)
        if target == "time.sleep":
            ctx.report(
                self.id, node,
                f"time.sleep in event-loop context {scope.name!r} "
                f"stalls every pipeline in the process (use a timer "
                f"handler)")
        elif tail in _BLOCKING_ATTRS:
            ctx.report(
                self.id, node,
                f".{tail}() in event-loop context {scope.name!r}: "
                f"{_BLOCKING_ATTRS[tail]}")


@rule
class RawLockRule(LintRule):
    id = "lint-raw-lock"
    doc = ("threading.Lock() where the diagnostic utils.lock.Lock is "
           "required (named holder, misuse errors, lock-order cycle "
           "detection); threading.RLock is exempt")
    example = "threading.Lock()  # graft: disable=lint-raw-lock"

    def module_call(self, ctx, node):
        if ast.unparse(node.func) == "threading.Lock":
            ctx.report(
                self.id, node,
                "raw threading.Lock: use aiko_services_tpu.utils.Lock "
                "(named holder, misuse errors, AIKO_LOCK_CHECK "
                "lock-order cycle detection)")


@rule
class AssertRule(LintRule):
    id = "lint-assert"
    doc = ("`assert` used for validation in non-test code (compiled "
           "away under -O; raise instead)")
    example = "assert ready  # graft: disable=lint-assert"

    def module_assert(self, ctx, node):
        if not ctx.is_test:
            ctx.report(
                self.id, node,
                "assert used for validation in non-test code: compiled "
                "away under python -O — raise ValueError/RuntimeError")


@rule
class PublishLockedRule(LintRule):
    id = "lint-publish-locked"
    doc = ("broker publish/route while holding a lock (delivery can "
           "re-enter or block under the lock)")
    example = "bus.publish(topic, m)  # graft: disable=lint-publish-locked"

    def module_call(self, ctx, node):
        if ctx.lock_depth > 0 and \
                _func_tail(node.func) in ("publish", "route"):
            ctx.report(
                self.id, node,
                f".{_func_tail(node.func)}() while holding a lock: "
                f"delivery can re-enter or block under the lock — "
                f"buffer under the lock, publish after release")


@rule
class JitHotRule(LintRule):
    id = "lint-jit-hot"
    doc = ("jax.jit in per-frame code (a recompile per frame-shape: "
           "the classic serving latency cliff)")
    example = "fn = jax.jit(step)  # graft: disable=lint-jit-hot"

    def context_call(self, ctx, scope, node):
        if scope.event and ast.unparse(node.func) in ("jax.jit", "jit"):
            ctx.report(
                self.id, node,
                f"jax.jit in per-frame context {scope.name!r}: "
                f"build the jitted program once in __init__/_setup "
                f"(per-frame jit recompiles per shape)")


@rule
class HotAllocRule(LintRule):
    id = "lint-hot-alloc"
    doc = ("numpy/jnp array CONSTRUCTION (np.zeros, jnp.full, arange, "
           "...) inside a `# graft: hot-path` function — preallocate "
           "in __init__ and refill in place; transfers (np.asarray of "
           "an existing buffer) are not flagged")
    example = "buf = np.zeros(n)  # graft: disable=lint-hot-alloc"

    def context_call(self, ctx, scope, node):
        tail = _func_tail(node.func)
        target = ast.unparse(node.func)
        if scope.hot and tail in _ALLOC_TAILS and \
                target.rpartition(".")[0] in _ALLOC_MODULES:
            ctx.report(
                self.id, node,
                f"{target}() allocates a fresh array every pass through "
                f"hot path {scope.name!r}: preallocate in "
                f"__init__/_setup and refill in place (per-round host "
                f"allocations are the pump loop's death by a thousand "
                f"cuts)")


@rule
class PrintRule(LintRule):
    id = "lint-print"
    doc = ("bare print( in package (non-test) modules: telemetry flows "
           "through utils.logger or the observe registry — deliberate "
           "console CLIs carry waivers (or a file-level "
           "`graft: disable-file=lint-print`)")
    example = "print(report)  # graft: disable=lint-print"

    def module_call(self, ctx, node):
        if isinstance(node.func, ast.Name) and \
                node.func.id == "print" and not ctx.is_test:
            ctx.report(
                self.id, node,
                "bare print( in package module: route telemetry "
                "through utils.logger / the observe metrics registry "
                "(deliberate console output carries a "
                "`graft: disable=lint-print` waiver)")


@rule
class UnboundedQueueRule(LintRule):
    id = "lint-unbounded-queue"
    doc = ("accumulation in event-handler contexts with no visible "
           "bound or shed policy: a bare deque() stored beyond the "
           "call, or .append whose receiver is never popped, cleared, "
           "len()-checked, or deleted from")
    example = "self.q.append(x)  # graft: disable=lint-unbounded-queue"

    def context_call(self, ctx, scope, node):
        tail = _func_tail(node.func)
        if scope.event and tail in ("append", "appendleft") and \
                isinstance(node.func, ast.Attribute):
            receiver = ast.unparse(node.func.value)
            if not scope.receiver_bounded(receiver):
                ctx.report(
                    self.id, node,
                    f"{receiver}.{tail}() accumulates in event-loop "
                    f"context {scope.name!r} with no visible "
                    f"bound or shed policy in this function: cap "
                    f"it (maxlen / len() check / shed-oldest) or "
                    f"waive the audited site with `graft: "
                    f"disable=lint-unbounded-queue`")

    def context_assign(self, ctx, scope, node):
        # a bare deque() STORED beyond the call (attribute/subscript
        # target) in an event context is an unbounded cross-frame
        # queue; a per-call local deque dies with the call, mirroring
        # receiver_bounded's local exemption for .append
        if scope.event and isinstance(node.value, ast.Call) and \
                _func_tail(node.value.func) == "deque" and \
                not any(kw.arg == "maxlen"
                        for kw in node.value.keywords) and \
                any(not isinstance(target, ast.Name)
                    for target in node.targets):
            ctx.report(
                self.id, node,
                f"unbounded deque() stored from event-loop context "
                f"{scope.name!r}: give it a maxlen or a shed policy "
                f"— handler-side accumulation without a bound queues "
                f"until deadlines blow instead of shedding at "
                f"admission")


@rule
class UnboundedCacheRule(LintRule):
    id = "lint-unbounded-cache"
    doc = ("dict/OrderedDict CACHES mutated from event-handler or "
           "hot-path contexts with no eviction on the same receiver "
           "(subscript store or .setdefault with a dynamic key): one "
           "entry per distinct key forever")
    example = "self.c[k] = v  # graft: disable=lint-unbounded-cache"

    def context_call(self, ctx, scope, node):
        if _func_tail(node.func) == "setdefault" and \
                isinstance(node.func, ast.Attribute) and node.args and \
                not isinstance(node.args[0], ast.Constant):
            receiver = ast.unparse(node.func.value)
            if not scope.cache_exempt(receiver):
                ctx.report(
                    self.id, node,
                    f"{receiver}.setdefault() grows a keyed cache in "
                    f"context {scope.name!r} with no eviction on the "
                    f"same receiver: pop/popitem/clear or a len() "
                    f"budget check must bound it, or waive the audited "
                    f"site with `graft: disable=lint-unbounded-cache`")

    def context_assign(self, ctx, scope, node):
        # a keyed store (`cache[key] = value`) with no eviction on the
        # same receiver: the unbounded-queue rule's sibling for
        # dict/OrderedDict caches — one entry per distinct key forever.
        # Plain Assign only: AugAssign on a subscript (`stats[k] += 1`)
        # mutates an EXISTING entry, the counter idiom, not insertion
        # growth.  Constant keys are exempt (a fixed-field record
        # update cannot grow — `state["latest"] = frame` is a register,
        # not a cache); growth requires a DYNAMIC key.
        for target in node.targets:
            if not isinstance(target, ast.Subscript) or \
                    isinstance(target.slice, ast.Constant):
                continue
            receiver = ast.unparse(target.value)
            if scope.cache_exempt(receiver):
                continue
            ctx.report(
                self.id, node,
                f"{receiver}[...] = stores into a keyed cache in "
                f"context {scope.name!r} with no eviction on "
                f"the same receiver (pop/popitem/clear/del/len() "
                f"budget check): a per-key cache grows FOREVER — "
                f"bound it like the prefix cache's byte budgets, "
                f"or waive the audited site with `graft: "
                f"disable=lint-unbounded-cache`")


@rule
class LinearTimerRule(LintRule):
    id = "lint-linear-timer"
    doc = ("remove_timer_handler called with a handler FUNCTION "
           "instead of a handle: O(n) identity scan per cancel — keep "
           "the handle add_*_handler returned and cancel by it")
    example = "remove_timer_handler(h)  # graft: disable=lint-linear-timer"

    def module_call(self, ctx, node):
        if _func_tail(node.func) == "remove_timer_handler" and node.args:
            arg_tail = _func_tail(node.args[0])
            if arg_tail and arg_tail in ctx.handler_names:
                ctx.report(
                    self.id, node,
                    f"remove_timer_handler({arg_tail}) cancels by "
                    f"HANDLER IDENTITY — a linear scan over every "
                    f"outstanding timer (O(n) at session cardinality): "
                    f"keep the handle add_*_handler returned and cancel "
                    f"by it (O(1) on the timer wheel); the sparse "
                    f"periodic heap's internal scan is the one waived "
                    f"exception")


@rule
class MetricLabelRule(LintRule):
    id = "lint-metric-label"
    doc = ("an UNBOUNDED value (topic path, session/stream/request/hop "
           "id) used as a metric label: every distinct value mints a "
           "registry series forever — a cardinality bomb")
    example = 'labels={"tenant": t}  # graft: disable=lint-metric-label'

    # underscores count as separators (unlike \b): "topic_path" and
    # "session_id" must trip on their stems, "inside"/"shop" must not
    _LABEL_TOKEN_RE = re.compile(
        r"(?<![a-z0-9])(" + "|".join(_UNBOUNDED_LABEL_TOKENS)
        + r")(?![a-z0-9])")

    def module_call(self, ctx, node):
        """Inspect the labels= dict (or the third positional argument)
        of a counter/gauge/histogram get-or-create call for unbounded
        label values — dynamic expressions whose source text names a
        per-request identity (topic, session id, hop id, ...), or a
        suspicious label KEY fed a dynamic value."""
        if _func_tail(node.func) not in _METRIC_FACTORIES or \
                ctx.is_test:
            return
        labels_node = None
        for keyword in node.keywords:
            if keyword.arg == "labels":
                labels_node = keyword.value
                break
        if labels_node is None and len(node.args) >= 3:
            labels_node = node.args[2]
        if not isinstance(labels_node, ast.Dict):
            return
        for key_node, value_node in zip(labels_node.keys,
                                        labels_node.values):
            if isinstance(value_node, ast.Constant):
                continue
            value_text = ast.unparse(value_node).lower()
            key_text = "" if key_node is None \
                else ast.unparse(key_node).lower()
            if self._LABEL_TOKEN_RE.search(value_text) or \
                    self._LABEL_TOKEN_RE.search(key_text):
                label = key_text or value_text
                ctx.report(
                    self.id, value_node,
                    f"metric label {label} takes an unbounded value "
                    f"({ast.unparse(value_node)}): every distinct "
                    f"value mints a registry series FOREVER — label by "
                    f"bounded dimensions (tenant, kind, reason, "
                    f"pipeline name) or waive the audited site with "
                    f"`graft: disable=lint-metric-label`")


@rule
class WallClockRule(LintRule):
    id = "lint-wall-clock"
    doc = ("time.time() / datetime.now() / utcnow() / today() in "
           "package modules: use the engine clock for event/deadline "
           "time, monotonic/perf_counter for durations — wall time "
           "breaks virtual-clock determinism")
    example = "time.time()  # graft: disable=lint-wall-clock"

    def module_call(self, ctx, node):
        if not ctx.is_test and _canonical_clock_target(
                ast.unparse(node.func),
                ctx.clock_aliases) in _WALL_CLOCK_CALLS:
            ctx.report(
                self.id, node,
                f"{ast.unparse(node.func)}() reads the wall-epoch "
                f"clock in a package module: use the engine clock "
                f"(runtime.event.clock.now()) for event/deadline "
                f"time, time.monotonic()/perf_counter() for "
                f"durations — wall time breaks virtual-clock "
                f"determinism and merged flight timelines (calendar-"
                f"time sites carry a `graft: disable=lint-wall-clock` "
                f"waiver)")


@rule
class PagedFreeRule(LintRule):
    id = "lint-paged-free"
    doc = ("block-pool .alloc_blocks() result DISCARDED in event/hot "
           "contexts: the returned ids are the only refcount handle — "
           "a bare-statement alloc leaks pool blocks forever")
    example = "ids = pool.alloc_blocks(n)  # capture, release at retire"

    def context_expr(self, ctx, scope, node):
        # a bare-statement pool alloc drops the ONLY handle to the
        # allocated blocks' refcounts — nothing can ever release them,
        # so the pool leaks one block set per pass
        if isinstance(node.value, ast.Call) and \
                _func_tail(node.value.func) in _POOL_ALLOC_TAILS and \
                isinstance(node.value.func, ast.Attribute):
            receiver = ast.unparse(node.value.func.value)
            ctx.report(
                self.id, node,
                f"{receiver}.{_func_tail(node.value.func)}() result "
                f"discarded in context {scope.name!r}: the returned "
                f"block ids are the only refcount handle — capture "
                f"them and release at retire, or the pool leaks one "
                f"allocation per pass (waive an audited site with "
                f"`graft: disable=lint-paged-free`)")


@rule
class PallasFallbackRule(LintRule):
    id = "lint-pallas-fallback"
    doc = ("pl.pallas_call without an interpret= keyword: every kernel "
           "site must carry the interpret/compiled dispatch seam so "
           "tier-1 runs the same kernel code path on CPU")
    example = "pl.pallas_call(k, interpret=_interpret())"

    def module_call(self, ctx, node):
        if _func_tail(node.func) == "pallas_call" and \
                not ctx.is_test and \
                not any(kw.arg == "interpret" for kw in node.keywords):
            ctx.report(
                self.id, node,
                "pallas_call without an interpret= keyword: every "
                "kernel site must carry the interpret/compiled "
                "dispatch seam (auto-select interpret off-TPU, the "
                "ops/attention.py pattern) so tier-1 runs the same "
                "kernel code path on CPU instead of skipping it")


@rule
class HostTransferRule(LintRule):
    id = "lint-host-transfer"
    doc = ("device↔host copies of KV pool-block rows (device_put / "
           "np.asarray / np.array of block_rows()/k_rows/... ) inside "
           "event or hot contexts: a tier crossing is a synchronous "
           "per-block copy — route it through the AsyncPromoter seam")
    example = "np.asarray(k_rows)  # graft: disable=lint-host-transfer"

    def context_call(self, ctx, scope, node):
        tail = _func_tail(node.func)
        target = ast.unparse(node.func)
        if tail in _TRANSFER_TAILS and node.args and \
                (target.rpartition(".")[0] in _TRANSFER_MODULES
                 or target == "device_put"):
            arg_src = ast.unparse(node.args[0])
            if any(token in arg_src for token in _POOL_ROW_TOKENS):
                ctx.report(
                    self.id, node,
                    f"{target}() copies KV pool-block rows across the "
                    f"device/host boundary in context {scope.name!r}: "
                    f"a tier crossing is a synchronous per-block copy "
                    f"that stalls every decode round — route it "
                    f"through the tiered cache's prefetcher seam "
                    f"(AsyncPromoter stages off-loop, the loop "
                    f"installs staged arrays) or waive the audited "
                    f"site with `graft: disable=lint-host-transfer`")


# modules whose pool/host seam calls report into the KV memory ledger
# (ISSUE 20) — a direct seam call anywhere else bypasses attribution
_LEDGER_SEAM_TAILS = ("alloc_blocks", "release_blocks",
                      "put_from_device", "pop_promoted")
_LEDGER_SEAM_MODULES = ("serving.py", "serving_paged.py",
                        "serving_tiered.py", "serving_disagg.py",
                        "serving_chaos.py", "ledger.py")


@rule
class LedgerSeamRule(LintRule):
    id = "lint-ledger-seam"
    doc = ("direct BlockPool alloc_blocks/release_blocks or host-store "
           "put_from_device/pop_promoted call outside the "
           "ledger-instrumented serving modules: bytes moved there "
           "never reach the KV memory ledger, so per-tenant "
           "attribution silently under-counts")
    example = "pool.alloc_blocks(n)  # graft: disable=lint-ledger-seam"

    def module_call(self, ctx, node):
        tail = _func_tail(node.func)
        if tail not in _LEDGER_SEAM_TAILS or \
                not isinstance(node.func, ast.Attribute):
            return
        if ctx.is_test or Path(ctx.path).name in _LEDGER_SEAM_MODULES:
            return
        receiver = ast.unparse(node.func.value)
        ctx.report(
            self.id, node,
            f"{receiver}.{tail}() outside the ledger-instrumented "
            f"serving modules: this block/byte movement bypasses the "
            f"KV memory ledger — route it through the instrumented "
            f"seams (serving/serving_paged/serving_tiered/"
            f"serving_disagg) or waive an audited site with "
            f"`graft: disable=lint-ledger-seam`")


# stable public rule-id table, in registration (catalog) order —
# lint-parse (the syntax-failure pseudo-rule) and lint-stale-waiver
# (the self-check audit) are emitted outside the registry
LINT_RULES = tuple(entry.id for entry in _REGISTRY)

# rules emitted by the other analysis layers (effects, drift,
# baseline, the waiver audit) — no visitor entry, but the catalog and
# README table must still name them
_LAYER_RULES = (
    ("lint-lock-order", WARNING,
     "static lock-order cycle: a with-lock body (transitively) "
     "acquires a lock that elsewhere (transitively) acquires this one "
     "— the static twin of the AIKO_LOCK_CHECK runtime detector",
     ""),
    ("lint-metric-drift", ERROR,
     "metric family consumed (bench/scripts/tools/autoscaler/"
     "dashboard/observe) but never created in any registry — or "
     "created and mentioned nowhere else (warning); hardware-only "
     "fields live in METRIC_DRIFT_ALLOWLIST",
     'registry.value("asr_frames_total")'),
    ("lint-wire-schema", ERROR,
     "transport/wire.py envelope constants diverge from the committed "
     "analysis/wire_schema.lock — envelope changes must be a "
     "two-sided diff (--update-wire-lock)",
     ""),
    ("lint-stale-waiver", WARNING,
     "a `graft: disable=` comment that suppressed nothing across the "
     "syntactic AND effect passes — remove it so the audit trail "
     "stays honest",
     ""),
    ("baseline-stale", WARNING,
     "a baseline entry that no longer matches any finding — the debt "
     "was paid down; regenerate with --update-baseline",
     ""),
)


def rule_catalog() -> list:
    """(id, severity, doc, example) per rule, visitor-registered rules
    first, then the layer rules — powers `--rules`, the README rule
    table, and its coverage test."""
    return [(entry.id, entry.severity, entry.doc, entry.example)
            for entry in _REGISTRY] + list(_LAYER_RULES)


def _bucket(hook: str) -> tuple:
    return tuple(entry for entry in _REGISTRY
                 if type(entry).__dict__.get(hook) is not None)


_MODULE_CALL_RULES = _bucket("module_call")
_MODULE_ASSERT_RULES = _bucket("module_assert")
_CONTEXT_CALL_RULES = _bucket("context_call")
_CONTEXT_ASSIGN_RULES = _bucket("context_assign")
_CONTEXT_EXPR_RULES = _bucket("context_expr")


# ---------------------------------------------------------------------------
# the one walker


class _LintContext:
    """Per-module state shared by every rule: reporting (with waiver
    resolution and dedupe), handler registrations, clock aliases, and
    the module-wide lock depth."""

    def __init__(self, path: str, source: str, tree: ast.AST,
                 waiver_log: WaiverLog | None = None):
        self.path = path
        self.is_test = _is_test_path(path)
        self.waivers = WaiverIndex(source, tree)
        self.waiver_log = waiver_log
        self.handler_names, self.lambda_ids = _collect_handlers(tree)
        self.clock_aliases = _clock_aliases(tree)
        self.lock_depth = 0
        self.lines = self.waivers.lines
        self.findings: list = []
        self._seen: set = set()
        if waiver_log is not None:
            waiver_log.register(path, self.waivers)

    def report(self, rule_id: str, node: ast.AST, message: str,
               severity: str = ERROR) -> None:
        key = (rule_id, node.lineno, getattr(node, "col_offset", 0))
        if key in self._seen:
            return
        waived_at = self.waivers.match(rule_id, node.lineno)
        if waived_at is not None:
            if self.waiver_log is not None:
                self.waiver_log.mark_used(self.path, waived_at)
            return
        self._seen.add(key)
        self.findings.append(
            Finding(rule_id, severity, self.path, node.lineno, message))

    def hot_marked(self, node) -> bool:
        """`graft: hot-path` on the def line (or the line above —
        decorator or standalone comment) opts the function into the
        allocation rule."""
        for line_number in (node.lineno, node.lineno - 1):
            if 1 <= line_number <= len(self.lines) and \
                    _HOT_MARKER in self.lines[line_number - 1]:
                return True
        return False


class _Walker(ast.NodeVisitor):
    def __init__(self, ctx: _LintContext):
        self.ctx = ctx
        self._scopes: list = []

    def _scope(self):
        return self._scopes[-1] if self._scopes else None

    # -- scopes ------------------------------------------------------------
    def visit_FunctionDef(self, node):
        ctx = self.ctx
        event = node.name in _FRAME_METHODS or \
            node.name in ctx.handler_names
        hot = ctx.hot_marked(node)
        self._scopes.append(_Scope(node.name, event, hot, node))
        self.generic_visit(node)
        self._scopes.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        event = id(node) in self.ctx.lambda_ids
        self._scopes.append(
            _Scope("<lambda handler>", event, False,
                   node if event else None))
        self.generic_visit(node)
        self._scopes.pop()

    def visit_With(self, node):
        locked = any(_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self.ctx.lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.ctx.lock_depth -= 1

    # -- dispatch ----------------------------------------------------------
    def visit_Call(self, node):
        for entry in _MODULE_CALL_RULES:
            entry.module_call(self.ctx, node)
        scope = self._scope()
        if scope is not None and scope.active:
            for entry in _CONTEXT_CALL_RULES:
                entry.context_call(self.ctx, scope, node)
        self.generic_visit(node)

    def visit_Assert(self, node):
        for entry in _MODULE_ASSERT_RULES:
            entry.module_assert(self.ctx, node)
        self.generic_visit(node)

    def visit_Assign(self, node):
        scope = self._scope()
        if scope is not None and scope.active:
            for entry in _CONTEXT_ASSIGN_RULES:
                entry.context_assign(self.ctx, scope, node)
        self.generic_visit(node)

    def visit_Expr(self, node):
        scope = self._scope()
        if scope is not None and scope.active:
            for entry in _CONTEXT_EXPR_RULES:
                entry.context_expr(self.ctx, scope, node)
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# public API


def lint_source(source: str, path: str = "<string>",
                waiver_log: WaiverLog | None = None) -> list:
    """Lint one source text; returns Findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding("lint-parse", ERROR, path, exc.lineno or 0,
                        f"syntax error: {exc.msg}")]
    ctx = _LintContext(path, source, tree, waiver_log)
    _Walker(ctx).visit(tree)
    return ctx.findings


def lint_file(pathname, waiver_log: WaiverLog | None = None) -> list:
    path = Path(pathname)
    try:
        source = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as exc:
        return [Finding("lint-parse", ERROR, str(path), 0, str(exc))]
    return lint_source(source, str(path), waiver_log)


def lint_paths(paths, waiver_log: WaiverLog | None = None) -> list:
    """Lint files and/or directories (recursive over *.py)."""
    findings: list = []
    for entry in paths:
        path = Path(entry)
        if path.is_dir():
            for file_path in sorted(path.rglob("*.py")):
                if "__pycache__" in file_path.parts:
                    continue
                findings.extend(lint_file(file_path, waiver_log))
        else:
            findings.extend(lint_file(path, waiver_log))
    return findings
