# Event-loop lint: AST rules for the failure modes that only bite under
# load.
#
# The event engine is cooperative — one blocking call in any handler
# stalls EVERY pipeline in the process — and jit-in-frame or
# publish-under-lock bugs pass every unit test, then melt down at the
# 200-stream rung.  These rules are purely lexical (no imports, no
# execution) so they run on user element files too.
#
#   lint-blocking-call    time.sleep / .result() / .block_until_ready()
#                         / blocking socket ops inside an event-loop
#                         context (process_frame, start_stream,
#                         stop_stream, or any function registered via
#                         add_*_handler — including add_message_handler,
#                         so transport-inbound and peer-handshake
#                         handlers are covered)
#   lint-raw-lock         threading.Lock() where the diagnostic
#                         utils.lock.Lock is required (named holder,
#                         misuse errors, lock-order cycle detection);
#                         threading.RLock is exempt (the diagnostic lock
#                         is not reentrant)
#   lint-assert           `assert` used for validation in non-test code
#                         (compiled away under -O; raise instead)
#   lint-publish-locked   broker publish/route while holding a lock
#                         (delivery can re-enter or block under the lock)
#   lint-jit-hot          jax.jit in per-frame code (a recompile per
#                         frame-shape: the classic serving latency cliff)
#   lint-hot-alloc        numpy/jnp array CONSTRUCTION (np.zeros,
#                         jnp.full, arange, ...) inside a function
#                         marked `# graft: hot-path` — the serving pump
#                         loop's per-round allocations are death by a
#                         thousand cuts at high round rates; preallocate
#                         in __init__ and refill in place.  Transfers
#                         (np.asarray / jnp.array of an existing
#                         buffer) are NOT flagged: moving bytes to the
#                         device is the round's job, allocating fresh
#                         host arrays per round is not.
#   lint-print            bare print( in package (non-test) modules:
#                         telemetry must flow through utils.logger or
#                         the observe metrics registry, where it is
#                         levelled, routable, and exportable — stdout
#                         is none of those (CLIs and deliberate console
#                         tools carry per-line waivers)
#   lint-linear-timer     remove_timer_handler called with a HANDLER
#                         FUNCTION instead of a handle: removal by
#                         identity is a linear scan over every
#                         outstanding timer — O(n) per cancel at
#                         session cardinality, exactly the pattern the
#                         timer wheel (state/wheel.py) exists to kill.
#                         Keep the handle add_*_handler returned and
#                         cancel by it (O(1) on the wheel).  The
#                         sparse periodic-handler heap keeps the
#                         identity path for reference parity; its one
#                         internal scan carries a waiver
#   lint-wall-clock       time.time() / datetime.now() / utcnow() /
#                         today() in package (non-test) modules: the
#                         runtime keeps THREE clocks on purpose — the
#                         engine clock (virtual in every deterministic
#                         test; event timestamps, deadlines, windowed
#                         series), time.monotonic (scheduler stamps),
#                         and time.perf_counter (span walls) — and the
#                         wall-epoch clock is none of them.  A
#                         wall-epoch stamp breaks virtual-clock
#                         determinism, jumps with NTP, and lands
#                         instants decades off a merged flight
#                         timeline (the exact bug class fixed twice in
#                         the PR 11 FlightLogHandler review).  Sites
#                         that genuinely need calendar time (report
#                         filenames, human-readable logs) carry
#                         per-line waivers
#   lint-metric-label     an UNBOUNDED value (raw topic path, session /
#                         stream / request / hop / client id) used as a
#                         metric label in a counter/gauge/histogram
#                         family: every distinct label value mints a
#                         new series FOREVER (the registry never
#                         forgets), so per-session labels turn the
#                         metrics plane into a memory leak and make
#                         every family aggregate meaningless — the
#                         exact failure Monarch/Prometheus operators
#                         call a cardinality bomb.  Label by BOUNDED
#                         dimensions (tenant, kind, reason, pipeline
#                         name); audited exceptions carry per-line
#                         waivers
#   lint-unbounded-queue  accumulation in message/event-handler
#                         contexts with no visible bound or shed
#                         policy: a bare deque() (no maxlen) built in a
#                         handler, or .append/.appendleft whose
#                         receiver the function never pops, clears,
#                         len()-checks, or deletes from — the unbounded
#                         mailbox is THE classic overload failure
#                         (SEDA): it queues until deadlines blow
#                         instead of shedding at admission.  Sites
#                         whose bound lives elsewhere (a drain method,
#                         a lease) carry per-line waivers so the audit
#                         trail stays in the diff
#   lint-paged-free       block-pool alloc/free imbalance in event or
#                         `graft: hot-path` contexts: a call to
#                         .alloc_blocks()/.alloc_block() whose result
#                         is DISCARDED (a bare expression statement) —
#                         the returned ids are the ONLY handle to the
#                         allocated blocks' refcounts, so dropping
#                         them leaks pool blocks forever (the paged KV
#                         pool's sibling of the unbounded-queue rule:
#                         serving's drain audit asserts zero live
#                         blocks, and a discarded alloc can never be
#                         released).  Capture the ids and release them
#                         at retire, or waive the audited site
#   lint-pallas-fallback  pl.pallas_call without an `interpret=`
#                         keyword: every pallas kernel site in the
#                         package must carry the interpret/compiled
#                         dispatch seam (ops/attention.py and
#                         ops/paged_attention.py both auto-select
#                         interpret off-TPU), so tier-1 exercises the
#                         SAME kernel code path on CPU instead of
#                         silently skipping it — a bare pallas_call is
#                         hardware-only dead weight in CI and a crash
#                         on the CPU fallback path
#   lint-host-transfer    device↔host copies of KV pool-block rows
#                         (jax.device_put / np.asarray / np.array of
#                         block_rows()/k_rows/v_rows/k_pools/v_pools
#                         expressions) inside event-handler or
#                         `graft: hot-path` contexts: a tier crossing
#                         is milliseconds of synchronous copy per
#                         block — on the event loop it stalls every
#                         decode round in the process.  Tier moves go
#                         through the prefetcher seam (the tiered
#                         cache's AsyncPromoter worker stages off-loop
#                         and the loop installs staged arrays), never
#                         inline in a handler; audited exceptions
#                         carry per-line waivers
#   lint-unbounded-cache  dict/OrderedDict CACHES mutated from
#                         event-handler or `graft: hot-path` contexts
#                         with no eviction on the same receiver: a
#                         subscript store (`self._cache[key] = ...`) or
#                         .setdefault() whose receiver the function
#                         never pops/popitems/clears, len()-checks, or
#                         deletes from.  The queue rule's sibling for
#                         keyed state: a keyed cache grows one entry
#                         per DISTINCT key forever (per-request keys =
#                         a memory leak with a hit rate), exactly the
#                         failure the prefix cache's budget eviction
#                         and the reply replay cache's byte caps exist
#                         to prevent.  Per-call locals are exempt;
#                         fixed-key or externally-bounded receivers
#                         (MirroredStats counters, stream-lifetime
#                         state) carry per-line waivers so the audit
#                         trail stays in the diff
#
# Hot-path marking: a `graft: hot-path` comment on (or directly above)
# a `def` line opts that function into the allocation rule — purely
# lexical, like the waivers, so it works on user element files too.
#
# Waivers: a line (or its enclosing statement's first line) containing
# `graft: disable=<rule-id>` (or `graft: disable=all`) suppresses that
# rule there — deliberate exceptions stay visible in the diff.

from __future__ import annotations

import ast
import re
from pathlib import Path

from .findings import ERROR, Finding

__all__ = ["lint_file", "lint_paths", "lint_source", "LINT_RULES"]

LINT_RULES = ("lint-blocking-call", "lint-raw-lock", "lint-assert",
              "lint-publish-locked", "lint-jit-hot", "lint-hot-alloc",
              "lint-print", "lint-unbounded-queue",
              "lint-unbounded-cache", "lint-linear-timer",
              "lint-metric-label", "lint-wall-clock",
              "lint-paged-free", "lint-pallas-fallback",
              "lint-host-transfer")

# block-pool allocator call tails (lint-paged-free): the returned ids
# are the only refcount handle — a discarded result is a leak
_POOL_ALLOC_TAILS = {"alloc_blocks", "alloc_block"}

# device<->host transfer calls applied to KV pool-block rows
# (lint-host-transfer, ISSUE 17): tier crossings are synchronous
# millisecond copies — in a handler they stall every decode round.
# Matched lexically: a transfer-call tail from these modules whose
# first argument's source mentions a pool-row expression.
_TRANSFER_TAILS = {"device_put", "asarray", "array"}
_TRANSFER_MODULES = {"jax", "np", "numpy", "jnp", "jax.numpy"}
_POOL_ROW_TOKENS = ("block_rows", "k_rows", "v_rows", "k_pools",
                    "v_pools")

# wall-epoch clock reads (lint-wall-clock): canonical spellings; call
# targets are CANONICALIZED through the module's actual time/datetime
# import aliases first (_clock_aliases), so `import datetime as dt;
# dt.datetime.now()`, `import time as t; t.time()`, and `from time
# import time; time()` all trip — while an unrelated object attribute
# named .time() does not (no alias resolves it).
_WALL_CLOCK_CALLS = {
    "time.time",
    "datetime.now", "datetime.utcnow", "datetime.today",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "date.today", "datetime.date.today",
}


def _clock_aliases(tree: ast.AST) -> dict:
    """Local names bound to the time/datetime modules (or their
    wall-clock members) by this module's imports: {name: canonical}."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for entry in node.names:
                if entry.name in ("time", "datetime"):
                    aliases[entry.asname or entry.name] = entry.name
        elif isinstance(node, ast.ImportFrom):
            if node.module == "datetime":
                for entry in node.names:
                    if entry.name in ("datetime", "date"):
                        aliases[entry.asname or entry.name] = \
                            f"datetime.{entry.name}"
            elif node.module == "time":
                for entry in node.names:
                    if entry.name == "time":
                        aliases[entry.asname or entry.name] = \
                            "time.time"
    return aliases


def _canonical_clock_target(target: str, aliases: dict) -> str:
    head, sep, rest = target.partition(".")
    canonical = aliases.get(head)
    if canonical is None:
        return target
    return f"{canonical}.{rest}" if sep else canonical

# metric-factory call tails whose labels= dict the label rule inspects
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
# identifier fragments that mark a label VALUE as per-request /
# per-session / per-topic — unbounded by construction.  Purely lexical
# (matched against the value expression's source text), like every
# other rule here.
_UNBOUNDED_LABEL_TOKENS = (
    "topic", "session", "sid", "stream_id", "request_id", "hop_id",
    "hop", "client_id", "trace_id", "span_id", "uuid", "frame_id",
)

# evidence that an accumulation target is bounded or shed within the
# same function: any of these appearing against the SAME receiver text
_BOUND_HINTS = (".pop", ".popleft", ".clear", ".maxlen")

_HOT_MARKER = "graft: hot-path"
# array CONSTRUCTORS (fresh allocation per call).  asarray/array are
# deliberately absent: in a hot loop they are host→device transfers of
# existing buffers, which the round cannot avoid.
_ALLOC_TAILS = {"zeros", "ones", "empty", "full", "zeros_like",
                "ones_like", "full_like", "empty_like", "arange",
                "linspace", "eye"}
_ALLOC_MODULES = {"np", "numpy", "jnp", "jax.numpy"}

_HANDLER_REGISTRARS = {
    "add_timer_handler", "add_oneshot_handler", "add_mailbox_handler",
    "add_queue_handler", "add_flatout_handler",
    # transport-inbound handlers run on the event loop too: a blocking
    # call in a message handler — the peer handshake handlers included
    # (transport/peer.py, ISSUE 6) — stalls every pipeline the same way
    "add_message_handler",
}
_FRAME_METHODS = {"process_frame", "start_stream", "stop_stream"}
_BLOCKING_ATTRS = {
    "result": "concurrent-future .result() blocks until completion",
    "block_until_ready": "device sync blocks the event loop",
    "recv": "blocking socket receive",
    "recvfrom": "blocking socket receive",
    "accept": "blocking socket accept",
    "wait_for_publish": "broker round-trip blocks the event loop",
}


def _is_test_path(path: str) -> bool:
    name = Path(path).name
    parts = Path(path).parts
    return name.startswith("test_") or name == "conftest.py" or \
        "tests" in parts


def _func_tail(node: ast.AST) -> str:
    """Last attribute/name component of a call target ('' when dynamic)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _collect_handlers(tree: ast.AST) -> tuple[set, set]:
    """Names (and lambda node ids) registered as event-engine handlers
    anywhere in the module — including method references like
    self._mailbox_handler."""
    names: set = set()
    lambda_ids: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        if _func_tail(node.func) not in _HANDLER_REGISTRARS:
            continue
        target = node.args[0]
        if isinstance(target, ast.Name):
            names.add(target.id)
        elif isinstance(target, ast.Attribute):
            names.add(target.attr)
        elif isinstance(target, ast.Lambda):
            lambda_ids.add(id(target))
    return names, lambda_ids


def _mentions_lock(node: ast.AST) -> bool:
    return "lock" in ast.unparse(node).lower()


class _ContextScanner(ast.NodeVisitor):
    """Scan one event-loop-context (and/or hot-path) function body for
    blocking calls, jit use, and per-round allocations.  Nested
    function definitions and lambdas are NOT descended into: a nested
    thread target may legitimately block, and nested registered
    handlers get their own scan from the module linter."""

    def __init__(self, lint, context_name, event: bool = True,
                 hot: bool = False):
        self.lint = lint
        self.context = context_name
        self.event = event
        self.hot = hot
        self._source = ""           # the scanned function's own text

    def scan(self, node):
        try:
            self._source = ast.unparse(node)
        except Exception:       # pragma: no cover — unparse is total
            self._source = ""
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    def _receiver_bounded(self, receiver: str) -> bool:
        """True when the enclosing function visibly bounds or sheds the
        accumulation target: pops/clears it, checks len() against it,
        deletes entries — or the target is a LOCAL the function itself
        created (a per-call list dies with the call; the rule is about
        state that outlives the handler).  Purely lexical, like the
        waivers."""
        if "." not in receiver and "[" not in receiver and (
                f"{receiver} = " in self._source
                or f"{receiver}: " in self._source):
            return True
        return any(f"{receiver}{hint}" in self._source
                   for hint in _BOUND_HINTS) \
            or f"len({receiver})" in self._source \
            or f"del {receiver}" in self._source

    def _cache_exempt(self, receiver: str) -> bool:
        """lint-unbounded-cache exemptions beyond _receiver_bounded:
        per-stream scratch space (stream.variables — torn down with
        the stream, the sanctioned keyed-state home for elements) is
        bounded by stream lifetime, not by code in this function."""
        return receiver.endswith("stream.variables") or \
            self._receiver_bounded(receiver)

    def visit_FunctionDef(self, node):      # no descent (see docstring)
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Call(self, node):
        tail = _func_tail(node.func)
        target = ast.unparse(node.func)
        if self.event:
            if target == "time.sleep":
                self.lint.report(
                    "lint-blocking-call", node,
                    f"time.sleep in event-loop context {self.context!r} "
                    f"stalls every pipeline in the process (use a timer "
                    f"handler)")
            elif tail in _BLOCKING_ATTRS:
                self.lint.report(
                    "lint-blocking-call", node,
                    f".{tail}() in event-loop context {self.context!r}: "
                    f"{_BLOCKING_ATTRS[tail]}")
            if target in ("jax.jit", "jit"):
                self.lint.report(
                    "lint-jit-hot", node,
                    f"jax.jit in per-frame context {self.context!r}: "
                    f"build the jitted program once in __init__/_setup "
                    f"(per-frame jit recompiles per shape)")
            if tail in ("append", "appendleft") and \
                    isinstance(node.func, ast.Attribute):
                receiver = ast.unparse(node.func.value)
                if not self._receiver_bounded(receiver):
                    self.lint.report(
                        "lint-unbounded-queue", node,
                        f"{receiver}.{tail}() accumulates in event-loop "
                        f"context {self.context!r} with no visible "
                        f"bound or shed policy in this function: cap "
                        f"it (maxlen / len() check / shed-oldest) or "
                        f"waive the audited site with `graft: "
                        f"disable=lint-unbounded-queue`")
        if (self.event or self.hot) and tail == "setdefault" and \
                isinstance(node.func, ast.Attribute) and node.args and \
                not isinstance(node.args[0], ast.Constant):
            receiver = ast.unparse(node.func.value)
            if not self._cache_exempt(receiver):
                self.lint.report(
                    "lint-unbounded-cache", node,
                    f"{receiver}.setdefault() grows a keyed cache in "
                    f"context {self.context!r} with no eviction on the "
                    f"same receiver: pop/popitem/clear or a len() "
                    f"budget check must bound it, or waive the audited "
                    f"site with `graft: disable=lint-unbounded-cache`")
        if (self.event or self.hot) and tail in _TRANSFER_TAILS and \
                node.args and \
                (target.rpartition(".")[0] in _TRANSFER_MODULES
                 or target == "device_put"):
            arg_src = ast.unparse(node.args[0])
            if any(token in arg_src for token in _POOL_ROW_TOKENS):
                self.lint.report(
                    "lint-host-transfer", node,
                    f"{target}() copies KV pool-block rows across the "
                    f"device/host boundary in context {self.context!r}: "
                    f"a tier crossing is a synchronous per-block copy "
                    f"that stalls every decode round — route it "
                    f"through the tiered cache's prefetcher seam "
                    f"(AsyncPromoter stages off-loop, the loop "
                    f"installs staged arrays) or waive the audited "
                    f"site with `graft: disable=lint-host-transfer`")
        if self.hot and tail in _ALLOC_TAILS and \
                target.rpartition(".")[0] in _ALLOC_MODULES:
            self.lint.report(
                "lint-hot-alloc", node,
                f"{target}() allocates a fresh array every pass through "
                f"hot path {self.context!r}: preallocate in "
                f"__init__/_setup and refill in place (per-round host "
                f"allocations are the pump loop's death by a thousand "
                f"cuts)")
        self.generic_visit(node)

    def visit_Expr(self, node):
        # lint-paged-free: a bare-statement pool alloc drops the ONLY
        # handle to the allocated blocks' refcounts — nothing can ever
        # release them, so the pool leaks one block set per pass
        if (self.event or self.hot) and \
                isinstance(node.value, ast.Call) and \
                _func_tail(node.value.func) in _POOL_ALLOC_TAILS and \
                isinstance(node.value.func, ast.Attribute):
            receiver = ast.unparse(node.value.func.value)
            self.lint.report(
                "lint-paged-free", node,
                f"{receiver}.{_func_tail(node.value.func)}() result "
                f"discarded in context {self.context!r}: the returned "
                f"block ids are the only refcount handle — capture "
                f"them and release at retire, or the pool leaks one "
                f"allocation per pass (waive an audited site with "
                f"`graft: disable=lint-paged-free`)")
        self.generic_visit(node)

    def visit_Assign(self, node):
        # a bare deque() STORED beyond the call (attribute/subscript
        # target) in an event context is an unbounded cross-frame
        # queue; a per-call local deque dies with the call, mirroring
        # _receiver_bounded's local exemption for .append
        if self.event and isinstance(node.value, ast.Call) and \
                _func_tail(node.value.func) == "deque" and \
                not any(kw.arg == "maxlen"
                        for kw in node.value.keywords) and \
                any(not isinstance(target, ast.Name)
                    for target in node.targets):
            self.lint.report(
                "lint-unbounded-queue", node,
                f"unbounded deque() stored from event-loop context "
                f"{self.context!r}: give it a maxlen or a shed policy "
                f"— handler-side accumulation without a bound queues "
                f"until deadlines blow instead of shedding at "
                f"admission")
        # a keyed store (`cache[key] = value`) in an event-handler or
        # hot-path context with no eviction on the same receiver: the
        # unbounded-queue rule's sibling for dict/OrderedDict caches —
        # one entry per distinct key forever.  Plain Assign only:
        # AugAssign on a subscript (`stats[k] += 1`) mutates an
        # EXISTING entry, the counter idiom, not insertion growth.
        # Constant keys are exempt (a fixed-field record update cannot
        # grow — `state["latest"] = frame` is a register, not a cache);
        # growth requires a DYNAMIC key.
        if self.event or self.hot:
            for target in node.targets:
                if not isinstance(target, ast.Subscript) or \
                        isinstance(target.slice, ast.Constant):
                    continue
                receiver = ast.unparse(target.value)
                if self._cache_exempt(receiver):
                    continue
                self.lint.report(
                    "lint-unbounded-cache", node,
                    f"{receiver}[...] = stores into a keyed cache in "
                    f"context {self.context!r} with no eviction on "
                    f"the same receiver (pop/popitem/clear/del/len() "
                    f"budget check): a per-key cache grows FOREVER — "
                    f"bound it like the prefix cache's byte budgets, "
                    f"or waive the audited site with `graft: "
                    f"disable=lint-unbounded-cache`")
        self.generic_visit(node)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, source: str):
        self.path = path
        self.lines = source.splitlines()
        self.findings: list = []
        self._seen: set = set()
        self.is_test = _is_test_path(path)
        self.handler_names: set = set()
        self.lambda_ids: set = set()
        self.clock_aliases: dict = {}
        self.lock_depth = 0

    # -- waivers -----------------------------------------------------------
    def _waived(self, rule: str, lineno: int) -> bool:
        for line_number in (lineno, lineno - 1):
            if 1 <= line_number <= len(self.lines):
                text = self.lines[line_number - 1]
                if "graft: disable=" in text and \
                        (rule in text or "disable=all" in text):
                    return True
        return False

    def report(self, rule: str, node: ast.AST, message: str) -> None:
        key = (rule, node.lineno, getattr(node, "col_offset", 0))
        if key in self._seen or self._waived(rule, node.lineno):
            return
        self._seen.add(key)
        self.findings.append(
            Finding(rule, ERROR, self.path, node.lineno, message))

    # -- module-wide rules -------------------------------------------------
    def visit_Call(self, node):
        if isinstance(node.func, ast.Name) and node.func.id == "print" \
                and not self.is_test:
            self.report(
                "lint-print", node,
                "bare print( in package module: route telemetry "
                "through utils.logger / the observe metrics registry "
                "(deliberate console output carries a "
                "`graft: disable=lint-print` waiver)")
        if not self.is_test and _canonical_clock_target(
                ast.unparse(node.func),
                self.clock_aliases) in _WALL_CLOCK_CALLS:
            self.report(
                "lint-wall-clock", node,
                f"{ast.unparse(node.func)}() reads the wall-epoch "
                f"clock in a package module: use the engine clock "
                f"(runtime.event.clock.now()) for event/deadline "
                f"time, time.monotonic()/perf_counter() for "
                f"durations — wall time breaks virtual-clock "
                f"determinism and merged flight timelines (calendar-"
                f"time sites carry a `graft: disable=lint-wall-clock` "
                f"waiver)")
        if ast.unparse(node.func) == "threading.Lock":
            self.report(
                "lint-raw-lock", node,
                "raw threading.Lock: use aiko_services_tpu.utils.Lock "
                "(named holder, misuse errors, AIKO_LOCK_CHECK "
                "lock-order cycle detection)")
        if _func_tail(node.func) == "remove_timer_handler" and node.args:
            arg_tail = _func_tail(node.args[0])
            if arg_tail and arg_tail in self.handler_names:
                self.report(
                    "lint-linear-timer", node,
                    f"remove_timer_handler({arg_tail}) cancels by "
                    f"HANDLER IDENTITY — a linear scan over every "
                    f"outstanding timer (O(n) at session cardinality): "
                    f"keep the handle add_*_handler returned and cancel "
                    f"by it (O(1) on the timer wheel); the sparse "
                    f"periodic heap's internal scan is the one waived "
                    f"exception")
        if _func_tail(node.func) == "pallas_call" and not self.is_test \
                and not any(kw.arg == "interpret"
                            for kw in node.keywords):
            self.report(
                "lint-pallas-fallback", node,
                "pallas_call without an interpret= keyword: every "
                "kernel site must carry the interpret/compiled "
                "dispatch seam (auto-select interpret off-TPU, the "
                "ops/attention.py pattern) so tier-1 runs the same "
                "kernel code path on CPU instead of skipping it")
        if _func_tail(node.func) in _METRIC_FACTORIES and \
                not self.is_test:
            self._check_metric_labels(node)
        if self.lock_depth > 0 and \
                _func_tail(node.func) in ("publish", "route"):
            self.report(
                "lint-publish-locked", node,
                f".{_func_tail(node.func)}() while holding a lock: "
                f"delivery can re-enter or block under the lock — "
                f"buffer under the lock, publish after release")
        self.generic_visit(node)

    # underscores count as separators (unlike \b): "topic_path" and
    # "session_id" must trip on their stems, "inside"/"shop" must not
    _LABEL_TOKEN_RE = re.compile(
        r"(?<![a-z0-9])(" + "|".join(_UNBOUNDED_LABEL_TOKENS)
        + r")(?![a-z0-9])")

    def _check_metric_labels(self, node) -> None:
        """lint-metric-label: inspect the labels= dict (or the third
        positional argument) of a counter/gauge/histogram get-or-create
        call for unbounded label values — dynamic expressions whose
        source text names a per-request identity (topic, session id,
        hop id, ...), or a suspicious label KEY fed a dynamic value."""
        labels_node = None
        for keyword in node.keywords:
            if keyword.arg == "labels":
                labels_node = keyword.value
                break
        if labels_node is None and len(node.args) >= 3:
            labels_node = node.args[2]
        if not isinstance(labels_node, ast.Dict):
            return
        for key_node, value_node in zip(labels_node.keys,
                                        labels_node.values):
            if isinstance(value_node, ast.Constant):
                continue
            value_text = ast.unparse(value_node).lower()
            key_text = "" if key_node is None \
                else ast.unparse(key_node).lower()
            if self._LABEL_TOKEN_RE.search(value_text) or \
                    self._LABEL_TOKEN_RE.search(key_text):
                label = key_text or value_text
                self.report(
                    "lint-metric-label", value_node,
                    f"metric label {label} takes an unbounded value "
                    f"({ast.unparse(value_node)}): every distinct "
                    f"value mints a registry series FOREVER — label by "
                    f"bounded dimensions (tenant, kind, reason, "
                    f"pipeline name) or waive the audited site with "
                    f"`graft: disable=lint-metric-label`")

    def visit_With(self, node):
        locked = any(_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self.lock_depth += 1
        self.generic_visit(node)
        if locked:
            self.lock_depth -= 1

    def visit_Assert(self, node):
        if not self.is_test:
            self.report(
                "lint-assert", node,
                "assert used for validation in non-test code: compiled "
                "away under python -O — raise ValueError/RuntimeError")
        self.generic_visit(node)

    # -- event-loop / hot-path contexts ------------------------------------
    def _hot_marked(self, node) -> bool:
        """`graft: hot-path` on the def line (or the line above —
        decorator or standalone comment) opts the function into the
        allocation rule."""
        for line_number in (node.lineno, node.lineno - 1):
            if 1 <= line_number <= len(self.lines) and \
                    _HOT_MARKER in self.lines[line_number - 1]:
                return True
        return False

    def visit_FunctionDef(self, node):
        event = node.name in _FRAME_METHODS or \
            node.name in self.handler_names
        hot = self._hot_marked(node)
        if event or hot:
            _ContextScanner(self, node.name, event=event,
                            hot=hot).scan(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if id(node) in self.lambda_ids:
            _ContextScanner(self, "<lambda handler>").scan(node)
        self.generic_visit(node)


def lint_source(source: str, path: str = "<string>") -> list:
    """Lint one source text; returns Findings."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding("lint-parse", ERROR, path, exc.lineno or 0,
                        f"syntax error: {exc.msg}")]
    linter = _Linter(path, source)
    linter.handler_names, linter.lambda_ids = _collect_handlers(tree)
    linter.clock_aliases = _clock_aliases(tree)
    linter.visit(tree)
    return linter.findings


def lint_file(pathname) -> list:
    path = Path(pathname)
    try:
        source = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as exc:
        return [Finding("lint-parse", ERROR, str(path), 0, str(exc))]
    return lint_source(source, str(path))


def lint_paths(paths) -> list:
    """Lint files and/or directories (recursive over *.py)."""
    findings: list = []
    for entry in paths:
        path = Path(entry)
        if path.is_dir():
            for file_path in sorted(path.rglob("*.py")):
                if "__pycache__" in file_path.parts:
                    continue
                findings.extend(lint_file(file_path))
        else:
            findings.extend(lint_file(path))
    return findings
