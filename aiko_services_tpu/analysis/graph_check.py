# Pipeline contract checker: prove a PipelineDefinition sound WITHOUT
# instantiating any element.
#
# PipelineGraph.validate (pipeline.py) catches the direct-predecessor
# dataflow errors at construction time; this checker goes deployment-deep:
#
#   graph-parse          definition/graph DSL does not build
#   graph-cycle          the DAG has a cycle
#   graph-unused-element element defined but absent from the graph
#   graph-unreachable    node not reachable from any graph head
#   graph-mapping        edge name-mapping references an undeclared
#                        output (source side) or input (target side)
#   graph-missing-input  an input no upstream output, head swag, or
#                        stream parameter can ever provide
#   graph-dead-output    output of a non-terminal element that nothing
#                        downstream consumes (warning)
#   graph-contract-syntax  a declared contract string does not parse
#   graph-contract       producer/consumer contracts cannot unify on an
#                        edge (dtype/shape/codec mismatch)
#   graph-codec          a wire codec hint on a remote hop is illegal
#                        for the dtype the contract says it carries
#
# Contracts come from the definition (element-level "contracts" dict or
# per-io "contract" entries) or, for local/builtin elements, from a
# class-level `contracts` attribute — resolved by IMPORT only, never by
# construction, so checking a definition has zero runtime side effects.

from __future__ import annotations

from ..pipeline import (PipelineDefinition, PipelineError, PipelineGraph,
                        load_pipeline_definition, lookup_contract)
from ..transport import wire
from ..utils.graph import GraphError
from .contracts import ContractError, compatible, parse_contract
from .findings import ERROR, WARNING, Finding

__all__ = ["check_definition", "check_pipeline_file",
           "check_wire_schemas"]

# dtype-alias inverse map for the wire-schema check: contract alts
# carry canonical numpy names; the wire runtime tables do too
_WIRE_SCHEMA_PATH = "aiko_services_tpu/transport/wire.py"


def check_wire_schemas(schema=None, dtypes=None, ranks=None) -> list:
    """Prove the declared KV-transfer payload schema sound (ISSUE 14):
    every field's contract string parses under the contract grammar,
    and its declared dtypes/rank agree EXACTLY with the runtime
    legality tables encode_kv_transfer/decode_kv_transfer enforce —
    the same "declare dtype/shape" discipline the wire codecs follow
    (WIRE_CODEC_DTYPES/WIRE_CODEC_RANK), applied to the disaggregated
    KV transfer.  A drifted declaration is an ERROR: graft-check's
    self-check is the gate that keeps the wire contract and the wire
    code the same fact."""
    schema = wire.KV_TRANSFER_SCHEMA if schema is None else schema
    dtypes = wire.KV_TRANSFER_DTYPES if dtypes is None else dtypes
    ranks = wire.KV_TRANSFER_RANK if ranks is None else ranks
    findings = []

    def fail(field, message):
        findings.append(Finding(
            rule="wire-kv-schema", severity=ERROR,
            path=_WIRE_SCHEMA_PATH, line=0,
            message=f"KV_TRANSFER field {field!r}: {message}"))

    for field, text in schema.items():
        try:
            alts = parse_contract(text)
        except ContractError as exc:
            fail(field, f"contract {text!r} does not parse: {exc}")
            continue
        declared = []
        for alt in alts:
            if alt.codec:
                fail(field, f"alternative {alt} names a lossy codec; "
                            f"KV rows must cross bit-exact")
            declared.append(alt.dtype)
            rank = ranks.get(field)
            if alt.shape is None or rank is None or \
                    len(alt.shape) != rank:
                fail(field,
                     f"alternative {alt} rank "
                     f"{len(alt.shape) if alt.shape else None} != "
                     f"KV_TRANSFER_RANK {rank}")
        runtime = dtypes.get(field)
        if runtime is None:
            fail(field, "missing from KV_TRANSFER_DTYPES (declared "
                        "but never enforced)")
        elif sorted(set(declared)) != sorted(set(runtime)):
            fail(field, f"schema dtypes {sorted(set(declared))} != "
                        f"runtime table {sorted(set(runtime))}")
    for field in dtypes:
        if field not in schema:
            fail(field, "enforced at runtime but not declared in "
                        "KV_TRANSFER_SCHEMA")
    for field in ranks:
        if field not in schema:
            fail(field, "ranked at runtime but not declared in "
                        "KV_TRANSFER_SCHEMA")
    return findings


def check_pipeline_file(pathname: str, element_classes=None,
                        wire_codecs=None) -> list:
    try:
        definition = load_pipeline_definition(pathname)
    except (PipelineError, OSError, ValueError) as exc:
        return [Finding("graph-parse", ERROR, pathname, 0, str(exc))]
    return check_definition(definition, element_classes=element_classes,
                            wire_codecs=wire_codecs, source=pathname)


def _resolve_class(element_def, element_classes):
    """Find the element's implementation class without constructing it
    (imports only).  None when unresolvable (remote / unknown)."""
    if element_def.is_remote:
        return None
    local = element_def.deploy.get("local", {})
    class_name = local.get("class_name", element_def.name)
    if element_classes and class_name in element_classes:
        return element_classes[class_name]
    if "module" in local:
        try:
            from ..utils import load_class
            return load_class(local["module"], class_name)
        except Exception:
            return None
    try:
        from .. import elements as builtin
        return getattr(builtin, class_name, None)
    except Exception:       # pragma: no cover - import environment
        return None


class _Contracts:
    """Per-element contract lookup: definition first, class attribute
    fallback; parses each string once and reports syntax errors once."""

    def __init__(self, definition, element_classes, report):
        self._definition = definition
        self._element_classes = element_classes
        self._report = report
        self._raw_cache: dict[str, dict] = {}
        self._parsed: dict[tuple, object] = {}

    def _raw(self, element_name: str) -> dict:
        """Class-attribute contracts (the fallback when the definition
        declares none), resolved by import only."""
        if element_name not in self._raw_cache:
            element_def = self._definition.element(element_name)
            cls = _resolve_class(element_def, self._element_classes)
            self._raw_cache[element_name] = \
                dict(getattr(cls, "contracts", None) or {})
        return self._raw_cache[element_name]

    def get(self, element_name: str, direction: str, io_name: str):
        """Parsed alternatives for an element's input ("in") or output
        ("out") name, or None when undeclared/unparseable."""
        text = self.text(element_name, direction, io_name)
        if text is None:
            return None
        key = (element_name, direction, io_name)
        if key not in self._parsed:
            try:
                self._parsed[key] = parse_contract(text)
            except ContractError as exc:
                self._parsed[key] = None
                self._report(
                    "graph-contract-syntax", ERROR,
                    f"element {element_name}: contract for "
                    f"{direction}put {io_name!r}: {exc}")
        return self._parsed[key]

    def text(self, element_name: str, direction: str, io_name: str):
        element_def = self._definition.element(element_name)
        if element_def.contracts:
            return element_def.contract_for(io_name, direction)
        return lookup_contract(self._raw(element_name), io_name,
                               direction)


def check_definition(definition: PipelineDefinition, *,
                     element_classes=None, wire_codecs=None,
                     source: str = "") -> list:
    """Statically validate one PipelineDefinition; returns Findings."""
    findings: list = []
    where = source or f"<pipeline {definition.name}>"

    def report(rule, severity, message):
        findings.append(Finding(rule, severity, where, 0, message))

    try:
        graph = PipelineGraph.from_definition(definition)
    except (PipelineError, GraphError) as exc:
        report("graph-parse", ERROR, str(exc))
        return findings
    try:
        topo = graph.topological_order()
    except GraphError as exc:
        report("graph-cycle", ERROR, str(exc))
        return findings
    preds = graph.predecessor_map()

    # -- elements defined but never placed in the graph -------------------
    graph_names = set(graph.node_names())
    for element_def in definition.elements:
        if element_def.name not in graph_names:
            report("graph-unused-element", WARNING,
                   f"element {element_def.name} is defined but does not "
                   f"appear in the graph")

    # -- reachability from the declared head(s) ---------------------------
    reachable: set = set()
    frontier = [h for h in graph.head_names if h in graph_names]
    while frontier:
        name = frontier.pop()
        if name in reachable:
            continue
        reachable.add(name)
        frontier.extend(graph.successors(name))
    for name in graph_names - reachable:
        report("graph-unreachable", WARNING,
               f"element {name} is not reachable from graph head(s) "
               f"{graph.head_names}")

    # -- edge name-mapping validity ---------------------------------------
    for (tail, head), mapping in sorted(graph.mappings.items()):
        tail_outputs = definition.element(tail).output_names
        head_inputs = definition.element(head).input_names
        for src, dst in mapping.items():
            if src not in tail_outputs:
                report("graph-mapping", ERROR,
                       f"edge {tail}->{head}: mapping source {src!r} is "
                       f"not an output of {tail} (outputs: {tail_outputs})")
            if dst not in head_inputs:
                report("graph-mapping", ERROR,
                       f"edge {tail}->{head}: mapping target {dst!r} is "
                       f"not an input of {head} (inputs: {head_inputs})")

    # -- full swag dataflow ------------------------------------------------
    # The engine's swag is cumulative along the walk: anything a
    # topologically earlier element produced (plus the head frame's swag
    # and stream/pipeline parameters) is available.  An input neither of
    # those can supply WILL fail on the first frame.
    parameter_names = set()
    for key in definition.parameters:
        parameter_names.add(key.split(".", 1)[1] if "." in key else key)
    available = set(parameter_names)
    for node in topo:
        element_def = definition.element(node.name)
        if not preds[node.name]:
            # head node: its declared inputs arrive with the frame swag
            available |= set(element_def.input_names)
        else:
            rename = {}
            for pred in preds[node.name]:
                mapping = graph.mappings.get((pred, node.name), {})
                for src, dst in mapping.items():
                    rename[dst] = src
            for input_name in element_def.input_names:
                if input_name in available or \
                        rename.get(input_name) in available:
                    continue
                report("graph-missing-input", ERROR,
                       f"element {node.name}: input {input_name!r} is not "
                       f"produced by any upstream element, head frame "
                       f"swag, or stream parameter")
        outputs = set(element_def.output_names)
        available |= outputs
        for successor in graph.successors(node.name):
            mapping = graph.mappings.get((node.name, successor), {})
            for src, dst in mapping.items():
                if src in outputs:
                    available.add(dst)

    # -- dead outputs ------------------------------------------------------
    consumed: set = set()
    for node in topo:
        element_def = definition.element(node.name)
        rename = {}
        for pred in preds[node.name]:
            mapping = graph.mappings.get((pred, node.name), {})
            for src, dst in mapping.items():
                rename[dst] = src
        for input_name in element_def.input_names:
            consumed.add(input_name)
            consumed.add(rename.get(input_name, input_name))
    for node in topo:
        if not graph.successors(node.name):
            continue            # terminal outputs are the pipeline product
        element_def = definition.element(node.name)
        for output_name in element_def.output_names:
            aliases = {output_name}
            for successor in graph.successors(node.name):
                mapping = graph.mappings.get((node.name, successor), {})
                if output_name in mapping:
                    aliases.add(mapping[output_name])
            if not aliases & consumed:
                report("graph-dead-output", WARNING,
                       f"element {node.name}: output {output_name!r} is "
                       f"never consumed by any downstream element")

    # -- per-edge dtype/shape/codec contracts ------------------------------
    contracts = _Contracts(definition, element_classes,
                           lambda rule, sev, msg: report(rule, sev, msg))
    for node in topo:
        tail_def = definition.element(node.name)
        for successor in graph.successors(node.name):
            head_def = definition.element(successor)
            mapping = graph.mappings.get((node.name, successor), {})
            inverse = {dst: src for src, dst in mapping.items()}
            for input_name in head_def.input_names:
                src = inverse.get(input_name)
                if src is None and input_name in tail_def.output_names:
                    src = input_name
                if src is None:
                    continue        # fed by another ancestor, not this edge
                produced = contracts.get(node.name, "out", src)
                accepted = contracts.get(successor, "in", input_name)
                if not produced or not accepted:
                    continue
                if not compatible(produced, accepted):
                    report("graph-contract", ERROR,
                           f"edge {node.name}->{successor}: output "
                           f"{src!r} "
                           f"({contracts.text(node.name, 'out', src)}) "
                           f"cannot satisfy input {input_name!r} "
                           f"({contracts.text(successor, 'in', input_name)})")

    # -- wire codec legality on remote hops --------------------------------
    hints = dict(definition.parameters.get("wire_codecs") or {})
    hints.update(wire_codecs or {})
    if hints:
        _check_codecs(definition, graph, preds, contracts, hints, report)
    return findings


def _check_codecs(definition, graph, preds, contracts, hints, report):
    """Frames crossing a remote hop carry the remote element's inputs out
    and its outputs back; any of those keys with a wire codec hint must
    tag a dtype the codec can legally carry (wire.WIRE_CODEC_DTYPES)."""
    matched: set = set()
    for element_def in definition.elements:
        if not element_def.is_remote or element_def.name not in graph:
            continue
        carried = [("in", name) for name in element_def.input_names] + \
                  [("out", name) for name in element_def.output_names]
        for direction, key in carried:
            codec = hints.get(key)
            if codec is None:
                continue
            matched.add(key)
            if codec not in wire.WIRE_CODECS:
                report("graph-codec", ERROR,
                       f"remote element {element_def.name}: unknown wire "
                       f"codec {codec!r} for key {key!r} "
                       f"(known: {sorted(wire.WIRE_CODECS)})")
                continue
            alts = contracts.get(element_def.name, direction, key)
            if alts is None and direction == "in":
                # fall back to whatever the producers say they emit
                for pred in preds.get(element_def.name, []):
                    mapping = graph.mappings.get(
                        (pred, element_def.name), {})
                    inverse = {dst: src for src, dst in mapping.items()}
                    src = inverse.get(key, key)
                    alts = contracts.get(pred, "out", src)
                    if alts is not None:
                        break
            if not alts:
                continue            # no declared dtype: nothing to prove
            legal = [alt for alt in alts
                     if alt.dtype == "any" or wire.codec_legal(
                         codec, alt.dtype,
                         None if alt.shape is None else len(alt.shape))]
            if not legal:
                report("graph-codec", ERROR,
                       f"remote element {element_def.name}: wire codec "
                       f"{codec!r} cannot legally carry {key!r} "
                       f"(contract: "
                       f"{' | '.join(str(a) for a in alts)}; legal "
                       f"dtypes: "
                       f"{wire.WIRE_CODEC_DTYPES.get(codec)})")
    for key in sorted(set(hints) - matched):
        # a typo'd key silently disables compression at runtime (the
        # encoder never sees it) — exactly the misconfiguration class
        # this checker exists to catch
        report("graph-codec-unused", WARNING,
               f"wire codec hint for key {key!r} matches no input or "
               f"output of any remote element — typo, or the hop is "
               f"not remote?")
