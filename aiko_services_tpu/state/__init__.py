# Session-cardinality state plane (ISSUE 10).
#
# The reference framework's eventual-consistency state and lease/timer
# machinery are sized for tens of services; the ROADMAP north star is
# millions of user sessions.  This package holds the pieces that make
# state O(1)-per-operation at 1e5-1e6 cardinality:
#
#   fsm.py      — the declarative StateMachine (moved from the old
#                 top-level state.py; re-exported here so
#                 `from .state import StateMachine` keeps working)
#   wheel.py    — hierarchical hashed timer wheel (Varghese & Lauck,
#                 SOSP '87): O(1) schedule/cancel/advance.  event.py
#                 backs every oneshot/lease timer with one; the heap
#                 remains only for sparse periodic handlers.
#   sessions.py — SessionTable: (tenant, session_id)-keyed sessions,
#                 hash-sharded across per-shard ECProducer topics,
#                 wheel-backed lease expiry with batch callbacks, and
#                 per-tenant byte budgets with demote-to-dedup-only
#                 shedding.
#   loadgen.py  — the open-loop session load generator (seeded Poisson
#                 arrivals, tenant mix, create/touch/expire lifecycle)
#                 that proves the table flat across 1k → 100k rungs.

from .fsm import StateMachine, StateMachineError            # noqa: F401
from .wheel import TimerWheel                               # noqa: F401

__all__ = [
    "StateMachine", "StateMachineError", "TimerWheel",
    "SessionTable", "SessionView", "TenantBudget", "session_shard",
]

_SESSION_NAMES = ("SessionTable", "SessionView", "TenantBudget",
                  "session_shard")


def __getattr__(name):
    # sessions.py pulls in the share layer; event.py imports THIS
    # package for the wheel — loading sessions lazily keeps that import
    # edge acyclic (event → state.wheel only, never state → share →
    # ... → event at import time)
    if name in _SESSION_NAMES:
        from . import sessions
        return getattr(sessions, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
