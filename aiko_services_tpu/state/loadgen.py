# Open-loop session load generator (ISSUE 10 tentpole c).
#
# Drives a SessionTable through a REAL runtime — engine, broker, EC
# shard topics, a consumer-side SessionView — with seeded Poisson
# arrivals and a configurable tenant mix, while the observe layer
# records what happened: sessions/s, lease churn, shard delta bytes,
# and the event engine's own handler-latency histogram.
#
# Open-loop means arrivals do NOT wait for the system: the generator
# schedules create/touch/expire lifecycles off virtual time at the
# configured rate, exactly like real users who neither know nor care
# how loaded the table is (closed-loop generators hide knees by
# slowing down with the system — the classic coordinated-omission
# trap).
#
# The proof obligation (ROADMAP item 5): p95 handler latency stays
# FLAT as cardinality steps 1k → 10k → 100k.  Every per-op path is
# O(1) — wheel schedule/cancel, flat-view EC update, hash-shard
# lookup — so the p95 must not grow with the number of live sessions;
# an O(n) regression anywhere in the lifecycle shows up as a knee
# between rungs.  Leak gate: after drain, zero sessions and zero
# outstanding timers anywhere (table wheel AND engine).
#
# Everything runs on a VirtualClock: a 100k-session steady state over
# minutes of virtual time replays deterministically in seconds of wall
# time, while handler latency is still measured in REAL wall time
# (time.perf_counter in event._guard) — virtual time compresses the
# waiting, not the work.

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field

from ..event import EventEngine, VirtualClock
from ..observe.export import series_quantile
from ..observe.metrics import default_registry
from ..process import ProcessRuntime
from ..service import Service
from ..transport.memory import MemoryBroker, MemoryMessage
from .sessions import SessionTable, SessionView, TenantBudget

__all__ = ["TenantSpec", "LoadConfig", "run_session_load"]


@dataclass(frozen=True)
class TenantSpec:
    """One tenant in the arrival mix.  `flood=True` marks the tenant
    whose budget is sized to be breached — the budget-enforcement
    probe."""
    name: str
    weight: float = 1.0
    flood: bool = False


# polite/bulk carry the traffic; flood is over-weighted relative to the
# budget it will be given, so shed/demote verdicts MUST appear there
DEFAULT_TENANTS = (
    TenantSpec("polite", weight=3.0),
    TenantSpec("bulk", weight=5.0),
    TenantSpec("flood", weight=2.0, flood=True),
)


@dataclass
class LoadConfig:
    seed: int = 11
    rungs: tuple = (1_000, 10_000, 100_000)
    lease_time: float = 20.0        # virtual seconds
    touches: int = 2                # lease extensions per session life
    num_shards: int = 8
    tick: float = 0.05              # driver tick (virtual seconds)
    payload_bytes: int = 64
    tenants: tuple = DEFAULT_TENANTS
    view_tenant: str = "polite"     # the consumer-side subscription
    snapshot_interval: float = 0.0  # per-shard compaction cadence
    # flatness policy: p95 may move at most two log2 histogram buckets
    # between the smallest and largest rung
    max_p95_ratio: float = 4.0


class _HandlerLatencyProbe:
    """Delta view over the process-wide event_handler_seconds
    histogram: rung-local p95/mean regardless of what ran before."""

    def __init__(self):
        registry = default_registry()
        self._hist = registry.histogram(
            "event_handler_seconds",
            "wall time per event-engine handler invocation")
        self._counts = list(self._hist.counts)
        self._sum = self._hist.sum
        self._count = self._hist.count

    def delta(self) -> dict:
        counts = [a - b for a, b in zip(self._hist.counts, self._counts)]
        count = self._hist.count - self._count
        total = self._hist.sum - self._sum
        p95 = series_quantile({"count": count, "counts": counts,
                               "bounds": list(self._hist.bounds)}, 0.95)
        return {
            "count": count,
            "p95_ms": round(p95 * 1000.0, 4),
            "mean_us": round(total / count * 1e6, 2) if count else 0.0,
        }


@dataclass
class _Lifecycle:
    """Bookkeeping for one rung's in-flight session lifecycles."""
    counter: int = 0
    touches_scheduled: int = 0
    peak_sessions: int = 0
    create_failures: dict = field(default_factory=dict)


def _run_rung(config: LoadConfig, target: int, rng: random.Random) -> dict:
    """One cardinality rung on a FRESH engine/broker/runtimes: ramp to
    ~`target` concurrent sessions, hold, then drain to zero."""
    engine = EventEngine(VirtualClock())
    broker = MemoryBroker()

    def make_runtime(name):
        def transport_factory(on_message, lwt_topic, lwt_payload,
                              lwt_retain):
            return MemoryMessage(
                on_message=on_message, broker=broker,
                lwt_topic=lwt_topic, lwt_payload=lwt_payload,
                lwt_retain=lwt_retain)
        return ProcessRuntime(name=name, engine=engine,
                              transport_factory=transport_factory)

    table_runtime = make_runtime("state_plane").initialize()
    view_runtime = make_runtime("state_view").initialize()
    service = Service(table_runtime, "session_table")

    lease = config.lease_time
    touch_spacing = 0.6 * lease
    lifetime = lease + config.touches * touch_spacing
    # rate targets `target` CONCURRENT sessions at steady state
    # (Little's law: N = λ·lifetime), compensated for the flood
    # tenant's arrivals being mostly shed at its budget
    total_weight = sum(t.weight for t in config.tenants)
    admitted_fraction = sum(t.weight for t in config.tenants
                            if not t.flood) / total_weight
    rate = 1.05 * target / lifetime / max(admitted_fraction, 0.1)

    # the flood tenant's budget is sized to be breached at EVERY rung:
    # its fair share of arrivals far exceeds both caps, so shed (count)
    # and demote (bytes) verdicts must both fire
    flood_names = [t.name for t in config.tenants if t.flood]
    budgets = {name: TenantBudget(
        max_sessions=max(16, target // 50),
        max_bytes=max(16, target // 50) * config.payload_bytes // 2)
        for name in flood_names}

    expired_batches = []
    table = SessionTable(
        service, num_shards=config.num_shards, lease_time=lease,
        wheel_tick=config.tick, budgets=budgets,
        snapshot_interval=config.snapshot_interval,
        on_expired=lambda keys: expired_batches.append(len(keys)))
    view = SessionView(view_runtime, service.topic_path,
                       config.num_shards, tenants=config.view_tenant)
    view_deltas = [0]
    view.add_handler(lambda *_: view_deltas.__setitem__(
        0, view_deltas[0] + 1))

    names = [t.name for t in config.tenants]
    weights = [t.weight for t in config.tenants]
    payload = "x" * config.payload_bytes
    state = _Lifecycle()

    def arrive():
        state.counter += 1
        tenant = rng.choices(names, weights)[0]
        sid = f"s{state.counter}"
        if not table.create(tenant, sid, payload):
            bucket = state.create_failures
            bucket[tenant] = bucket.get(tenant, 0) + 1
            return
        if state.counter % 4 == 0:
            # every 4th session mutates its payload mid-life: the
            # update leg of the lifecycle (delta publish + budget
            # re-check) rides the same wheel-driven schedule
            engine.add_oneshot_handler(
                (lambda t=tenant, s=sid:
                 table.update(t, s, payload + "u")),
                0.3 * touch_spacing)
        for k in range(1, config.touches + 1):
            engine.add_oneshot_handler(
                (lambda t=tenant, s=sid: table.touch(t, s)),
                k * touch_spacing)
            state.touches_scheduled += 1

    def drive(duration: float, arrivals: bool) -> None:
        clock = engine.clock
        end = clock.now() + duration
        next_arrival = clock.now() + (rng.expovariate(rate)
                                      if arrivals else float("inf"))
        while clock.now() < end:
            if arrivals:
                now = clock.now()
                while next_arrival <= now:
                    arrive()
                    next_arrival += rng.expovariate(rate)
            while engine.step():
                pass
            state.peak_sessions = max(state.peak_sessions, len(table))
            clock.advance(config.tick)

    probe = _HandlerLatencyProbe()
    stats_before = dict(table.stats)
    delta_before = table.delta_bytes()
    wall_start = time.perf_counter()

    drive(lifetime, arrivals=True)           # ramp to steady state
    steady_sessions = len(table)
    measure_virtual = lease
    drive(measure_virtual, arrivals=True)    # hold at steady state
    measured = probe.delta()
    # drain: stop arrivals, let every outstanding lease lapse (final
    # touches land within `lifetime`, plus one lease after the last)
    drive(lifetime + lease + 1.0, arrivals=False)
    wall_s = time.perf_counter() - wall_start

    stats = {k: table.stats.get(k, 0) - stats_before.get(k, 0)
             for k in ("created", "touched", "updated", "expired",
                       "shed", "demoted")}
    churn = stats["touched"] + stats["expired"]
    leaked_sessions = len(table)
    leaked_table_timers = table.outstanding_timers()
    view.terminate()
    table.stop()
    while engine.step():                    # deliver teardown messages
        pass
    leaked_engine_timers = len(engine.live_timer_handlers())
    table_runtime.terminate()
    view_runtime.terminate()

    per_tenant = {name: {"shed": state.create_failures.get(name, 0)}
                  for name in names}

    ops = stats["created"] + stats["touched"] + stats["expired"] \
        + stats["updated"]
    return {
        "target": target,
        "steady_sessions": steady_sessions,
        "peak_sessions": state.peak_sessions,
        "wall_s": round(wall_s, 3),
        "ops": ops,
        "ops_per_wall_s": round(ops / wall_s, 1) if wall_s else 0.0,
        "sessions_per_wall_s": round(stats["created"] / wall_s, 1)
        if wall_s else 0.0,
        "lease_churn_per_virtual_s": round(
            churn / (lifetime + measure_virtual), 1),
        "delta_bytes": table.delta_bytes() - delta_before,
        "handler_p95_ms": measured["p95_ms"],
        "handler_mean_us": measured["mean_us"],
        "handler_count": measured["count"],
        "expiry_batches": len(expired_batches),
        "max_expiry_batch": max(expired_batches, default=0),
        "view_deltas": view_deltas[0],
        "stats": stats,
        "per_tenant": per_tenant,
        "leaked_sessions": leaked_sessions,
        "leaked_timers": leaked_table_timers + leaked_engine_timers,
    }


def run_session_load(config: LoadConfig | None = None) -> dict:
    """Run every rung; returns the full report with pass/fail verdicts:
    `flat` (no O(n) knee in handler p95 across rungs), `budgets`
    (flooding tenant shed AND demoted, polite tenants untouched),
    `drain` (zero leaked sessions/timers everywhere), and the overall
    `ok`."""
    config = config or LoadConfig()
    rng = random.Random(config.seed)
    rungs = [_run_rung(config, target, rng)
             for target in sorted(config.rungs)]

    first, last = rungs[0], rungs[-1]
    # flatness on the p95 (log2-bucketed: a ratio of 4 = two buckets);
    # guard the degenerate all-sub-bucket case with the mean
    p95_ratio = (last["handler_p95_ms"] / first["handler_p95_ms"]) \
        if first["handler_p95_ms"] else 1.0
    flat_ok = p95_ratio <= config.max_p95_ratio
    flood_names = {t.name for t in config.tenants if t.flood}
    flood_shed = sum(r["stats"]["shed"] for r in rungs)
    flood_demoted = sum(r["stats"]["demoted"] for r in rungs)
    polite_shed = sum(
        r["per_tenant"][name]["shed"]
        for r in rungs for name in r["per_tenant"]
        if name not in flood_names)
    budgets_ok = flood_shed > 0 and flood_demoted > 0 \
        and polite_shed == 0
    leaked_sessions = sum(r["leaked_sessions"] for r in rungs)
    leaked_timers = sum(r["leaked_timers"] for r in rungs)
    drain_ok = leaked_sessions == 0 and leaked_timers == 0
    sustained = last["steady_sessions"]
    report = {
        "seed": config.seed,
        "lease_time": config.lease_time,
        "touches": config.touches,
        "num_shards": config.num_shards,
        "rungs": rungs,
        "sustained_sessions": sustained,
        "flat": {"p95_ratio": round(p95_ratio, 3),
                 "max_p95_ratio": config.max_p95_ratio,
                 "ok": flat_ok},
        "budgets": {"flood_shed": flood_shed,
                    "flood_demoted": flood_demoted,
                    "polite_shed": polite_shed,
                    "ok": budgets_ok},
        "drain": {"leaked_sessions": leaked_sessions,
                  "leaked_timers": leaked_timers,
                  "ok": drain_ok},
    }
    report["ok"] = flat_ok and budgets_ok and drain_ok
    return report
