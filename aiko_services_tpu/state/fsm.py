# Finite state machine with declarative transitions.
#
# Capability parity with the reference StateMachine (aiko_services/state.py:
# 16-61, a wrapper over the external `transitions` package): named states,
# trigger-driven transitions with on_enter callbacks on a delegate object,
# fail-fast on illegal transitions.  Implemented from scratch — no external
# dependency.

from __future__ import annotations

__all__ = ["StateMachine", "StateMachineError"]


class StateMachineError(RuntimeError):
    pass


class StateMachine:
    """transitions: list of {"trigger", "source" (str|list|"*"), "dest"};
    on entering state S, delegate.on_enter_S(...) is called if defined."""

    def __init__(self, delegate, states: list[str],
                 transitions: list[dict], initial: str,
                 fail_fast: bool = True):
        self.delegate = delegate
        self.states = list(states)
        self.fail_fast = fail_fast
        self._state = initial
        self._transitions: dict[tuple[str, str], str] = {}
        for t in transitions:
            sources = t["source"]
            if sources == "*":
                sources = self.states
            elif isinstance(sources, str):
                sources = [sources]
            for source in sources:
                self._transitions[(t["trigger"], source)] = t["dest"]

    @property
    def state(self) -> str:
        return self._state

    def transition(self, trigger: str, *args, **kwargs) -> None:
        dest = self._transitions.get((trigger, self._state))
        if dest is None:
            message = (f"illegal transition: trigger {trigger!r} "
                       f"from state {self._state!r}")
            if self.fail_fast:
                raise StateMachineError(message)
            return
        self._state = dest
        handler = getattr(self.delegate, f"on_enter_{dest}", None)
        if handler:
            handler(*args, **kwargs)
