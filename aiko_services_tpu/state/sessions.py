# SessionTable: the million-session state plane (ISSUE 10, ROADMAP
# item 5).
#
# The share layer's ECProducer is a fine state primitive for tens of
# items; a session table holds 1e5-1e6.  What breaks at that
# cardinality, and what this module does about it:
#
#   * one producer topic → every consumer sees every delta.  Here the
#     table is HASH-SHARDED: each shard is its own ECProducer on
#     {table}/state/{i}, so delta fan-out, snapshot replay, and
#     consumer lease churn split across shards (Dynamo-style hash
#     partitioning of the keyspace).  Consumers subscribe shards with
#     a tenant filter — a dashboard watching one tenant receives that
#     tenant's deltas only.
#   * a heap timer per session lease → O(log n) churn and tombstone
#     decay.  Session expiry rides a private TimerWheel advanced by ONE
#     periodic engine timer; expiries surface as BATCH callbacks
#     (on_expired(keys)), so 10k leases lapsing in one tick cost one
#     handler dispatch plus O(10k) work, not 10k timer dispatches.
#   * an unbounded table → one flooding tenant evicts everyone.  Every
#     tenant has a session-count and byte budget (TenantBudget).  Over
#     the count budget, NEW sessions are shed at creation (admission
#     semantics: newest work is refused, established sessions live).
#     Over the byte budget, the tenant's OLDEST-TOUCHED sessions are
#     DEMOTED to dedup-only — payload dropped, key retained — the same
#     demote-not-forget semantics as the serving reply replay cache
#     (pipeline._cache_served_reply): the session is still recognized
#     (touch/update revive it), it just pins no bytes.
#
# Key space: (tenant, session_id) maps to the EC item "tenant.sid", so
# the share layer's existing top-level filter grammar selects tenants
# and ECConsumer caches stay flat.  Tenant and session ids must not
# contain "." or "/" (enforced at create).

from __future__ import annotations

import zlib
from dataclasses import dataclass

from ..observe.metrics import MirroredStats, default_registry
from ..share import EC_LEASE_TIME, ECConsumer, ECProducer
from .wheel import TimerWheel

__all__ = ["SessionTable", "SessionView", "TenantBudget",
           "session_shard", "DEMOTED"]

# EC value of a demoted session: existence without payload
DEMOTED = "(demoted)"

_BAD_KEY_CHARS = (".", "/", " ")


def session_shard(tenant: str, session_id: str, num_shards: int) -> int:
    """Stable shard index for a session key (crc32, not hash(): the
    mapping must not depend on the process's hash seed — operators
    correlate shard topics across runs)."""
    key = f"{tenant}\x00{session_id}"
    return zlib.crc32(key.encode("utf-8")) % num_shards


def _value_nbytes(value) -> int:
    """Approximate retained weight of a session payload — the budget
    currency.  Deliberately cheap and deterministic; containers
    recurse, scalars charge their storage order of magnitude."""
    if value is None:
        return 0
    if isinstance(value, (bytes, bytearray, str)):
        return len(value)
    if isinstance(value, bool):
        return 1
    if isinstance(value, (int, float)):
        return 8
    if isinstance(value, dict):
        return sum(len(str(k)) + _value_nbytes(v)
                   for k, v in value.items())
    if isinstance(value, (list, tuple)):
        return sum(_value_nbytes(v) for v in value)
    return len(str(value))


@dataclass(frozen=True)
class TenantBudget:
    """Per-tenant admission limits.  None = unlimited."""
    max_sessions: int | None = None
    max_bytes: int | None = None


class _Session:
    __slots__ = ("tenant", "sid", "payload", "nbytes", "due", "gen",
                 "demoted", "touched")

    def __init__(self, tenant, sid, payload, nbytes, due, touched):
        self.tenant = tenant
        self.sid = sid
        self.payload = payload
        self.nbytes = nbytes
        self.due = due
        self.gen = 0            # bumped per touch: stale wheel entries
        self.demoted = False
        self.touched = touched  # last activity: the idle-demote clock

    @property
    def key(self):
        return (self.tenant, self.sid)


class _ShardEndpoint:
    """Service-shaped shim carrying one shard's EC topics — an
    ECProducer needs only runtime/topic_control/topic_out, and a full
    Service per shard would put N discovery records on the registrar
    for what is one logical table."""
    __slots__ = ("runtime", "topic_path", "topic_control", "topic_out")

    def __init__(self, runtime, base_path: str, index: int):
        self.runtime = runtime
        self.topic_path = f"{base_path}/state/{index}"
        self.topic_control = f"{self.topic_path}/control"
        self.topic_out = f"{self.topic_path}/out"


class _Shard:
    """One hash partition: its ECProducer plus delta accounting."""
    __slots__ = ("endpoint", "producer", "delta_bytes", "dirty",
                 "_counter")

    def __init__(self, runtime, base_path: str, index: int, counter):
        self.endpoint = _ShardEndpoint(runtime, base_path, index)
        self.producer = ECProducer(self.endpoint, {})
        self.delta_bytes = 0
        self.dirty = False
        self._counter = counter     # shared state_delta_bytes_total

    def publish(self, name: str, value) -> None:
        nbytes = len(name) + _value_nbytes(value)
        self.delta_bytes += nbytes
        self._counter.inc(nbytes)
        self.dirty = True
        self.producer.update(name, value)

    def retract(self, name: str) -> None:
        self.delta_bytes += len(name)
        self._counter.inc(len(name))
        self.dirty = True
        self.producer.remove(name)


class SessionTable:
    """(tenant, session_id)-keyed leased state, sharded over per-shard
    ECProducer topics, expired off a timer wheel in batches, budgeted
    per tenant.

    Single-threaded by design: call it from the owning engine's thread
    (element handlers, timers, or a driver loop between step()s) —
    exactly the discipline every other runtime surface already has.
    """

    def __init__(self, service, num_shards: int = 8,
                 lease_time: float = 30.0, wheel_tick: float = 0.05,
                 snapshot_interval: float = 0.0,
                 default_budget: TenantBudget | None = None,
                 budgets: dict[str, TenantBudget] | None = None,
                 on_expired=None, on_demoted=None,
                 demote_idle: float | None = None):
        """`service` supplies the runtime and the topic root (a Service
        or anything with .runtime/.topic_path).  `on_expired(keys)` is
        the expiry-batch callback: one call per wheel advance that
        lapsed anything, with every lapsed (tenant, sid).
        `on_demoted(keys)` fires when the byte budget demotes sessions
        to dedup-only — both hooks release whatever the payload pinned
        OUTSIDE the table (the serving prefix cache's conversation KV
        handles ride them, ISSUE 13 / PR 10 residue (c)).
        `snapshot_interval` > 0 re-synchronizes dirty shards' live
        consumers periodically (compacted snapshot: current state, not
        the delta history); 0 leaves recovery to lease re-requests.
        `demote_idle` > 0 demotes sessions untouched for that many
        seconds BEFORE their lease lapses (ISSUE 17): the session
        survives for dedup/revival but its payload — and whatever it
        pinned outside the table, when on_demoted routes into a tiered
        KV cache — drops to the cold tier early instead of hogging the
        hot tier for a whole lease."""
        self.runtime = service.runtime
        self.topic_path = service.topic_path
        self.num_shards = int(num_shards)
        self.lease_time = float(lease_time)
        self.default_budget = default_budget or TenantBudget()
        self.budgets = dict(budgets or {})
        self.on_expired = on_expired
        self.on_demoted = on_demoted
        self.demote_idle = float(demote_idle) \
            if demote_idle and float(demote_idle) > 0 else None
        self._sessions: dict[tuple, _Session] = {}
        # per-tenant insertion-ordered sid → session (touch re-inserts,
        # so iteration order IS oldest-touched-first: the demote scan
        # pops from the front without sorting)
        self._by_tenant: dict[str, dict] = {}
        self._tenant_bytes: dict[str, int] = {}
        registry = default_registry()
        delta_counter = registry.counter(
            "state_delta_bytes_total",
            "approximate bytes of EC deltas published by session shards")
        self._shards = [_Shard(self.runtime, self.topic_path, i,
                               delta_counter)
                        for i in range(self.num_shards)]
        engine = self.runtime.event
        self._wheel = TimerWheel(engine.clock.now(), tick=wheel_tick)
        self._tick_timer = engine.add_timer_handler(
            self._advance, wheel_tick)
        self._snapshot_interval = float(snapshot_interval)
        self._next_snapshot = engine.clock.now() + self._snapshot_interval
        self.stats = MirroredStats(
            metric="state_session_events_total",
            help="session lifecycle events by kind",
            label="event")
        self._gauge_sessions = registry.gauge(
            "state_sessions", "live sessions in the table")
        self._gauge_bytes = registry.gauge(
            "state_session_bytes", "payload bytes pinned by live sessions")
        self._expiry_batches = registry.histogram(
            "state_expiry_batch_size", "sessions lapsed per wheel advance",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096,
                     16384, 65536))
        # KV memory ledger (ISSUE 20): lease pins/demotions count as
        # lifecycle events (the KV bytes they pin are charged by the
        # prefix cache's session handles, not here)
        self._ledger = None
        self._stopped = False

    def attach_ledger(self, ledger) -> None:
        self._ledger = ledger

    # -- introspection -----------------------------------------------------
    def __len__(self) -> int:
        return len(self._sessions)

    def get(self, tenant: str, sid: str, default=None):
        session = self._sessions.get((tenant, sid))
        return default if session is None else session.payload

    def tenant_sessions(self, tenant: str) -> int:
        return len(self._by_tenant.get(tenant, ()))

    def items(self) -> list:
        """Live sessions as [(tenant, sid, payload), ...] — the drain
        migrator's enumeration surface (ISSUE 19): everything a
        retiring runtime must ship before it stops."""
        return [(tenant, sid, session.payload)
                for (tenant, sid), session in self._sessions.items()]

    def tenant_bytes(self, tenant: str) -> int:
        return self._tenant_bytes.get(tenant, 0)

    def shard_of(self, tenant: str, sid: str) -> int:
        return session_shard(tenant, sid, self.num_shards)

    def delta_bytes(self) -> int:
        return sum(shard.delta_bytes for shard in self._shards)

    def outstanding_timers(self) -> int:
        return len(self._wheel)

    def _budget(self, tenant: str) -> TenantBudget:
        return self.budgets.get(tenant, self.default_budget)

    # -- lifecycle API -----------------------------------------------------
    def create(self, tenant: str, sid: str, payload=None,
               lease_time: float | None = None) -> bool:
        """Admit a session.  Returns False (shed) when the tenant is at
        its session-count budget — admission refuses NEW work, it never
        evicts an established session for a newcomer."""
        if any(c in tenant for c in _BAD_KEY_CHARS) \
                or any(c in sid for c in _BAD_KEY_CHARS):
            raise ValueError(f"session key {(tenant, sid)!r} may not "
                             f"contain '.', '/' or spaces")
        key = (tenant, sid)
        existing = self._sessions.get(key)
        if existing is not None:
            self.update(tenant, sid, payload)
            self.touch(tenant, sid, lease_time)
            return True
        budget = self._budget(tenant)
        held = self._by_tenant.get(tenant)
        if budget.max_sessions is not None and held is not None \
                and len(held) >= budget.max_sessions:
            self.stats["shed"] += 1
            return False
        nbytes = _value_nbytes(payload)
        now = self.runtime.event.clock.now()
        session = _Session(tenant, sid, payload, nbytes,
                           now + (lease_time or self.lease_time), now)
        self._sessions[key] = session
        self._by_tenant.setdefault(tenant, {})[sid] = session
        self._tenant_bytes[tenant] = \
            self._tenant_bytes.get(tenant, 0) + nbytes
        self._wheel.schedule(session.due, (key, session.gen))
        self._publish(session)
        self.stats["created"] += 1
        if self._ledger is not None:
            self._ledger.event("lease_pin")
        self._gauge_sessions.inc()
        self._gauge_bytes.inc(nbytes)
        self._enforce_bytes(tenant)
        return True

    def update(self, tenant: str, sid: str, payload) -> bool:
        """Replace a session's payload (revives a demoted session)."""
        session = self._sessions.get((tenant, sid))
        if session is None:
            return False
        nbytes = _value_nbytes(payload)
        delta = nbytes - session.nbytes
        session.payload = payload
        session.nbytes = nbytes
        session.demoted = False
        # a fresh payload is activity: a just-revived session must not
        # re-demote on the next wheel tick
        session.touched = self.runtime.event.clock.now()
        self._tenant_bytes[tenant] = \
            self._tenant_bytes.get(tenant, 0) + delta
        self._gauge_bytes.inc(delta)
        self._publish(session)
        self.stats["updated"] += 1
        self._enforce_bytes(tenant)
        return True

    def touch(self, tenant: str, sid: str,
              lease_time: float | None = None) -> bool:
        """Extend the session's lease.  O(1): a fresh wheel entry is
        scheduled and the old one goes stale (gen check) — no cancel,
        no scan."""
        key = (tenant, sid)
        session = self._sessions.get(key)
        if session is None:
            return False
        now = self.runtime.event.clock.now()
        session.due = now + (lease_time or self.lease_time)
        session.touched = now
        session.gen += 1
        self._wheel.schedule(session.due, (key, session.gen))
        # re-insert → this tenant dict stays oldest-touched-first
        held = self._by_tenant[tenant]
        del held[sid]
        held[sid] = session
        self.stats["touched"] += 1
        return True

    def remove(self, tenant: str, sid: str, reason: str = "removed") -> bool:
        key = (tenant, sid)
        session = self._sessions.pop(key, None)
        if session is None:
            return False
        held = self._by_tenant.get(tenant)
        if held is not None:
            held.pop(sid, None)
            if not held:
                del self._by_tenant[tenant]
        remaining = self._tenant_bytes.get(tenant, 0) - session.nbytes
        if remaining > 0:
            self._tenant_bytes[tenant] = remaining
        else:
            self._tenant_bytes.pop(tenant, None)
        self._shards[self.shard_of(tenant, sid)].retract(
            f"{tenant}.{sid}")
        self.stats[reason] += 1
        self._gauge_sessions.dec()
        self._gauge_bytes.dec(session.nbytes)
        return True

    # -- internals ---------------------------------------------------------
    def _publish(self, session: _Session) -> None:
        value = DEMOTED if session.demoted else session.payload
        if value is None:
            value = ""
        self._shards[self.shard_of(session.tenant, session.sid)].publish(
            f"{session.tenant}.{session.sid}", value)

    def _enforce_bytes(self, tenant: str) -> None:
        """Demote the tenant's oldest-touched sessions to dedup-only
        until the tenant is back under its byte budget."""
        budget = self._budget(tenant)
        if budget.max_bytes is None:
            return
        held = self._by_tenant.get(tenant)
        if not held:
            return
        over = self._tenant_bytes.get(tenant, 0) - budget.max_bytes
        if over <= 0:
            return
        demoted = []
        for session in list(held.values()):
            if over <= 0:
                break
            if session.demoted or session.nbytes == 0:
                continue
            over -= self._demote(session)
            demoted.append(session.key)
        if demoted and self.on_demoted is not None:
            # demotion drops the payload, so whatever it pinned outside
            # the table (conversation KV handles) must release too
            self.on_demoted(demoted)

    def _demote(self, session: _Session) -> int:
        """Drop one session's payload to dedup-only; returns the bytes
        freed inside the table (the on_demoted batch frees the rest)."""
        freed = session.nbytes
        session.payload = None
        session.nbytes = 0
        session.demoted = True
        self._tenant_bytes[session.tenant] -= freed
        self._gauge_bytes.dec(freed)
        self.stats["demoted"] += 1
        if self._ledger is not None:
            self._ledger.event("lease_demote")
        self._publish(session)
        return freed

    def _demote_idle(self, now: float) -> None:
        """Idle-demote sweep (ISSUE 17): one pass per wheel tick over
        each tenant's oldest-touched session(s).  The per-tenant dicts
        iterate oldest-touched-first, so the scan stops at the first
        live session that is not yet idle — cost is O(idle found), not
        O(sessions)."""
        idle_before = now - self.demote_idle
        demoted = []
        for held in list(self._by_tenant.values()):
            for session in list(held.values()):
                if session.touched > idle_before:
                    break           # oldest-first: the rest are newer
                if session.demoted or session.nbytes == 0:
                    continue
                self._demote(session)
                self.stats["demoted_idle"] += 1
                demoted.append(session.key)
        if demoted and self.on_demoted is not None:
            self.on_demoted(demoted)

    def _advance(self) -> None:
        """The ONE engine timer behind every session lease: advance the
        wheel, lapse what's due, deliver the expiry batch."""
        if self._stopped:
            return
        now = self.runtime.event.clock.now()
        lapsed = []
        for entry in self._wheel.advance(now):
            key, gen = entry.payload
            session = self._sessions.get(key)
            if session is None or session.gen != gen:
                continue            # touched since scheduled: stale
            lapsed.append(key)
        for tenant, sid in lapsed:
            self.remove(tenant, sid, reason="expired")
        if lapsed:
            self._expiry_batches.observe(len(lapsed))
            if self.on_expired is not None:
                self.on_expired(lapsed)
        if self.demote_idle is not None:
            self._demote_idle(now)
        if self._snapshot_interval > 0 and now >= self._next_snapshot:
            self._next_snapshot = now + self._snapshot_interval
            self._compact()

    def _compact(self) -> None:
        """Periodic compacted snapshot: every dirty shard replays its
        CURRENT filtered state to its live leaseholders (the delta
        history is never replayed — compaction is implicit in the
        share dict).  Consumers apply add/update idempotently, so a
        consumer that missed deltas heals here without waiting for its
        own lease re-request."""
        for shard in self._shards:
            if not shard.dirty:
                continue
            shard.dirty = False
            producer = shard.producer
            for response_topic, consumer in list(
                    producer._consumers.items()):
                producer._synchronize(response_topic, consumer["filter"])

    def stop(self) -> None:
        """Drain: cancel the tick timer, drop every shard's control
        subscription and consumer leases.  Leak gate: after stop() the
        engine holds NO timer owned by this table."""
        if self._stopped:
            return
        self._stopped = True
        self.runtime.event.remove_timer_handler(self._tick_timer)
        for shard in self._shards:
            shard.producer.terminate()


class SessionView:
    """Consumer-side merged view of a SessionTable: one ECConsumer per
    shard (same filter), all writing one flat cache keyed
    "tenant.sid".  `tenants` narrows the subscription — a per-tenant
    dashboard receives only its tenant's deltas from every shard."""

    def __init__(self, runtime, table_topic_path: str, num_shards: int,
                 tenants="*", lease_time: float = EC_LEASE_TIME):
        self.cache: dict = {}
        self._consumers = [
            ECConsumer(runtime, self.cache,
                       f"{table_topic_path}/state/{i}/control",
                       item_filter=tenants, lease_time=lease_time)
            for i in range(int(num_shards))]

    @property
    def synchronized(self) -> bool:
        return all(c.synchronized for c in self._consumers)

    def __len__(self) -> int:
        return len(self.cache)

    def get(self, tenant: str, sid: str, default=None):
        return self.cache.get(f"{tenant}.{sid}", default)

    def add_handler(self, handler) -> None:
        for consumer in self._consumers:
            consumer.add_handler(handler)

    def share_request_stats(self) -> dict:
        totals = {"share_requests": 0, "share_requests_deduped": 0}
        for consumer in self._consumers:
            for key in totals:
                totals[key] += consumer.stats[key]
        return totals

    def terminate(self) -> None:
        for consumer in self._consumers:
            consumer.terminate()
