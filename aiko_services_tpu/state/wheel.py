# Hierarchical hashed timer wheel (Varghese & Lauck, SOSP '87).
#
# The event engine's original timer store was one heapq: O(log n) per
# schedule, O(n) removal-by-identity, and — the killer at session
# cardinality — every cancelled entry stays in the heap until its due
# time bubbles it to the top.  At 1e5-1e6 outstanding leases (ROADMAP
# item 5: million-session state plane) where almost every timer is
# cancelled/extended before it fires (a touch extends the lease, a
# reply cancels the hop timeout), the heap is mostly tombstones and
# every operation pays for them.
#
# The wheel makes the common case O(1):
#   schedule — hash the due tick into a slot of the coarsest-fitting
#              level (no ordering work at all);
#   cancel   — pop the handle from the entry map (the slot keeps a dead
#              reference that expiry skips: lazy deletion, no scan);
#   advance  — each elapsed tick visits exactly one level-0 slot; when
#              a level wraps, one slot of the next level up cascades
#              back down.  Cost is O(ticks elapsed + entries expired),
#              independent of how many timers are outstanding.
#
# Levels: slot counts are a power of two so slot indexing is a shift +
# mask of the integer tick counter.  With tick=10 ms and 256 slots the
# levels span 2.56 s / ~11 min / ~2 days — lease times land in level 0
# or 1, so a cascade touches an entry at most twice in its life.
#
# Determinism: the wheel has no clock of its own — advance(now) is
# driven by the caller (the event engine's step(), or settle_virtual
# through it), so virtual-clock tests replay bit-identically.
#
# Ordering: entries expire in tick order; within one tick they expire
# in insertion order.  Sub-tick ordering is NOT preserved — the wheel's
# contract is "within tick tolerance", which is what lease semantics
# need (a lease is a coarse timeout, not a sequencer).

from __future__ import annotations

import itertools
from typing import Any

__all__ = ["TimerWheel", "WheelEntry"]

_SLOT_BITS = 8
_SLOTS = 1 << _SLOT_BITS            # 256 slots per level
_LEVELS = 3


class WheelEntry:
    """One scheduled timer.  `payload` is whatever the caller wants to
    get back at expiry (a callback for the event engine, a session key
    for the SessionTable)."""
    __slots__ = ("handle", "due", "tick_due", "payload")

    def __init__(self, handle: int, due: float, tick_due: int,
                 payload: Any):
        self.handle = handle
        self.due = due
        self.tick_due = tick_due
        self.payload = payload

    def __repr__(self):
        return f"WheelEntry({self.handle} due={self.due:.3f})"


class TimerWheel:
    """Hierarchical hashed timer wheel: O(1) schedule/cancel, O(ticks +
    expiries) advance.

    Not thread-safe by itself — the event engine calls it under its own
    lock, and the SessionTable drives its private wheel from one timer
    handler.
    """

    def __init__(self, now: float = 0.0, tick: float = 0.01):
        if tick <= 0:
            raise ValueError("TimerWheel tick must be > 0")
        self.tick = float(tick)
        self._now_tick = self._tick_of(now)
        # level l slot s → list of WheelEntry (may hold cancelled
        # tombstone refs; liveness is `_entries.get(handle) is entry`)
        self._slots = [[[] for _ in range(_SLOTS)] for _ in range(_LEVELS)]
        self._entries: dict[int, WheelEntry] = {}
        self._handles = itertools.count(1)
        self._dirty = False         # any slot may hold (dead) refs
        # entries whose slot has been processed but whose exact due is
        # still ahead of the caller's `now` (sub-tick precision: an
        # entry never fires BEFORE its due), plus entries scheduled
        # into the past (0-delay oneshots fire on the very next
        # advance, clock movement or not — heap parity).  Bounded by
        # one tick's worth of schedules.
        self._pending: list[WheelEntry] = []

    # -- geometry ----------------------------------------------------------
    def _tick_of(self, when: float) -> int:
        """First tick boundary at or after `when` (never fires early)."""
        ticks = when / self.tick
        whole = int(ticks)
        return whole if whole == ticks else whole + 1

    def _place(self, entry: WheelEntry) -> None:
        """Hash the entry into the coarsest-fitting level's slot.  Dues
        beyond the top level's span land in the top level and cascade
        around again when their slot comes up — correct, just touched
        once per top-level revolution."""
        if entry.tick_due < self._now_tick:
            # its slot has already been processed: overdue — fires on
            # the next advance
            self._pending.append(entry)
            return
        delta = entry.tick_due - self._now_tick
        for level in range(_LEVELS):
            if delta < (1 << (_SLOT_BITS * (level + 1))) \
                    or level == _LEVELS - 1:
                slot = (entry.tick_due >> (_SLOT_BITS * level)) \
                    & (_SLOTS - 1)
                self._slots[level][slot].append(entry)
                self._dirty = True
                return

    # -- API ---------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    def schedule(self, due: float, payload: Any,
                 handle: int | None = None) -> int:
        """Schedule `payload` for expiry at absolute time `due` (same
        clock domain as the `now` passed to advance()).  Returns the
        cancel handle; pass `handle` to use an external id space (the
        event engine reuses its timer seq numbers)."""
        if handle is None:
            handle = next(self._handles)
        entry = WheelEntry(handle, due, self._tick_of(due), payload)
        self._entries[handle] = entry
        self._place(entry)
        return handle

    def cancel(self, handle: int) -> bool:
        """O(1): drop the handle from the entry map.  The slot's stale
        reference is skipped (and discarded) when its tick comes up —
        no scan, no tombstone accumulation beyond one revolution."""
        return self._entries.pop(handle, None) is not None

    def entries(self):
        """Live entries (unordered) — diagnostic/compat use only."""
        return list(self._entries.values())

    def next_due(self) -> float | None:
        """Conservative lower bound on the next expiry: the next tick
        boundary while anything is outstanding.  The event engine caps
        its idle sleep at one tick anyway, so a tighter bound would buy
        nothing; an empty wheel reports None so loop() can exit."""
        if not self._entries:
            return None
        return self._now_tick * self.tick

    def advance(self, now: float) -> list[WheelEntry]:
        """Advance wheel time to `now`; returns entries with due <= now
        in tick order (insertion order within a tick).  An entry never
        fires before its exact due; an entry scheduled in the past
        fires on the very next advance, whether or not the clock
        moved.  Expired entries are REMOVED from the wheel — the
        caller owns delivering them."""
        expired: list[WheelEntry] = []
        entries = self._entries
        if self._pending:
            still: list[WheelEntry] = []
            for entry in self._pending:
                if entries.get(entry.handle) is not entry:
                    continue                # cancelled: tombstone
                if entry.due <= now:
                    del entries[entry.handle]
                    expired.append(entry)
                else:
                    still.append(entry)
            self._pending = still
        # process every tick boundary at or below `now` — plus the one
        # just above it, so a sub-tick due (e.g. a 0-delay oneshot
        # scheduled mid-tick) is examined now instead of waiting for
        # the clock to cross the boundary
        target = self._tick_of(now)
        if target < self._now_tick:
            return expired
        if not entries:
            # fast-skip an empty wheel: slots hold only tombstones (if
            # anything), which the jump orphans harmlessly — liveness
            # is the entry map, and it is empty.  Drop the tombstone
            # refs once so the idle path stays allocation-free after.
            if self._dirty:
                self._slots = [[[] for _ in range(_SLOTS)]
                               for _ in range(_LEVELS)]
                self._dirty = False
            self._now_tick = target + 1
            return expired
        level0 = self._slots[0]
        while self._now_tick <= target:
            tick = self._now_tick
            bucket = level0[tick & (_SLOTS - 1)]
            if bucket:
                level0[tick & (_SLOTS - 1)] = []
                for entry in bucket:
                    if entries.get(entry.handle) is not entry:
                        continue            # cancelled: tombstone
                    if entry.tick_due > tick:
                        # future revolution of this slot: put it back
                        self._place(entry)
                    elif entry.due <= now:
                        del entries[entry.handle]
                        expired.append(entry)
                    else:
                        # right tick, due still sub-tick ahead of
                        # `now`: hold for the next advance
                        self._pending.append(entry)
            self._now_tick = tick + 1
            # level wrap: cascade one slot of the next level down.
            # Cascading BEFORE re-placement sees the new _now_tick, so
            # redistributed entries land in level 0 slots still ahead.
            shifted = self._now_tick
            for level in range(1, _LEVELS):
                shifted >>= _SLOT_BITS
                if self._now_tick & ((1 << (_SLOT_BITS * level)) - 1):
                    break
                slot = shifted & (_SLOTS - 1)
                bucket = self._slots[level][slot]
                if bucket:
                    self._slots[level][slot] = []
                    for entry in bucket:
                        if entries.get(entry.handle) is entry:
                            self._place(entry)
        return expired
