# Command-line entry points.
#
# Capability parity with the reference console scripts
# (reference: pyproject.toml:36-40 — aiko, aiko_dashboard, aiko_pipeline,
# aiko_registrar; CLI autogen: aiko_services/cli.py:96-206, pipeline CLI:
# pipeline.py:874-936).
#
#   aiko_tpu registrar                  — run a registrar process
#   aiko_tpu pipeline create DEF.json   — run a pipeline from a definition
#   aiko_tpu pipeline show DEF.json     — validate + print a definition
#   aiko_tpu dashboard                  — curses service dashboard
#   aiko_tpu storage                    — run a storage service
#   aiko_tpu recorder                   — run a log recorder
#
# Transport selection: --transport memory|mqtt (AIKO_TPU_TRANSPORT env);
# mqtt interops with a real broker, memory is single-process.

from __future__ import annotations

import json
import os
import sys

import click

__all__ = ["main"]


def _make_runtime(name, transport):
    from .process import ProcessRuntime

    if transport == "mqtt":
        from .transport.mqtt import MQTT_AVAILABLE, MQTTMessage
        if not MQTT_AVAILABLE:
            raise click.ClickException(
                "mqtt transport requested but paho-mqtt is not installed")

        def factory(on_message, lwt_topic, lwt_payload, lwt_retain):
            from .utils.configuration import \
                get_transport_configuration
            config = get_transport_configuration()
            return MQTTMessage(on_message=on_message, lwt_topic=lwt_topic,
                               lwt_payload=lwt_payload,
                               lwt_retain=lwt_retain,
                               host=config.host, port=config.port,
                               username=config.username,
                               password=config.password, tls=config.tls)
        runtime = ProcessRuntime(name=name, transport_factory=factory)
    else:
        runtime = ProcessRuntime(name=name)
    return runtime.initialize()


transport_option = click.option(
    "--transport", default=lambda: os.environ.get("AIKO_TPU_TRANSPORT",
                                                  "memory"),
    type=click.Choice(["memory", "mqtt"]), help="control-plane transport")


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed service framework."""
    # some accelerator plugins force-set jax_platforms at import,
    # clobbering the env var; honour an explicit JAX_PLATFORMS ask
    # (e.g. =cpu with xla_force_host_platform_device_count for a
    # virtual mesh) the way tests/conftest.py does
    import os
    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        try:
            import jax
            jax.config.update("jax_platforms", requested)
        except Exception:
            pass          # jax optional for pure control-plane commands


@main.command()
@transport_option
def registrar(transport) -> None:
    """Run a registrar (primary election + service discovery)."""
    from .registrar import Registrar

    runtime = _make_runtime("registrar", transport)
    Registrar(runtime)
    click.echo(f"registrar on {runtime.topic_path} ({transport})")
    runtime.run(loop_when_no_handlers=True)


@main.group()
def pipeline() -> None:
    """Pipeline operations."""


def _snake(name: str) -> str:
    """PE_WhisperASR → pe_whisper_asr (the reference CLI's flag
    naming: aiko_services/cli.py:96-206)."""
    import re
    return re.sub(r"(?<=[a-z0-9])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])",
                  "_", name).lower().replace("__", "_")


def parse_mesh_spec(spec: str | None):
    """'model=4,data=2' → a jax Mesh over the visible devices (None
    passes through: single-device ComputeRuntime).  This is the CLI
    seam that makes the parallelism modes user-reachable — the same
    axis names the elements' logical-axis rules shard over (TP
    'model', MoE 'expert', ring attention 'sequence', DP 'data')."""
    if not spec:
        return None
    axes = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise click.ClickException(
                f"--mesh: expected axis=N, got {part!r}")
        axis, _, count = part.partition("=")
        axis = axis.strip()
        if not axis:
            raise click.ClickException(
                f"--mesh: missing axis name in {part!r}")
        if axis in axes:
            raise click.ClickException(
                f"--mesh: duplicate axis {axis!r}")
        try:
            size = int(count)
        except ValueError:
            raise click.ClickException(
                f"--mesh: axis size must be an integer, got {count!r}")
        if size < 1:
            raise click.ClickException(
                f"--mesh: axis size must be >= 1, got {size}")
        axes[axis] = size
    from .parallel import create_mesh
    try:
        import math

        import jax
        # the mesh takes the first product-many devices: an axes
        # product smaller than the machine is a valid ask (e.g.
        # expert=4 on an 8-device host)
        need = math.prod(axes.values())
        return create_mesh(axes, devices=jax.devices()[:need])
    except Exception as exc:
        raise click.ClickException(
            f"--mesh {spec!r}: {exc} (visible devices may be fewer "
            f"than the axes' product; on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=N)")


def parse_element_flags(definition, extra_args) -> dict:
    """Autogenerated per-element parameter flags, reference-style
    (aiko_services/cli.py:96-206 turns every element parameter into a
    `--element-param` option).  Two accepted spellings per parameter:

        --PE_WhisperASR.max_tokens 24     (exact names)
        --pe-whisper-asr-max-tokens 24    (snake/kebab)

    Values parse as JSON when possible, else raw strings.  Unknown
    flags raise with the discoverable flag list (`pipeline params`)."""
    elements = [e.name for e in definition.elements]
    prefixes = {_snake(name).replace("_", "-"): name
                for name in elements}
    overrides = {}
    queue = list(extra_args)
    while queue:
        flag = queue.pop(0)
        if not flag.startswith("--"):
            raise click.ClickException(f"unexpected argument {flag!r}")
        flag = flag[2:]
        if "=" in flag:
            flag, value = flag.split("=", 1)
        elif queue:
            value = queue.pop(0)
        else:
            raise click.ClickException(f"flag --{flag} needs a value")
        key = None
        if "." in flag:
            element, param = flag.split(".", 1)
            if element in elements:
                key = f"{element}.{param}"
        else:
            kebab = flag.replace("_", "-")
            # longest prefix first: PE_Microphone must not capture
            # PE_MicrophoneSim's flags
            for prefix in sorted(prefixes, key=len, reverse=True):
                if kebab.startswith(prefix + "-"):
                    param = kebab[len(prefix) + 1:].replace("-", "_")
                    key = f"{prefixes[prefix]}.{param}"
                    break
        if key is None:
            raise click.ClickException(
                f"--{flag} matches no element of "
                f"{elements}; run `pipeline params` to list flags")
        try:
            overrides[key] = json.loads(value)
        except ValueError:
            overrides[key] = value
    return overrides


@pipeline.command(context_settings=dict(ignore_unknown_options=True))
@click.argument("definition_pathname")
@click.option("--name", default=None, help="pipeline service name")
@click.option("--stream", "stream_id", default="*",
              help="stream id to create")
@click.option("--stream-parameters", default="{}",
              help="JSON dict of stream parameters")
@click.option("--frame", "frame_json", default=None,
              help="JSON swag for one immediate frame")
@click.option("--mesh", "mesh_spec", default=None,
              help="device mesh for the ComputeRuntime, e.g. "
                   "'model=4,data=2' (TP x DP), 'expert=8' (MoE), "
                   "'sequence=8' (ring attention).  Elements shard "
                   "their params over it via their logical axes.")
@transport_option
@click.argument("element_flags", nargs=-1,
                type=click.UNPROCESSED)
def create(definition_pathname, name, stream_id, stream_parameters,
           frame_json, transport, mesh_spec, element_flags) -> None:
    """Run a pipeline from DEFINITION_PATHNAME.

    Every element parameter is additionally a flag:
    `--PE_Element.param VALUE` or `--pe-element-param VALUE`
    (see `pipeline params DEFINITION` for the list)."""
    from .compute import ComputeRuntime
    from .pipeline import Pipeline, load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    parameters = json.loads(stream_parameters)
    parameters |= parse_element_flags(definition, element_flags)
    runtime = _make_runtime(name or definition.name, transport)
    ComputeRuntime(runtime, "compute", mesh=parse_mesh_spec(mesh_spec))
    pipe = Pipeline(runtime, definition, name=name,
                    definition_pathname=definition_pathname)
    pipe.create_stream(stream_id, parameters=parameters)
    if frame_json is not None:
        pipe.post("process_frame", stream_id, json.loads(frame_json))
    click.echo(f"pipeline {pipe.name} on {pipe.topic_path} "
               f"({len(pipe.graph)} elements, {transport})")
    runtime.run(loop_when_no_handlers=True)


@pipeline.command("params")
@click.argument("definition_pathname")
def pipeline_params(definition_pathname) -> None:
    """List every element parameter as its autogenerated flags (the
    reference's discoverable-flags UX, aiko_services/cli.py:96-206)."""
    from .pipeline import load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    declared: dict[str, dict] = {e.name: {} for e in definition.elements}
    for key, value in (definition.parameters or {}).items():
        element, _, param = key.partition(".")
        if param and element in declared:
            declared[element][param] = value
    for element in definition.elements:
        params = declared.get(element.name, {})
        params = {**(element.parameters or {}), **params}
        click.echo(f"{element.name}:")
        if not params:
            click.echo("  (no declared parameters; any --"
                       f"{element.name}.<param> VALUE is accepted)")
        prefix = _snake(element.name).replace("_", "-")
        for param, default in sorted(params.items()):
            click.echo(f"  --{element.name}.{param} / "
                       f"--{prefix}-{param.replace('_', '-')}"
                       f"  [default: {default!r}]")


@pipeline.command()
@click.argument("definition_pathname")
@click.option("--dump", "dump_format", default=None,
              type=click.Choice(["json", "yaml"]),
              help="export the validated definition instead of "
                   "pretty-printing")
@click.option("--output", "output_pathname", default=None,
              help="write the --dump export to a file (default stdout)")
def show(definition_pathname, dump_format, output_pathname) -> None:
    """Validate and print (or --dump) a pipeline definition."""
    from .pipeline import (PipelineGraph, definition_to_dict,
                           load_pipeline_definition)

    if output_pathname and not dump_format:
        raise click.UsageError("--output requires --dump json|yaml")
    definition = load_pipeline_definition(definition_pathname)
    graph = PipelineGraph.from_definition(definition)
    graph.validate(definition)
    if dump_format:
        data = definition_to_dict(definition)
        if dump_format == "yaml":
            try:
                import yaml
            except ImportError as exc:      # pragma: no cover
                raise click.ClickException(
                    "--dump yaml needs pyyaml (pip install pyyaml); "
                    "--dump json has no extra dependency") from exc
            text = yaml.safe_dump(data, sort_keys=False)
        else:
            text = json.dumps(data, indent=2) + "\n"
        if output_pathname:
            with open(output_pathname, "w") as f:
                f.write(text)
            click.echo(f"wrote {output_pathname}")
        else:
            click.echo(text, nl=False)
        return
    click.echo(f"pipeline: {definition.name} (runtime={definition.runtime})")
    for node in graph.topological_order():
        element = definition.element(node.name)
        deploy = "remote" if element.is_remote else "local"
        click.echo(f"  {node.name}: {element.input_names} -> "
                   f"{element.output_names} [{deploy}]"
                   + (f" -> {node.successors}" if node.successors else ""))
    click.echo("valid")


@main.command()
@transport_option
def storage(transport) -> None:
    """Run a storage service (sqlite key/value)."""
    from .storage import Storage

    runtime = _make_runtime("storage", transport)
    database, _ = os.environ.get("AIKO_TPU_STORAGE", "storage.db"), None
    Storage(runtime, database_path=database)
    click.echo(f"storage ({database}) on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def recorder(transport) -> None:
    """Run a log recorder."""
    from .recorder import Recorder

    runtime = _make_runtime("recorder", transport)
    Recorder(runtime)
    click.echo(f"recorder on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def dashboard(transport) -> None:
    """Curses dashboard: live service table + EC share browser."""
    from .dashboard import run_dashboard

    runtime = _make_runtime("dashboard", transport)
    run_dashboard(runtime)


# -- system bring-up (reference: scripts/system_start.sh etc.) ---------------

_DEFAULT_STATE_FILE = "~/.aiko_tpu_system.json"


def _state_path(state_file: str):
    import pathlib
    return pathlib.Path(state_file).expanduser()


def _load_state(state_file: str) -> dict:
    import json
    path = _state_path(state_file)
    if path.exists():
        try:
            return json.loads(path.read_text())
        except (ValueError, OSError):
            return {}
    return {}


def _pid_alive(pid: int) -> bool:
    import os
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _state_entry(value):
    """State-file values are [pid, start_time] (older files: bare
    pid → start_time None)."""
    if isinstance(value, (list, tuple)):
        return int(value[0]), value[1]
    return int(value), None


@main.group()
def system() -> None:
    """Bring a whole control plane up/down (registrar, recorder,
    storage — and mosquitto when the transport is mqtt)."""


@system.command("start")
@transport_option
@click.option("--state-file", default=_DEFAULT_STATE_FILE,
              help="where to record the spawned pids")
@click.option("--services", default="registrar,recorder,storage",
              help="comma-separated aiko_tpu subcommands to spawn")
def system_start(transport, state_file, services) -> None:
    """One-command bring-up (reference: scripts/system_start.sh —
    mosquitto + registrar + dashboard)."""
    import json
    import shutil
    import subprocess
    import sys

    from .utils.configuration import pid_start_time, pid_verified

    def _still_ours(name, value):
        pid, start = _state_entry(value)
        if not _pid_alive(pid):
            return False
        # a recycled pid (different start time) is NOT our process —
        # don't let a stale state file block startup forever; legacy
        # bare-pid entries fall back to the cmdline heuristic, which
        # must also try the service name (mirrors system_stop: a live
        # mosquitto never matches the default "aiko" marker, and
        # missing it here spawns a duplicate broker)
        if start is not None:
            return pid_verified(pid, start_time=start)
        return pid_verified(pid, name) or pid_verified(pid)

    state = {name: value
             for name, value in _load_state(state_file).items()
             if _still_ours(name, value)}
    if state:
        raise click.ClickException(
            f"system already running ({', '.join(state)}); "
            f"run `aiko_tpu system stop` first")

    if transport == "mqtt" and shutil.which("mosquitto"):
        from .utils.configuration import get_transport_configuration
        config = get_transport_configuration()
        if config.host in ("localhost", "127.0.0.1"):
            broker = subprocess.Popen(
                ["mosquitto", "-p", str(config.port)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            state["mosquitto"] = [broker.pid, pid_start_time(broker.pid)]
            click.echo(f"mosquitto: pid {broker.pid} (port {config.port})")

    for name in [s.strip() for s in services.split(",") if s.strip()]:
        child = subprocess.Popen(
            [sys.executable, "-m", "aiko_services_tpu", name,
             "--transport", transport],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        # record (pid, start_time): the exact process identity, so a
        # later `stop` can never signal a recycled pid
        state[name] = [child.pid, pid_start_time(child.pid)]
        click.echo(f"{name}: pid {child.pid}")
    _state_path(state_file).write_text(json.dumps(state))
    if transport == "memory":
        click.echo("note: memory transport is per-process — these "
                   "services are isolated; use --transport mqtt for a "
                   "multi-process system")


@system.command("stop")
@click.option("--state-file", default=_DEFAULT_STATE_FILE)
def system_stop(state_file) -> None:
    """Stop everything `system start` spawned (reference:
    scripts/system_stop.sh)."""
    import os
    import signal

    state = _load_state(state_file)
    if not state:
        click.echo("nothing recorded as running")
        return
    from .utils.configuration import pid_verified
    for name, value in state.items():
        pid, start = _state_entry(value)
        if _pid_alive(pid):
            # a stale pid file can point at a recycled pid belonging to
            # an unrelated process — only signal the exact process we
            # spawned (start-time identity when recorded; cmdline
            # heuristic for older state files)
            if start is not None:
                ok = pid_verified(pid, start_time=start)
                why = "start time changed"
            else:
                ok = pid_verified(pid, name) or pid_verified(pid)
                why = "cmdline no longer matches"
            if not ok:
                click.echo(f"{name}: pid {pid} alive but {why} — "
                           f"likely recycled, skipped")
                continue
            try:
                os.kill(pid, signal.SIGTERM)
                click.echo(f"{name}: stopped pid {pid}")
            except OSError as exc:
                click.echo(f"{name}: pid {pid} — {exc}")
        else:
            click.echo(f"{name}: pid {pid} already gone")
        try:
            # reap if the child is ours (same-process start/stop);
            # otherwise init adopts and reaps it
            os.waitpid(pid, os.WNOHANG)
        except (ChildProcessError, OSError):
            pass
    _state_path(state_file).unlink(missing_ok=True)


@system.command("status")
@click.option("--state-file", default=_DEFAULT_STATE_FILE)
def system_status(state_file) -> None:
    """Show what `system start` spawned and whether it is alive."""
    state = _load_state(state_file)
    if not state:
        click.echo("not running")
        return
    for name, value in state.items():
        pid, _ = _state_entry(value)
        click.echo(f"{name}: pid {pid} "
                   f"{'alive' if _pid_alive(pid) else 'DEAD'}")


@system.command("reset")
@transport_option
def system_reset(transport) -> None:
    """Clear durable bootstrap state — the retained registrar boot
    topic on the broker (reference: scripts/system_reset.sh)."""
    if transport == "memory":
        click.echo("memory transport keeps no retained state outside "
                   "processes; nothing to reset")
        return
    from .transport.mqtt import MQTT_AVAILABLE, MQTTMessage
    if not MQTT_AVAILABLE:
        raise click.ClickException("paho-mqtt is not installed")
    from .process import REGISTRAR_BOOT_SUFFIX
    from .utils.configuration import (get_namespace,
                                      get_transport_configuration)
    config = get_transport_configuration()
    message = MQTTMessage(host=config.host, port=config.port,
                          username=config.username,
                          password=config.password, tls=config.tls)
    message.connect()
    if not message.connected():
        message.disconnect()
        raise click.ClickException(
            f"cannot reach broker {config.host}:{config.port}"
            f"{': ' + str(message.stats['last_error']) if message.stats['last_error'] else ''}")
    boot_topic = f"{get_namespace()}/{REGISTRAR_BOOT_SUFFIX}"
    message.publish(boot_topic, "", retain=True, wait=True)
    message.disconnect()
    click.echo(f"cleared retained {boot_topic}")


if __name__ == "__main__":
    main()
