# Command-line entry points.
#
# Capability parity with the reference console scripts
# (reference: pyproject.toml:36-40 — aiko, aiko_dashboard, aiko_pipeline,
# aiko_registrar; CLI autogen: aiko_services/cli.py:96-206, pipeline CLI:
# pipeline.py:874-936).
#
#   aiko_tpu registrar                  — run a registrar process
#   aiko_tpu pipeline create DEF.json   — run a pipeline from a definition
#   aiko_tpu pipeline show DEF.json     — validate + print a definition
#   aiko_tpu dashboard                  — curses service dashboard
#   aiko_tpu storage                    — run a storage service
#   aiko_tpu recorder                   — run a log recorder
#
# Transport selection: --transport memory|mqtt (AIKO_TPU_TRANSPORT env);
# mqtt interops with a real broker, memory is single-process.

from __future__ import annotations

import json
import os
import sys

import click

__all__ = ["main"]


def _make_runtime(name, transport):
    from .process import ProcessRuntime

    if transport == "mqtt":
        from .transport.mqtt import MQTT_AVAILABLE, MQTTMessage
        if not MQTT_AVAILABLE:
            raise click.ClickException(
                "mqtt transport requested but paho-mqtt is not installed")

        def factory(on_message, lwt_topic, lwt_payload, lwt_retain):
            from .utils.configuration import \
                get_transport_configuration
            config = get_transport_configuration()
            return MQTTMessage(on_message=on_message, lwt_topic=lwt_topic,
                               lwt_payload=lwt_payload,
                               lwt_retain=lwt_retain,
                               host=config.host, port=config.port,
                               username=config.username,
                               password=config.password, tls=config.tls)
        runtime = ProcessRuntime(name=name, transport_factory=factory)
    else:
        runtime = ProcessRuntime(name=name)
    return runtime.initialize()


transport_option = click.option(
    "--transport", default=lambda: os.environ.get("AIKO_TPU_TRANSPORT",
                                                  "memory"),
    type=click.Choice(["memory", "mqtt"]), help="control-plane transport")


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed service framework."""


@main.command()
@transport_option
def registrar(transport) -> None:
    """Run a registrar (primary election + service discovery)."""
    from .registrar import Registrar

    runtime = _make_runtime("registrar", transport)
    Registrar(runtime)
    click.echo(f"registrar on {runtime.topic_path} ({transport})")
    runtime.run(loop_when_no_handlers=True)


@main.group()
def pipeline() -> None:
    """Pipeline operations."""


@pipeline.command()
@click.argument("definition_pathname")
@click.option("--name", default=None, help="pipeline service name")
@click.option("--stream", "stream_id", default="*",
              help="stream id to create")
@click.option("--stream-parameters", default="{}",
              help="JSON dict of stream parameters")
@click.option("--frame", "frame_json", default=None,
              help="JSON swag for one immediate frame")
@transport_option
def create(definition_pathname, name, stream_id, stream_parameters,
           frame_json, transport) -> None:
    """Run a pipeline from DEFINITION_PATHNAME."""
    from .compute import ComputeRuntime
    from .pipeline import Pipeline, load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    runtime = _make_runtime(name or definition.name, transport)
    ComputeRuntime(runtime, "compute")
    pipe = Pipeline(runtime, definition, name=name,
                    definition_pathname=definition_pathname)
    pipe.create_stream(stream_id,
                       parameters=json.loads(stream_parameters))
    if frame_json is not None:
        pipe.post("process_frame", stream_id, json.loads(frame_json))
    click.echo(f"pipeline {pipe.name} on {pipe.topic_path} "
               f"({len(pipe.graph)} elements, {transport})")
    runtime.run(loop_when_no_handlers=True)


@pipeline.command()
@click.argument("definition_pathname")
def show(definition_pathname) -> None:
    """Validate and print a pipeline definition."""
    from .pipeline import PipelineGraph, load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    graph = PipelineGraph.from_definition(definition)
    graph.validate(definition)
    click.echo(f"pipeline: {definition.name} (runtime={definition.runtime})")
    for node in graph.topological_order():
        element = definition.element(node.name)
        deploy = "remote" if element.is_remote else "local"
        click.echo(f"  {node.name}: {element.input_names} -> "
                   f"{element.output_names} [{deploy}]"
                   + (f" -> {node.successors}" if node.successors else ""))
    click.echo("valid")


@main.command()
@transport_option
def storage(transport) -> None:
    """Run a storage service (sqlite key/value)."""
    from .storage import Storage

    runtime = _make_runtime("storage", transport)
    database, _ = os.environ.get("AIKO_TPU_STORAGE", "storage.db"), None
    Storage(runtime, database_path=database)
    click.echo(f"storage ({database}) on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def recorder(transport) -> None:
    """Run a log recorder."""
    from .recorder import Recorder

    runtime = _make_runtime("recorder", transport)
    Recorder(runtime)
    click.echo(f"recorder on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def dashboard(transport) -> None:
    """Curses dashboard: live service table + EC share browser."""
    from .dashboard import run_dashboard

    runtime = _make_runtime("dashboard", transport)
    run_dashboard(runtime)


if __name__ == "__main__":
    main()
