# Command-line entry points.
#
# Capability parity with the reference console scripts
# (reference: pyproject.toml:36-40 — aiko, aiko_dashboard, aiko_pipeline,
# aiko_registrar; CLI autogen: aiko_services/cli.py:96-206, pipeline CLI:
# pipeline.py:874-936).
#
#   aiko_tpu registrar                  — run a registrar process
#   aiko_tpu pipeline create DEF.json   — run a pipeline from a definition
#   aiko_tpu pipeline show DEF.json     — validate + print a definition
#   aiko_tpu dashboard                  — curses service dashboard
#   aiko_tpu storage                    — run a storage service
#   aiko_tpu recorder                   — run a log recorder
#
# Transport selection: --transport memory|mqtt (AIKO_TPU_TRANSPORT env);
# mqtt interops with a real broker, memory is single-process.

from __future__ import annotations

import json
import os
import sys

import click

__all__ = ["main"]


def _make_runtime(name, transport):
    from .process import ProcessRuntime

    if transport == "mqtt":
        from .transport.mqtt import MQTT_AVAILABLE, MQTTMessage
        if not MQTT_AVAILABLE:
            raise click.ClickException(
                "mqtt transport requested but paho-mqtt is not installed")

        def factory(on_message, lwt_topic, lwt_payload, lwt_retain):
            from .utils.configuration import \
                get_transport_configuration
            config = get_transport_configuration()
            return MQTTMessage(on_message=on_message, lwt_topic=lwt_topic,
                               lwt_payload=lwt_payload,
                               lwt_retain=lwt_retain,
                               host=config.host, port=config.port,
                               username=config.username,
                               password=config.password, tls=config.tls)
        runtime = ProcessRuntime(name=name, transport_factory=factory)
    else:
        runtime = ProcessRuntime(name=name)
    return runtime.initialize()


transport_option = click.option(
    "--transport", default=lambda: os.environ.get("AIKO_TPU_TRANSPORT",
                                                  "memory"),
    type=click.Choice(["memory", "mqtt"]), help="control-plane transport")


@click.group()
def main() -> None:
    """aiko_services_tpu: TPU-native distributed service framework."""


@main.command()
@transport_option
def registrar(transport) -> None:
    """Run a registrar (primary election + service discovery)."""
    from .registrar import Registrar

    runtime = _make_runtime("registrar", transport)
    Registrar(runtime)
    click.echo(f"registrar on {runtime.topic_path} ({transport})")
    runtime.run(loop_when_no_handlers=True)


@main.group()
def pipeline() -> None:
    """Pipeline operations."""


@pipeline.command()
@click.argument("definition_pathname")
@click.option("--name", default=None, help="pipeline service name")
@click.option("--stream", "stream_id", default="*",
              help="stream id to create")
@click.option("--stream-parameters", default="{}",
              help="JSON dict of stream parameters")
@click.option("--frame", "frame_json", default=None,
              help="JSON swag for one immediate frame")
@transport_option
def create(definition_pathname, name, stream_id, stream_parameters,
           frame_json, transport) -> None:
    """Run a pipeline from DEFINITION_PATHNAME."""
    from .compute import ComputeRuntime
    from .pipeline import Pipeline, load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    runtime = _make_runtime(name or definition.name, transport)
    ComputeRuntime(runtime, "compute")
    pipe = Pipeline(runtime, definition, name=name,
                    definition_pathname=definition_pathname)
    pipe.create_stream(stream_id,
                       parameters=json.loads(stream_parameters))
    if frame_json is not None:
        pipe.post("process_frame", stream_id, json.loads(frame_json))
    click.echo(f"pipeline {pipe.name} on {pipe.topic_path} "
               f"({len(pipe.graph)} elements, {transport})")
    runtime.run(loop_when_no_handlers=True)


@pipeline.command()
@click.argument("definition_pathname")
def show(definition_pathname) -> None:
    """Validate and print a pipeline definition."""
    from .pipeline import PipelineGraph, load_pipeline_definition

    definition = load_pipeline_definition(definition_pathname)
    graph = PipelineGraph.from_definition(definition)
    graph.validate(definition)
    click.echo(f"pipeline: {definition.name} (runtime={definition.runtime})")
    for node in graph.topological_order():
        element = definition.element(node.name)
        deploy = "remote" if element.is_remote else "local"
        click.echo(f"  {node.name}: {element.input_names} -> "
                   f"{element.output_names} [{deploy}]"
                   + (f" -> {node.successors}" if node.successors else ""))
    click.echo("valid")


@main.command()
@transport_option
def storage(transport) -> None:
    """Run a storage service (sqlite key/value)."""
    from .storage import Storage

    runtime = _make_runtime("storage", transport)
    database, _ = os.environ.get("AIKO_TPU_STORAGE", "storage.db"), None
    Storage(runtime, database_path=database)
    click.echo(f"storage ({database}) on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def recorder(transport) -> None:
    """Run a log recorder."""
    from .recorder import Recorder

    runtime = _make_runtime("recorder", transport)
    Recorder(runtime)
    click.echo(f"recorder on {runtime.topic_path}")
    runtime.run(loop_when_no_handlers=True)


@main.command()
@transport_option
def dashboard(transport) -> None:
    """Curses dashboard: live service table + EC share browser."""
    from .dashboard import run_dashboard

    runtime = _make_runtime("dashboard", transport)
    run_dashboard(runtime)


# -- system bring-up (reference: scripts/system_start.sh etc.) ---------------

_DEFAULT_STATE_FILE = "~/.aiko_tpu_system.json"


def _state_path(state_file: str):
    import pathlib
    return pathlib.Path(state_file).expanduser()


def _load_state(state_file: str) -> dict:
    import json
    path = _state_path(state_file)
    if path.exists():
        try:
            return json.loads(path.read_text())
        except (ValueError, OSError):
            return {}
    return {}


def _pid_alive(pid: int) -> bool:
    import os
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


def _state_entry(value):
    """State-file values are [pid, start_time] (older files: bare
    pid → start_time None)."""
    if isinstance(value, (list, tuple)):
        return int(value[0]), value[1]
    return int(value), None


@main.group()
def system() -> None:
    """Bring a whole control plane up/down (registrar, recorder,
    storage — and mosquitto when the transport is mqtt)."""


@system.command("start")
@transport_option
@click.option("--state-file", default=_DEFAULT_STATE_FILE,
              help="where to record the spawned pids")
@click.option("--services", default="registrar,recorder,storage",
              help="comma-separated aiko_tpu subcommands to spawn")
def system_start(transport, state_file, services) -> None:
    """One-command bring-up (reference: scripts/system_start.sh —
    mosquitto + registrar + dashboard)."""
    import json
    import shutil
    import subprocess
    import sys

    from .utils.configuration import pid_start_time, pid_verified

    def _still_ours(value):
        pid, start = _state_entry(value)
        if not _pid_alive(pid):
            return False
        # a recycled pid (different start time) is NOT our process —
        # don't let a stale state file block startup forever; legacy
        # bare-pid entries fall back to the cmdline heuristic
        if start is not None:
            return pid_verified(pid, start_time=start)
        return pid_verified(pid)

    state = {name: value
             for name, value in _load_state(state_file).items()
             if _still_ours(value)}
    if state:
        raise click.ClickException(
            f"system already running ({', '.join(state)}); "
            f"run `aiko_tpu system stop` first")

    if transport == "mqtt" and shutil.which("mosquitto"):
        from .utils.configuration import get_transport_configuration
        config = get_transport_configuration()
        if config.host in ("localhost", "127.0.0.1"):
            broker = subprocess.Popen(
                ["mosquitto", "-p", str(config.port)],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            state["mosquitto"] = [broker.pid, pid_start_time(broker.pid)]
            click.echo(f"mosquitto: pid {broker.pid} (port {config.port})")

    for name in [s.strip() for s in services.split(",") if s.strip()]:
        child = subprocess.Popen(
            [sys.executable, "-m", "aiko_services_tpu", name,
             "--transport", transport],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        # record (pid, start_time): the exact process identity, so a
        # later `stop` can never signal a recycled pid
        state[name] = [child.pid, pid_start_time(child.pid)]
        click.echo(f"{name}: pid {child.pid}")
    _state_path(state_file).write_text(json.dumps(state))
    if transport == "memory":
        click.echo("note: memory transport is per-process — these "
                   "services are isolated; use --transport mqtt for a "
                   "multi-process system")


@system.command("stop")
@click.option("--state-file", default=_DEFAULT_STATE_FILE)
def system_stop(state_file) -> None:
    """Stop everything `system start` spawned (reference:
    scripts/system_stop.sh)."""
    import os
    import signal

    state = _load_state(state_file)
    if not state:
        click.echo("nothing recorded as running")
        return
    from .utils.configuration import pid_verified
    for name, value in state.items():
        pid, start = _state_entry(value)
        if _pid_alive(pid):
            # a stale pid file can point at a recycled pid belonging to
            # an unrelated process — only signal the exact process we
            # spawned (start-time identity when recorded; cmdline
            # heuristic for older state files)
            if start is not None:
                ok = pid_verified(pid, start_time=start)
                why = "start time changed"
            else:
                ok = pid_verified(pid, name) or pid_verified(pid)
                why = "cmdline no longer matches"
            if not ok:
                click.echo(f"{name}: pid {pid} alive but {why} — "
                           f"likely recycled, skipped")
                continue
            try:
                os.kill(pid, signal.SIGTERM)
                click.echo(f"{name}: stopped pid {pid}")
            except OSError as exc:
                click.echo(f"{name}: pid {pid} — {exc}")
        else:
            click.echo(f"{name}: pid {pid} already gone")
        try:
            # reap if the child is ours (same-process start/stop);
            # otherwise init adopts and reaps it
            os.waitpid(pid, os.WNOHANG)
        except (ChildProcessError, OSError):
            pass
    _state_path(state_file).unlink(missing_ok=True)


@system.command("status")
@click.option("--state-file", default=_DEFAULT_STATE_FILE)
def system_status(state_file) -> None:
    """Show what `system start` spawned and whether it is alive."""
    state = _load_state(state_file)
    if not state:
        click.echo("not running")
        return
    for name, value in state.items():
        pid, _ = _state_entry(value)
        click.echo(f"{name}: pid {pid} "
                   f"{'alive' if _pid_alive(pid) else 'DEAD'}")


@system.command("reset")
@transport_option
def system_reset(transport) -> None:
    """Clear durable bootstrap state — the retained registrar boot
    topic on the broker (reference: scripts/system_reset.sh)."""
    if transport == "memory":
        click.echo("memory transport keeps no retained state outside "
                   "processes; nothing to reset")
        return
    from .transport.mqtt import MQTT_AVAILABLE, MQTTMessage
    if not MQTT_AVAILABLE:
        raise click.ClickException("paho-mqtt is not installed")
    from .process import REGISTRAR_BOOT_SUFFIX
    from .utils.configuration import (get_namespace,
                                      get_transport_configuration)
    config = get_transport_configuration()
    message = MQTTMessage(host=config.host, port=config.port,
                          username=config.username,
                          password=config.password, tls=config.tls)
    message.connect()
    if not message.connected():
        message.disconnect()
        raise click.ClickException(
            f"cannot reach broker {config.host}:{config.port}"
            f"{': ' + str(message.stats['last_error']) if message.stats['last_error'] else ''}")
    boot_topic = f"{get_namespace()}/{REGISTRAR_BOOT_SUFFIX}"
    message.publish(boot_topic, "", retain=True, wait=True)
    message.disconnect()
    click.echo(f"cleared retained {boot_topic}")


if __name__ == "__main__":
    main()
