# Continuous batching for autoregressive decode: iteration-level
# scheduling of LLM generation on TPU.
#
# The BatchingScheduler (ops/batching.py) coalesces FIXED-size work —
# right for ASR chunks, wrong for generation, where requests finish at
# different steps and a fixed batch would idle the MXU on ragged tails.
# Here requests join and leave the running batch BETWEEN decode steps
# (the vLLM-style iteration-level discipline), built TPU-first:
#
#   * one compiled step function decodes one token for ALL slots —
#     [max_slots] is static, so XLA compiles exactly once; empty/done
#     slots compute garbage that is masked on the host (lane occupancy
#     is the scheduler's job, not the compiler's);
#   * per-slot KV caches live in one [S, H, T, D] buffer per layer with
#     per-slot lengths — no batch-global cursor, no reallocation;
#   * prefill is bucketed by prompt length (static shapes per bucket)
#     and scattered into a free slot's cache rows;
#   * K decode steps run per device round via lax.scan
#     (steps_per_sync), so the host syncs [K, S] tokens instead of
#     round-tripping per token — the tunnel/PCIe cost amortizes.
#
# The reference has no generation serving at all (its LLM hop is a
# blocking HTTP call: reference examples/speech/speech_elements.py:
# 155-172).  No counterpart file exists — this is TPU-native new build.

from __future__ import annotations

import dataclasses
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .models import layers as L
from .models.llama import LlamaConfig
from .utils import get_logger

__all__ = ["ContinuousDecoder", "DecodeRequest"]


@dataclasses.dataclass
class DecodeRequest:
    request_id: str
    prompt: list                      # token ids
    max_new_tokens: int
    callback: Callable                # callback(request_id, token_list)
    generated: list = dataclasses.field(default_factory=list)
    slot: int = -1


def _slot_attention(layer, config: LlamaConfig, x, cos, sin,
                    k_cache, v_cache, lengths):
    """One-token attention for all slots at per-slot positions.

    x: [S, 1, dim]; k_cache/v_cache: [S, H_kv, T, D]; lengths: [S] —
    tokens already in each slot's context (the new token's position)."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q = L._split_heads(L.linear(layer["attn"]["q"], x), num_heads)
    k = L._split_heads(L.linear(layer["attn"]["k"], x), num_kv)
    v = L._split_heads(L.linear(layer["attn"]["v"], x), num_kv)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)

    slots = jnp.arange(x.shape[0])
    # write this token's K/V at each slot's own cursor
    k_cache = k_cache.at[slots, :, lengths].set(k[:, :, 0])
    v_cache = v_cache.at[slots, :, lengths].set(v[:, :, 0])

    # attend over each slot's valid prefix (inclusive of the new token).
    # GQA via a grouped einsum against the SHARED KV — materializing
    # repeated caches (jnp.repeat) costs group× HBM and halves the slot
    # capacity that fits on a chip.
    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    valid = (jnp.arange(k_cache.shape[2])[None] <=
             lengths[:, None])[:, None, None, None]    # [S,1,1,1,T]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group, num_q, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    scores = jnp.einsum("skgqd,sktd->skgqt",
                        q_grouped.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) * scale
    scores = jnp.where(valid, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    out = jnp.einsum("skgqt,sktd->skgqd", weights, v_cache)
    out = out.reshape(slots_n, num_heads, num_q, head_dim)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_cache, v_cache)


def _build_step(config: LlamaConfig):
    """One decode iteration for every slot; jitted once, caches donated
    so the slot buffers update in place on device.  Params are an
    ARGUMENT, not a closure capture — captured trees get baked into the
    compiled program as constants (gigabytes for real checkpoints,
    duplicated per recompile)."""
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)

    def one_token(params, tokens, lengths, k_caches, v_caches):
        x = L.embedding(params["embed"],
                        tokens[:, None]).astype(config.dtype)
        new_k, new_v = [], []
        for i, layer in enumerate(params["layers"]):
            attn_out, k_c, v_c = _slot_attention(
                layer, config, L.rms_norm(layer["ln_attn"], x),
                cos, sin, k_caches[i], v_caches[i], lengths)
            new_k.append(k_c)
            new_v.append(v_c)
            x = x + attn_out
            normed = L.rms_norm(layer["ln_mlp"], x)
            x = x + L.linear(layer["down"],
                             jax.nn.silu(L.linear(layer["gate"], normed)) *
                             L.linear(layer["up"], normed))
        x = L.rms_norm(params["ln_out"], x)
        logits = L.linear(params["lm_head"], x.astype(jnp.float32))
        next_tokens = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        return next_tokens, new_k, new_v

    def step_k(params, tokens, lengths, active, k_caches, v_caches,
               num_steps):
        """lax.scan of `num_steps` iterations; returns tokens emitted
        [K, S].  Inactive slots keep length (no cache growth)."""
        def body(carry, _):
            tokens, lengths, k_caches, v_caches = carry
            next_tokens, k_caches, v_caches = one_token(
                params, tokens, lengths, k_caches, v_caches)
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            return (next_tokens, lengths, k_caches, v_caches), next_tokens

        (tokens, lengths, k_caches, v_caches), emitted = jax.lax.scan(
            body, (tokens, lengths, k_caches, v_caches), None,
            length=num_steps)
        return emitted, tokens, lengths, k_caches, v_caches

    return jax.jit(step_k, static_argnames=("num_steps",),
                   donate_argnames=("k_caches", "v_caches"))


class ContinuousDecoder:
    """Iteration-level scheduler over a fixed slot pool.

    submit() enqueues a request; drive it from the event engine
    (attach()) or call pump() manually.  Each pump round: admit pending
    prompts into free slots (bucketed prefill), run steps_per_sync
    decode iterations on device, sync the emitted tokens, retire
    EOS/max-length slots through their callbacks."""

    def __init__(self, params, config: LlamaConfig, max_slots: int = 8,
                 max_seq: int | None = None, eos_token: int | None = None,
                 prefill_buckets=(32, 128), steps_per_sync: int = 4,
                 name: str = "decoder"):
        self.config = config
        self.params = params
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq_len
        self.eos_token = eos_token
        self.steps_per_sync = steps_per_sync
        # buckets beyond the cache's time axis would blow up the admit
        # scatter — clamp, dedupe, keep sorted
        self.prefill_buckets = tuple(sorted(
            {min(int(b), self.max_seq - 1) for b in prefill_buckets}))
        self.logger = get_logger(f"serving.{name}")
        self.on_idle = None          # hook: fires when the last slot
                                     # retires and nothing is pending

        shape = (max_slots, config.num_kv_heads, self.max_seq,
                 config.head_dim)
        self._k = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        self._v = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        self._tokens = jnp.zeros((max_slots,), jnp.int32)
        self._lengths = jnp.zeros((max_slots,), jnp.int32)

        self._step = _build_step(config)
        self._prefill_fns: dict = {}
        self._slots: list[DecodeRequest | None] = [None] * max_slots
        self._pending: list[DecodeRequest] = []
        self._timer = None
        self.stats = {"steps": 0, "rounds": 0, "completed": 0,
                      "prefills": 0, "occupancy_sum": 0.0,
                      "prefill_s": 0.0, "decode_s": 0.0}

    # -- public API --------------------------------------------------------
    def submit(self, request_id: str, prompt, max_new_tokens: int,
               callback) -> None:
        # keep the TAIL on overflow (recent context matters most); the
        # largest prefill bucket is a hard cap — an oversized prompt
        # would blow up _admit's scatter
        limit = min(self.max_seq - 1, self.prefill_buckets[-1])
        # empty prompts would seed generation from a pad position —
        # normalize to a single pad token at position 0
        prompt = ([int(t) for t in prompt] or [0])[-limit:]
        self._pending.append(DecodeRequest(request_id, prompt,
                                           int(max_new_tokens), callback))

    def attach(self, engine, period: float = 0.002) -> int:
        # idempotent: re-attaching while already pumping (e.g. a stream
        # reopens during a deferred teardown) must not orphan the
        # first timer
        if self._timer is None:
            self._timer = engine.add_timer_handler(self.pump, period)
        return self._timer

    @property
    def attached(self) -> bool:
        return self._timer is not None

    def detach(self, engine) -> None:
        if self._timer is not None:
            engine.remove_timer_handler(self._timer)
            self._timer = None

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.active_count == 0 and not self._pending

    # -- scheduling --------------------------------------------------------
    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.prefill_buckets[-1]

    def _admit_fn(self, bucket: int, width: int):
        """Compiled once per (bucket, admit-width): ONE program runs the
        stacked prefill for up to `width` prompts AND scatters their
        K/V prefixes, first tokens, and lengths into the slot buffers
        on device.  The host syncs a single [width] token array per
        group — not one round-trip per request (the per-request admit
        was a throughput cliff under bursty arrivals on thin links)."""
        key = (bucket, width)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        from .models.llama import init_llama_caches, llama_hidden

        def admit(params, k_caches, v_caches, tokens, lengths,
                  prompts, true_lens, slots, valid):
            # prompts: [A, bucket]; slots: [A] DISTINCT slot ids (pad
            # rows point at other distinct slots and write back their
            # own current content — a no-op); valid: [A] bool.
            caches = init_llama_caches(self.config, width, bucket)
            hidden, caches = llama_hidden(params, self.config,
                                          prompts, caches)
            idx = jnp.maximum(true_lens - 1, 0)
            # select each prompt's last position BEFORE the vocab
            # projection: full prefill logits are [A, bucket, vocab] —
            # gigabytes at serving widths
            last_hidden = jnp.take_along_axis(
                hidden, idx[:, None, None], axis=1)[:, 0]
            last = L.linear(params["lm_head"],
                            last_hidden.astype(jnp.float32))
            firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
            mask = valid[:, None, None, None]
            for i, cache in enumerate(caches):
                cur_k = k_caches[i][slots][:, :, :bucket]
                cur_v = v_caches[i][slots][:, :, :bucket]
                k_caches[i] = k_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["k"], cur_k))
                v_caches[i] = v_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["v"], cur_v))
            tokens = tokens.at[slots].set(
                jnp.where(valid, firsts, tokens[slots]))
            lengths = lengths.at[slots].set(
                jnp.where(valid, true_lens, lengths[slots]))
            return firsts, k_caches, v_caches, tokens, lengths

        compiled = jax.jit(
            admit, donate_argnames=("k_caches", "v_caches", "tokens",
                                    "lengths"))
        self._prefill_fns[key] = compiled
        return compiled

    @staticmethod
    def _next_pow2(n: int) -> int:
        return 1 << max(0, (n - 1).bit_length())

    def _admit_pending(self) -> None:
        """Admit as many pending requests as there are free slots, in
        bucket groups: one stacked prefill + device-side scatter + one
        host sync per group."""
        free = [s for s in range(self.max_slots)
                if self._slots[s] is None]
        if not free or not self._pending:
            return
        take = self._pending[:len(free)]
        del self._pending[:len(take)]
        groups: dict[int, list[DecodeRequest]] = {}
        for request in take:
            groups.setdefault(self._bucket_for(len(request.prompt)),
                              []).append(request)
        start = time.perf_counter()
        for bucket, requests in groups.items():
            while requests:
                width = min(self.max_slots,
                            self._next_pow2(len(requests)))
                chunk, requests = requests[:width], requests[width:]
                self._admit_group(bucket, width, chunk, free)
        self.stats["prefill_s"] += time.perf_counter() - start

    def _admit_group(self, bucket: int, width: int,
                     chunk: list, free: list) -> None:
        n = len(chunk)
        slots = [free.pop(0) for _ in range(n)]
        # pad rows need DISTINCT slot ids (scatter order is unspecified
        # on collision): remaining free slots first, then occupied ones
        # — either way the pad row rewrites that slot's own content
        used = set(slots)
        spare = [s for s in range(self.max_slots) if s not in used]
        pad_slots = spare[:width - n]
        prompts = np.zeros((width, bucket), np.int32)
        true_lens = np.zeros((width,), np.int32)
        valid = np.zeros((width,), bool)
        for j, request in enumerate(chunk):
            prompts[j, :len(request.prompt)] = request.prompt
            true_lens[j] = len(request.prompt)
            valid[j] = True
        firsts, self._k, self._v, self._tokens, self._lengths = \
            self._admit_fn(bucket, width)(
                self.params, self._k, self._v, self._tokens,
                self._lengths, jnp.asarray(prompts),
                jnp.asarray(true_lens),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid))
        firsts = np.asarray(firsts)           # ONE sync per group
        for j, request in enumerate(chunk):
            slot = slots[j]
            first_token = int(firsts[j])
            request.slot = slot
            request.generated = [first_token]
            self._slots[slot] = request
            self.stats["prefills"] += 1
            if self._finished(request, first_token):
                self._retire(slot)

    def _finished(self, request: DecodeRequest, token: int) -> bool:
        return (self.eos_token is not None and token == self.eos_token) \
            or len(request.generated) >= request.max_new_tokens \
            or len(request.prompt) + len(request.generated) >= \
            self.max_seq - 1

    def _retire(self, slot: int) -> None:
        request = self._slots[slot]
        self._slots[slot] = None
        self.stats["completed"] += 1
        generated = request.generated
        if self.eos_token is not None and generated and \
                generated[-1] == self.eos_token:
            generated = generated[:-1]
        try:
            request.callback(request.request_id, generated)
        except Exception:
            self.logger.exception("callback failed for %s",
                                  request.request_id)

    def pump(self) -> None:
        """One scheduling round: admit, decode K steps, retire."""
        self._admit_pending()
        active = np.array([r is not None for r in self._slots])
        if not active.any():
            # admits can retire instantly (EOS as first token, 1-token
            # budget, prompt at the seq cap) — the idle hook must still
            # fire on this exit path or teardown callbacks never run
            if self.idle and self.on_idle is not None:
                self.on_idle()
            return
        self.stats["rounds"] += 1
        self.stats["occupancy_sum"] += float(active.mean())
        decode_start = time.perf_counter()
        emitted, self._tokens, self._lengths, self._k, self._v = \
            self._step(self.params, self._tokens, self._lengths,
                       jnp.asarray(active), self._k, self._v,
                       num_steps=self.steps_per_sync)
        self.stats["steps"] += self.steps_per_sync
        emitted = np.asarray(emitted)            # [K, S] host sync
        self.stats["decode_s"] += time.perf_counter() - decode_start
        for k in range(emitted.shape[0]):
            for slot in range(self.max_slots):
                request = self._slots[slot]
                if request is None:
                    continue
                token = int(emitted[k, slot])
                request.generated.append(token)
                if self._finished(request, token):
                    self._retire(slot)
        if self.idle and self.on_idle is not None:
            self.on_idle()

    def mean_occupancy(self) -> float:
        rounds = max(self.stats["rounds"], 1)
        return self.stats["occupancy_sum"] / rounds
