# Continuous batching for autoregressive decode: iteration-level
# scheduling of LLM generation on TPU.
#
# The BatchingScheduler (ops/batching.py) coalesces FIXED-size work —
# right for ASR chunks, wrong for generation, where requests finish at
# different steps and a fixed batch would idle the MXU on ragged tails.
# Here requests join and leave the running batch BETWEEN decode steps
# (the vLLM-style iteration-level discipline), built TPU-first:
#
#   * one compiled step function decodes one token for ALL slots —
#     [max_slots] is static, so XLA compiles exactly once; empty/done
#     slots compute garbage that is masked on the host (lane occupancy
#     is the scheduler's job, not the compiler's);
#   * per-slot KV caches live in one [S, H, T, D] buffer per layer with
#     per-slot lengths — no batch-global cursor, no reallocation;
#   * prefill is bucketed by prompt length (static shapes per bucket)
#     and scattered into a free slot's cache rows;
#   * K decode steps run per device round via lax.scan
#     (steps_per_sync), so the host syncs [K, S] tokens instead of
#     round-tripping per token — the tunnel/PCIe cost amortizes;
#   * prefill runs OFF the decode critical path (ISSUE 7): each pump
#     round dispatches the decode scan FIRST, then queues admit/extend
#     device calls BEHIND it — they execute while the host syncs the
#     scan and resolves tokens, so a decode round's sync never waits on
#     prefill (the Sarathi-Serve stall-free discipline).  A freshly
#     admitted slot's first token resolves from the admit program's own
#     output at the NEXT round's sync — the compiled decode step no
#     longer carries the deferred-admit resolution;
#   * the KV cache is storable as int8 with per-(slot, head, position)
#     scales (kv_cache_dtype="int8"): admits/extends write quantized
#     rows, the decode scan folds the scales into scores/weights
#     (layers.quantize_kv_cache) — the HBM-bound step's dominant read
#     is halved;
#   * self-speculative multi-token decoding (speculate_k=k): a
#     prompt-lookup n-gram drafter over a device-side context buffer
#     proposes k tokens per slot, one widened forward verifies the
#     (1+k)-token block, and greedy acceptance advances each slot by
#     its accepted run — provably the same tokens as the
#     non-speculative path, but up to 1+k tokens per weight-stream.
#
# The reference has no generation serving at all (its LLM hop is a
# blocking HTTP call: reference examples/speech/speech_elements.py:
# 155-172).  No counterpart file exists — this is TPU-native new build.

from __future__ import annotations

import dataclasses
import functools
import hashlib
import os
import time
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .models import layers as L
from .models.llama import LlamaConfig, llama_ffn
from .utils import get_logger

__all__ = ["ContinuousDecoder", "DecodeRequest", "PrefixKVCache",
           "prefix_chain_keys", "check_block_geometry",
           "measure_device_step"]


def measure_device_step(decoder, steps_per_sync: int = 64,
                        chains: int = 4) -> float:
    """Chained pure-device decode-step milliseconds for `decoder`'s
    compiled step at its serving shape: fresh zero caches, `chains`
    back-to-back rounds, ONE host sync at the end — separates device
    compute from the tunnel's ~0.1 s per-round dispatch+sync.  The
    single methodology behind the bench's llama_device_step_ms and
    tools/ab_w8.py, so the two cannot drift.  Probes the decoder's OWN
    configuration (int8 KV layout, speculative step) — in speculative
    mode the number is per VERIFY iteration, which emits up to
    1 + speculate_k tokens."""
    config = decoder.config
    slots = decoder.max_slots
    tokens = jnp.ones((slots,), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    budgets = jnp.full((slots,), 1 << 30, jnp.int32)
    context = jnp.zeros((slots, decoder.max_seq), jnp.int32) \
        if decoder.speculate_k else None
    if decoder.paged:
        # paged probe: fresh zero pools at the pool's CURRENT capacity
        # (shape-identical to the serving pool, so the compiled
        # executable is the one serving runs) and round-robin distinct
        # tables at the serving gather width
        nb = -(-decoder._cache_t // decoder.kv_block)
        k_probe = decoder.pool._zero_pools(decoder.pool.num_blocks)
        v_probe = decoder.pool._zero_pools(decoder.pool.num_blocks)
        ids = 1 + (np.arange(slots * nb) %
                   max(1, decoder.pool.num_blocks - 1))
        tables = jnp.asarray(ids.reshape(slots, nb).astype(np.int32))
    else:
        k_probe = decoder._zero_caches()
        v_probe = decoder._zero_caches()

    def chain(rounds):
        nonlocal k_probe, v_probe, tokens, lengths, context
        out = None
        for _ in range(rounds):
            if decoder.paged and decoder.speculate_k:
                out = decoder._step(decoder.params, tokens, lengths,
                                    active, budgets, context, k_probe,
                                    v_probe, tables,
                                    num_steps=steps_per_sync, eos=-1,
                                    t_cap=decoder._cache_t)
                (_, _, tokens, lengths, context, k_probe,
                 v_probe) = out
            elif decoder.paged:
                out = decoder._step(decoder.params, tokens, lengths,
                                    active, budgets, k_probe, v_probe,
                                    tables, num_steps=steps_per_sync,
                                    eos=-1, t_cap=decoder._cache_t)
                _, _, tokens, lengths, k_probe, v_probe = out
            elif decoder.speculate_k:
                out = decoder._step(decoder.params, tokens, lengths,
                                    active, budgets, context, k_probe,
                                    v_probe, num_steps=steps_per_sync,
                                    eos=-1)
                (_, _, tokens, lengths, context, k_probe,
                 v_probe) = out
            else:
                out = decoder._step(decoder.params, tokens, lengths,
                                    active, budgets, k_probe, v_probe,
                                    num_steps=steps_per_sync, eos=-1)
                _, _, tokens, lengths, k_probe, v_probe = out
        np.asarray(out[0][-1])          # one sync for the chain
    chain(1)                             # warm (compile cache hit)
    start = time.perf_counter()
    chain(chains)
    return (time.perf_counter() - start) * 1000.0 / \
        (chains * steps_per_sync)

# decode attention inner loop for the "select" KV mode: "two_pass"
# (scores einsum + softmax + weights einsum), "online" (flash-style
# single sweep over time blocks with running max/sum — measured a
# wash, -1%), or "vpu" (broadcast-multiply reductions — measured 70%
# SLOWER; kept as the recorded dead end).  The "block" KV mode (the
# default) hardcodes the two-pass einsums — ATTENTION_IMPL has no
# effect there; tools/ab_decode_attention.py pins KV mode per case so
# the labels stay meaningful.
# "paged_kernel" (ISSUE 16) applies to PAGED decoders only: the
# decode/spec/extend attentions run the fused pallas kernel
# (ops.paged_attention) reading pool blocks straight through the
# block table — no slot-major gather materializes.  The gather path
# stays the bit-parity oracle; dense decoders ignore the value (it
# falls through to two_pass).  Read at decoder CONSTRUCTION (stashed
# as self.paged_kernel), so flipping the module global never switches
# a live decoder's compiled programs mid-stream.
ATTENTION_IMPL = os.environ.get("AIKO_DECODE_ATTENTION", "two_pass")
# KV write strategy inside the decode scan:
#   "select" — masked full-cache select per step (r4 design);
#   "block"  — new tokens land in a small [S, H, num_steps, D] side
#              buffer at the SCAN index (uniform across slots, so XLA
#              updates in place) and merge into the main cache once per
#              round.  The main cache is READ-ONLY inside the scan.
# Measured motivation: step time vs cache size has a 37.9 us/T slope
# where the read-only floor is 10.2 us/T — the functional full-cache
# select makes XLA touch the KV ~4x per step (read for the select,
# write the full result, read again for attention, x K and V).  The
# side buffer removes every full-cache write from the hot loop:
# measured 14.6 -> 11.4 ms/step at the 1b/256-slot/cache-256 serving
# shape (slope 37.9 -> 16.1 us/T), identical tokens vs the oracle
# across the whole serving suite.  "select" remains available; it
# measures slightly better only below ~cache 180 (the merge+side
# fixed cost), where steps are cheap anyway.
KV_WRITE = os.environ.get("AIKO_DECODE_KV", "block")
_ONLINE_BLOCK = 256         # time-block per online-softmax sweep step


def _online_decode_attention(q_grouped, k_cache, v_cache, lengths,
                             scale):
    """Single-pass GQA decode attention: lax.scan over time blocks
    with a running (max, sum, accumulator) — the flash-attention
    recurrence expressed in plain XLA, so K and V stream through HBM
    exactly once instead of once per einsum pass.

    q_grouped: [S, Hkv, G, 1, D]; caches [S, Hkv, T, D]; lengths [S].
    Returns [S, Hkv, G, 1, D] f32."""
    slots_n, num_kv, group, num_q, head_dim = q_grouped.shape
    t_total = k_cache.shape[2]
    block = min(_ONLINE_BLOCK, t_total)
    num_blocks = -(-t_total // block)
    pad = num_blocks * block - t_total
    if pad:
        k_cache = jnp.pad(k_cache, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # [blocks, S, Hkv, block, D]: scan carries one block per step
    k_blocks = jnp.moveaxis(
        k_cache.reshape(slots_n, num_kv, num_blocks, block, head_dim),
        2, 0)
    v_blocks = jnp.moveaxis(
        v_cache.reshape(slots_n, num_kv, num_blocks, block, head_dim),
        2, 0)
    positions = jnp.arange(block)

    def body(carry, inputs):
        running_max, running_sum, acc = carry
        index, k_blk, v_blk = inputs
        t0 = index * block
        valid = ((t0 + positions)[None, :] <=
                 lengths[:, None])[:, None, None, None]   # [S,1,1,1,B]
        scores = jnp.einsum("skgqd,skbd->skgqb", q_grouped, k_blk,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid, scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(running_max, blk_max)
        # rescale the old accumulator into the new max's frame
        correction = jnp.exp(running_max - new_max)
        probs = jnp.exp(scores - new_max)
        new_sum = running_sum * correction + \
            jnp.sum(probs, axis=-1, keepdims=True)
        acc = acc * correction + jnp.einsum(
            "skgqb,skbd->skgqd", probs.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return (new_max, new_sum, acc), None

    init = (jnp.full((slots_n, num_kv, group, num_q, 1), -jnp.inf,
                     jnp.float32),
            jnp.zeros((slots_n, num_kv, group, num_q, 1), jnp.float32),
            jnp.zeros((slots_n, num_kv, group, num_q, head_dim),
                      jnp.float32))
    (final_max, final_sum, acc), _ = jax.lax.scan(
        body, init, (jnp.arange(num_blocks), k_blocks, v_blocks))
    return acc / jnp.maximum(final_sum, 1e-30)


@dataclasses.dataclass
class DecodeRequest:
    request_id: str
    prompt: list                      # token ids
    max_new_tokens: int
    callback: Callable                # callback(request_id, token_list)
    generated: list = dataclasses.field(default_factory=list)
    slot: int = -1
    # SLO timestamps (scheduler clock): TTFT = first_time - submit_time;
    # inter-token latency derives from (last_time - first_time) and the
    # per-sync max_gap (tokens arrive in sync bursts — the gap BETWEEN
    # syncs is what an admit stall inflates, so it is tracked per
    # request as the worst observed stall)
    submit_time: float = 0.0
    first_time: float = 0.0
    last_time: float = 0.0
    max_gap: float = 0.0
    # chunked-prefill progress: tokens of `prompt` already written to
    # the slot's KV cache; prefilling=True while chunks remain
    prefill_pos: int = 0
    prefilling: bool = False
    # request journey (ISSUE 12): per-request lifecycle record — the
    # admission verdict, queue/prefill timeline, bounded per-token tick
    # ring, deadline margin — correlated to the frame's TraceContext
    # (observe/journey.py).  None only when journeys are disabled.
    journey: object = None
    # end-to-end completion deadline (scheduler clock) as passed to
    # submit(); the journey reports the margin at completion against it
    deadline: float | None = None
    # prefix/KV reuse (ISSUE 13): tokens satisfied from the prefix
    # cache at admit (0 = cold), the pinned chain keys (released at
    # retire), whether the cache was already probed (miss metrics must
    # count once per request, not once per deferred round), and the
    # tenant the request bills its cache traffic to
    prefix_hit: int = 0
    prefix_nodes: list = dataclasses.field(default_factory=list)
    prefix_probed: bool = False
    tenant: str = ""
    # prefill population label override (ISSUE 14): "" derives
    # cached/cold from prefix_hit; the disaggregated client stamps
    # "remote" so the TTFT sketches and journeys split out requests
    # whose prompt KV was computed by a prefill runtime
    prefill_label: str = ""
    # in-flight prefix dedup window (ISSUE 14 satellite, PR 13 residue
    # d): dedup_wait holds the leading-block key this request is
    # waiting on (a same-batch duplicate defers until the leader's
    # prompt blocks land); dedup_hot marks a leader some follower is
    # waiting on (its prompt harvests EARLY, at first token, instead
    # of at retire); inflight_key is the leader's registration key
    dedup_wait: str = ""
    dedup_hot: bool = False
    inflight_key: str = ""
    # direct slot-table install (ISSUE 15 satellite): pool block ids a
    # disaggregated client pre-installed for this request on a paged
    # CACHELESS decoder — admit aliases them into the slot's table
    # (ownership transfers to the slot) and prefills only the suffix
    kv_block_ids: list = dataclasses.field(default_factory=list)
    # chunk-streamed prefill progress (ISSUE 17): invoked (request,
    # finished) after each chunk extend advances prefill_pos — the
    # disaggregated PrefillRuntime harvests + ships the newly
    # complete blocks from here, so transfer overlaps the remaining
    # prefill compute instead of trailing it
    progress_callback: object = None


def prefix_chain_keys(tenant: str, tokens, block_tokens: int) -> list:
    """Hash-chain block keys for a token sequence: block i is keyed
    blake2b(parent_key, block_i_tokens), with block 0's parent the
    TENANT root — so every key commits to the entire token prefix
    behind it (the path identity of SGLang's RadixAttention, in hash
    form over fixed blocks like vLLM's prefix caching) and two tenants
    never share a block (isolation by construction, per-tenant byte
    accounting for free).  Only complete blocks are keyed; the ragged
    tail is always prefilled."""
    tenant = str(tenant or "default")
    parent = b"t\x00" + tenant.encode("utf-8")
    keys = []
    for i in range(len(tokens) // block_tokens):
        digest = hashlib.blake2b(parent, digest_size=16)
        digest.update(np.asarray(
            tokens[i * block_tokens:(i + 1) * block_tokens],
            np.int64).tobytes())
        parent = digest.digest()
        keys.append(parent.hex())
    return keys


def check_block_geometry(layout, block_tokens: int, entry) -> None:
    """Refuse a shipped block whose ARRAYS do not match a bound
    storage layout — the wire schema proves dtype/rank, but a
    schema-legal payload with the wrong layer count or head/head-dim
    extents would poison the slot cache and wedge the pump at the next
    hit (PR 14 review finding).  Shared by the prefix cache's
    install_chain and the paged direct slot-table install (ISSUE 15).
    Raises ValueError; the disaggregated client rides its
    corrupt-transfer rung."""
    layers, heads, head_dim = (int(layout[0]), int(layout[1]),
                               int(layout[2]))
    int8 = str(layout[4]) not in ("False", "0", "")
    for side in ("k", "v"):
        rows = entry[side]
        if len(rows) != layers:
            raise ValueError(
                f"block ships {len(rows)} layers, cache layout "
                f"has {layers}")
        want = (heads, int(block_tokens), head_dim)
        for leaf in rows:
            if isinstance(leaf, dict) != int8:
                raise ValueError(
                    f"block {side} storage form does not match "
                    f"the cache's int8={int8} layout")
            values = leaf["q"] if isinstance(leaf, dict) else leaf
            if tuple(values.shape) != want:
                raise ValueError(
                    f"block {side} rows shape "
                    f"{tuple(values.shape)} != layout {want}")
            if isinstance(leaf, dict) and \
                    tuple(leaf["s"].shape) != want[:2]:
                raise ValueError(
                    f"block {side} scale shape "
                    f"{tuple(leaf['s'].shape)} != {want[:2]}")


def _stack_block_leaves(leaves):
    """Stack per-block host leaves into one [M, H, B, D] layer stack
    (int8 dicts leaf-wise) — the one-transfer-per-layer form the pool's
    write_blocks scatter consumes."""
    if isinstance(leaves[0], dict):
        return {"q": np.stack([leaf["q"] for leaf in leaves]),
                "s": np.stack([leaf["s"] for leaf in leaves])}
    return np.stack(leaves)


class _PrefixBlock:
    """One cached block: per-layer K/V rows in the DECODER's storage
    layout ([H, B, D] arrays, or {"q", "s"} int8 dicts — a hit on an
    int8 cache is a bytes win too), plus the tree bookkeeping eviction
    needs (parent/children for leaf-first order, refs for pinning).
    In PAGED mode (ISSUE 15) the rows live in the decoder's block pool
    instead: `pool_id` names the pool block (the cache holds one pool
    ref on it) and k_rows/v_rows are None — a hit aliases the pool
    block into the slot's table, no rows move at all."""

    __slots__ = ("key", "parent", "tenant", "k_rows", "v_rows",
                 "refs", "children", "nbytes", "pool_id")

    def __init__(self, key, parent, tenant, k_rows, v_rows, nbytes,
                 pool_id=None):
        self.key = key
        self.parent = parent
        self.tenant = tenant
        self.k_rows = k_rows
        self.v_rows = v_rows
        self.refs = 0
        self.children: set = set()
        self.nbytes = int(nbytes)
        self.pool_id = pool_id


class PrefixKVCache:
    """Hash-addressed prefix/KV reuse cache for ContinuousDecoder
    (ISSUE 13, ROADMAP item 3).

    Prompts are chunked into fixed `block_tokens` blocks, each keyed by
    hash(parent_key, block_tokens) — see prefix_chain_keys.  Admit does
    a longest-prefix match: a hit copies the cached K/V rows into the
    slot cache and prefill runs only on the uncached suffix, so a
    shared system prompt or a conversation's whole history costs one
    block copy instead of a re-prefill.  Blocks are harvested when a
    request RETIRES (prompt + all generated tokens but the last, whose
    K/V is never written), so a multi-turn session's next turn
    longest-matches everything it has ever said.

    HBM budgeting: a global byte cap plus an optional per-tenant cap;
    over budget, eviction walks LRU order and takes LEAF blocks only
    (refs == 0 and no children — evicting an interior block would
    orphan its entire subtree), so a block pinned by a live slot or a
    session handle is never dropped.  Session handles
    (session_store/session_release) pin a (tenant, sid) chain between
    turns; the PR 10 SessionTable's lease expiry / demotion hooks
    release them.

    Mirrors serving_prefix_{hit,miss}_tokens_total{tenant} counters and
    the prefix_cache_bytes gauge into the registry so the PR 11/12
    observability planes see reuse as a first-class signal.

    Single-threaded like the decoder that owns it (pump runs on the
    event engine); shareable across decoders of the SAME geometry
    (bind() enforces layout agreement)."""

    def __init__(self, block_tokens: int = 32,
                 max_bytes: int | None = 512 << 20,
                 tenant_max_bytes: int | None = None,
                 name: str = "prefix", registry=None):
        self.block_tokens = int(block_tokens)
        if self.block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.tenant_max_bytes = int(tenant_max_bytes) \
            if tenant_max_bytes else None
        self.name = str(name)
        # one OrderedDict is both storage and LRU order (oldest-
        # touched first; eviction walks from the front, touch is
        # move_to_end) — bounded by eviction itself (budget caps)
        from collections import OrderedDict
        self._nodes: OrderedDict = OrderedDict()
        self._tenant_bytes: dict = {}
        self._sessions: dict = {}       # (tenant, sid) -> [keys]
        self.bytes_used = 0
        self._layout = None
        # paged mode (ISSUE 15): when a paged decoder binds this cache
        # it attaches its BlockPool — nodes then hold pool block ids
        # instead of row arrays, insert/evict move refcounts instead of
        # bytes, and install_chain writes shipped rows straight into
        # pool blocks
        self._pool = None
        self._dense_bound = False
        # tiered KV (ISSUE 17): an attached HostBlockStore turns
        # eviction of pool-resident blocks into DEMOTION (rows copy to
        # host, the chain key survives) and brings the AsyncPromoter's
        # prefetch/promote seam online — see attach_host_store
        self._host = None
        self._promoter = None
        # KV memory ledger (ISSUE 20): when attached, dense inserts/
        # evictions charge the device tier directly (paged bytes are
        # the pool's to report), double-releases become recorded
        # violations, and the auditor reads this cache for the
        # pinned-vs-evictable split
        self._ledger = None
        from .observe.metrics import MirroredStats, default_registry
        self._registry = registry or default_registry()
        self.stats = MirroredStats(
            {"hits": 0, "misses": 0, "hit_tokens": 0, "miss_tokens": 0,
             "inserts": 0, "evictions": 0, "insert_refused": 0,
             "session_handles": 0, "session_released": 0,
             "demoted": 0, "promoted": 0},
            metric="prefix_cache_events_total",
            help="prefix KV cache events by kind",
            registry=self._registry,
            skip=("hit_tokens", "miss_tokens"))
        self._gauge_bytes = self._registry.gauge(
            "prefix_cache_bytes",
            "bytes pinned by cached prefix KV blocks",
            labels={"cache": self.name})
        self._gauge_blocks = self._registry.gauge(
            "prefix_cache_blocks", "cached prefix KV blocks",
            labels={"cache": self.name})
        self._token_counters: dict = {}

    # -- binding -----------------------------------------------------------
    def bind(self, layout: tuple, paged: bool = False) -> None:
        """Record (and enforce) the storage layout this cache holds:
        decoders sharing a cache must agree on (layers, kv heads, head
        dim, dtype, int8-ness, block size) or a hit would scatter rows
        of the wrong shape into a live slot.  `paged` records the
        binder's storage mode so dense and paged decoders can never
        mix on one cache regardless of construction order (a dense
        node's rows and a paged node's pool id are mutually
        unreadable)."""
        if self._layout is None:
            self._layout = tuple(layout)
        elif self._layout != tuple(layout):
            raise ValueError(
                f"prefix cache {self.name!r} already bound to layout "
                f"{self._layout}, decoder wants {tuple(layout)}")
        self._dense_bound = self._dense_bound or not paged

    @property
    def layout(self) -> tuple | None:
        """The bound storage layout — the geometry handshake the
        disaggregated KV transfer carries (ISSUE 14): a prefill
        runtime's transfer declares its donor layout and the decode
        side refuses a mismatch before any row lands."""
        return self._layout

    # -- paged storage (ISSUE 15) ------------------------------------------
    @property
    def paged(self) -> bool:
        return self._pool is not None

    @property
    def pool(self):
        """The attached BlockPool, or None — what a second paged
        decoder sharing this cache adopts at construction."""
        return self._pool

    def attach_pool(self, pool) -> None:
        """Bind this cache to a paged decoder's BlockPool: cached
        blocks become refcounted pool residents.  One pool per cache —
        decoders sharing a paged cache must share the pool (they
        already must share a geometry via bind())."""
        if self._pool is not None and self._pool is not pool:
            raise ValueError(
                f"prefix cache {self.name!r} is already attached to "
                f"pool {self._pool.name!r}")
        if self._dense_bound:
            # order-independent twin of the dense-decoder-refuses-
            # paged-cache check: a dense decoder bound FIRST would
            # later insert() rowful nodes a paged hit cannot alias
            # (pool_id None), crashing the pump instead of failing
            # loudly here at construction
            raise ValueError(
                f"prefix cache {self.name!r} is bound by a dense "
                f"decoder; dense and paged decoders cannot share a "
                f"cache")
        if self._pool is None and self._nodes:
            raise ValueError(
                f"prefix cache {self.name!r} holds dense blocks; "
                f"cannot switch to paged storage mid-flight")
        self._pool = pool
        if self._ledger is not None:
            pool.attach_ledger(self._ledger)

    def attach_ledger(self, ledger) -> None:
        """Wire the KV memory ledger through every tier this cache
        fronts: the pool reports device transitions, the host store
        reports demote/evict/promote, and the cache itself reports
        dense bytes + double-release violations.  One call covers the
        whole stack whichever attach order the caller used."""
        self._ledger = ledger
        if ledger is None:
            return
        ledger.attach_cache(self)
        if self._pool is not None:
            self._pool.attach_ledger(ledger)
        if self._host is not None:
            self._host.attach_ledger(ledger)

    def attach_host_store(self, store, promoter=None) -> None:
        """Bring the host KV tier online (ISSUE 17): pool-resident
        blocks DEMOTE into `store` instead of vanishing when LRU
        pressure or the SessionTable's demotion wheel evicts them, and
        the returned promoter's prefetch/promote_for seam re-lands
        them ahead of the prompts that need them.  Paged caches only —
        a dense node's rows never shared a pool geometry to begin
        with (offload them is a different, uninteresting copy)."""
        if self._dense_bound:
            raise ValueError(
                f"prefix cache {self.name!r} is bound by a dense "
                f"decoder; the host tier offloads pool blocks")
        if self._host is not None and self._host is not store:
            raise ValueError(
                f"prefix cache {self.name!r} already has host store "
                f"{self._host.name!r}")
        self._host = store
        if self._ledger is not None:
            store.attach_ledger(self._ledger)
        if self._promoter is None:
            if promoter is None:
                from .serving_tiered import AsyncPromoter
                promoter = AsyncPromoter(self, store,
                                         registry=self._registry)
            self._promoter = promoter

    @property
    def host_store(self):
        return self._host

    @property
    def promoter(self):
        return self._promoter

    @property
    def tiered(self) -> bool:
        return self._host is not None

    @property
    def promotions_ready(self) -> bool:
        """Hot-path probe: staged async promotions are waiting for
        poll_promotions() (checked every admit round)."""
        return self._promoter is not None and self._promoter.ready

    def prefetch(self, tenant: str, tokens) -> int:
        """Non-blocking promotion kick for this prompt's
        host-resident chain tail (admission probes, session touches,
        the disagg client's submit).  No-op without a host tier."""
        if self._promoter is None:
            return 0
        return self._promoter.prefetch(tenant, tokens)

    def poll_promotions(self) -> int:
        """Land staged async promotions (event loop only)."""
        if self._promoter is None:
            return 0
        return self._promoter.poll()

    def promote_for(self, tenant: str, tokens) -> int:
        """Admit-time sync fallback: ensure this prompt's promotable
        chain tail is device-resident before the probe runs."""
        if self._promoter is None:
            return 0
        return self._promoter.promote_for(tenant, tokens)

    def insert_block(self, tenant: str, parent: str, key: str,
                     pool_id: int) -> bool:
        """Paged insert: the harvest path's zero-copy registration —
        retain one pool ref on `pool_id` and record the key.  The
        slot's own block BECOMES the cache entry; no rows move.
        Same budget/refusal semantics as insert()."""
        tenant = str(tenant or "default")
        if key in self._nodes:
            self._nodes.move_to_end(key)
            return True
        self._pool.retain([pool_id])
        node = _PrefixBlock(key, parent, tenant, None, None,
                            self._pool.block_nbytes,
                            pool_id=int(pool_id))
        self._nodes[key] = node
        parent_node = self._nodes.get(parent)
        if parent_node is not None:
            parent_node.children.add(key)
        self.bytes_used += node.nbytes
        self._tenant_bytes[tenant] = \
            self._tenant_bytes.get(tenant, 0) + node.nbytes
        self.stats["inserts"] += 1
        self._evict_to_budget(tenant)
        if key not in self._nodes:      # budget evicted the newcomer
            self.stats["insert_refused"] += 1
            self._publish_gauges()
            return False
        self._publish_gauges()
        return True

    def block_rows(self, node) -> tuple:
        """(per-layer K leaves, per-layer V leaves) of a cached block
        in the storage layout — dense nodes carry their own rows,
        paged nodes read the pool (device-side slice views; the wire
        shipper host-copies them)."""
        if node.pool_id is not None:
            return self._pool.block_rows(node.pool_id)
        return node.k_rows, node.v_rows

    def wire_layout(self) -> tuple:
        """The layout as wire-safe string fields (what
        transport.wire.encode_kv_transfer ships)."""
        return tuple(str(f) for f in (self._layout or ()))

    def layout_compatible(self, fields) -> bool:
        """True when a transfer's declared layout fields match this
        cache's bound layout (string-compared: the fields crossed a
        text-semantics wire)."""
        return self._layout is not None and \
            tuple(str(f) for f in fields) == self.wire_layout()

    # -- disaggregated KV admit (ISSUE 14) ----------------------------------
    def install_chain(self, tenant: str, tokens, start_block: int,
                      blocks) -> int:
        """Install shipped chain blocks [start_block, start_block +
        len(blocks)) of `tokens` into this cache — the decode-side KV
        admit path of the disaggregated split.  Keys are recomputed
        locally from the tokens (content-addressed: the hash chain IS
        the handle, nothing but indices crosses for blocks the decode
        side already holds).  Rows must be in this cache's storage
        layout; host ndarrays are fine — a hit's copy-in concat
        device-puts the admitted chain as one transfer per layer
        (serving_disagg installs owned host copies, deliberately NOT
        per-leaf device_puts on the event loop).  Returns
        the number of blocks newly resident (already-cached keys count
        — the transfer confirmed them); stops early when the byte
        budget refuses an insert, so children never dangle."""
        tokens = [int(t) for t in tokens]
        count = min(len(tokens) // self.block_tokens,
                    start_block + len(blocks))
        if count <= start_block:
            return 0
        keys = self.keys_for(tenant,
                             tokens[:count * self.block_tokens])
        for entry in blocks[:count - start_block]:
            self._check_block_geometry(entry)
        parent = keys[start_block - 1] if start_block else ""
        installed = 0
        if self.paged:
            # paged landing (ISSUE 15): the wire rows write STRAIGHT
            # into freshly allocated pool blocks — one scatter per
            # layer for the whole chain — and the cache records the
            # ids.  The later prefix-admit is then a pure table edit:
            # the transferred bytes land exactly once.  The alloc refs
            # are ours; insert_block retains its own, so releasing at
            # the end leaves cache-held blocks at refs 1 and refused
            # ones free.
            entries = blocks[:count - start_block]
            ids = self._pool.alloc_blocks(len(entries),
                                          tenant=tenant)
            layers = int(self._layout[0])
            k_layers = [_stack_block_leaves(
                [entry["k"][i] for entry in entries])
                for i in range(layers)]
            v_layers = [_stack_block_leaves(
                [entry["v"][i] for entry in entries])
                for i in range(layers)]
            self._pool.write_blocks(ids, k_layers, v_layers)
            for j in range(start_block, count):
                if not self.insert_block(tenant, parent, keys[j],
                                         ids[j - start_block]):
                    break
                installed += 1
                parent = keys[j]
            self._pool.release_blocks(ids, tenant=tenant)
            if installed and self._ledger is not None:
                self._ledger.event("install", installed)
            return installed
        for j in range(start_block, count):
            entry = blocks[j - start_block]
            if not self.insert(tenant, parent, keys[j],
                               entry["k"], entry["v"]):
                break
            installed += 1
            parent = keys[j]
        return installed

    def _check_block_geometry(self, entry) -> None:
        if self._layout is None:
            raise ValueError("install into an unbound prefix cache")
        check_block_geometry(self._layout, self.block_tokens, entry)

    # -- lookup ------------------------------------------------------------
    def keys_for(self, tenant: str, tokens) -> list:
        return prefix_chain_keys(tenant, tokens, self.block_tokens)

    def has(self, key: str) -> bool:
        return key in self._nodes

    def nodes(self, keys) -> list:
        return [self._nodes[key] for key in keys]

    def match(self, tenant: str, tokens,
              limit: int | None = None) -> tuple:
        """(chain keys, hit tokens) of the longest cached prefix, over
        at most `limit` tokens (callers cap at len-1 so at least one
        suffix token remains to produce the first output).  Pure probe:
        no refcounts, no LRU movement, no metrics — what the admission
        estimator uses (ISSUE 13 satellite)."""
        cap = len(tokens) if limit is None else min(limit, len(tokens))
        count = max(0, cap) // self.block_tokens
        if count == 0:
            return [], 0
        keys = self.keys_for(tenant, tokens[:count * self.block_tokens])
        hit = 0
        for key in keys:
            if key not in self._nodes:
                break
            hit += 1
        return keys[:hit], hit * self.block_tokens

    def acquire(self, tenant: str, tokens,
                limit: int | None = None) -> tuple:
        """match() + pin: refs++ on every chain node (released by the
        owner at retire), LRU touch, and the per-tenant hit/miss token
        counters the bench and the SLO planes read."""
        keys, hit = self.match(tenant, tokens, limit)
        for key in keys:
            self._nodes[key].refs += 1
            self._nodes.move_to_end(key)
        self.stats["hits" if hit else "misses"] += 1
        self.stats["hit_tokens"] += hit
        self.stats["miss_tokens"] += len(tokens) - hit
        self._count_tokens(tenant, hit, len(tokens) - hit)
        return keys, hit

    def release(self, keys) -> None:
        for key in keys:
            node = self._nodes.get(key)
            if node is None:
                continue        # evicted/purged since pin: legitimate
            if node.refs > 0:
                node.refs -= 1
            elif self._ledger is not None:
                # an unpin of an unpinned resident block is a paired-
                # release bug somewhere upstream — record it with the
                # chain key so the postmortem names the chain
                self._ledger.violation(
                    "double-release", tenant=node.tenant,
                    chain_key=key,
                    detail=f"cache {self.name}: refs already 0")

    def evictable_bytes(self, tenant=None) -> int:
        """Bytes held by unpinned (refs == 0) cached blocks — the
        ledger's pinned-vs-evictable split reads this lazily (interior
        blocks count too: they become evictable leaves as their
        subtrees drain)."""
        tenant = None if tenant is None else str(tenant or "default")
        return sum(node.nbytes for node in self._nodes.values()
                   if node.refs == 0 and
                   (tenant is None or node.tenant == tenant))

    def hit_rate(self) -> float:
        total = self.stats["hit_tokens"] + self.stats["miss_tokens"]
        return self.stats["hit_tokens"] / total if total else 0.0

    def _count_tokens(self, tenant: str, hit: int, miss: int) -> None:
        tenant = str(tenant or "default")
        counters = self._token_counters.get(tenant)
        if counters is None:
            counters = tuple(self._registry.counter(
                f"serving_prefix_{kind}_tokens_total",
                f"prompt tokens {kind} by the prefix KV cache",
                labels={"cache": self.name, "tenant": tenant})
                for kind in ("hit", "miss"))
            self._token_counters[tenant] = counters
        if hit:
            counters[0].inc(hit)
        if miss:
            counters[1].inc(miss)

    # -- insertion / eviction ----------------------------------------------
    def insert(self, tenant: str, parent: str, key: str,
               k_rows, v_rows) -> bool:
        """Register one block (per-layer K/V leaves).  Content-
        addressed: an existing key is just touched.  Returns False when
        the byte budgets refused it (everything evictable was already
        evicted and the budget still doesn't fit) — the caller must
        stop its chain there, or children would dangle."""
        tenant = str(tenant or "default")
        if key in self._nodes:
            self._nodes.move_to_end(key)
            return True
        nbytes = L.kv_rows_nbytes(k_rows) + L.kv_rows_nbytes(v_rows)
        node = _PrefixBlock(key, parent, tenant, k_rows, v_rows, nbytes)
        self._nodes[key] = node
        parent_node = self._nodes.get(parent)
        if parent_node is not None:
            parent_node.children.add(key)
        self.bytes_used += nbytes
        self._tenant_bytes[tenant] = \
            self._tenant_bytes.get(tenant, 0) + nbytes
        self.stats["inserts"] += 1
        if self._ledger is not None:
            # dense mode: the cache IS the device-tier truth source
            # (paged bytes are charged by the pool at alloc)
            self._ledger.device_delta(tenant, nbytes, "cache_insert")
        self._evict_to_budget(tenant)
        if key not in self._nodes:      # budget evicted the newcomer
            self.stats["insert_refused"] += 1
            self._publish_gauges()
            return False
        self._publish_gauges()
        return True

    def tenant_bytes(self, tenant: str) -> int:
        return self._tenant_bytes.get(str(tenant or "default"), 0)

    def _over_budget(self, tenant: str) -> str | None:
        if self.tenant_max_bytes is not None and \
                self.tenant_bytes(tenant) > self.tenant_max_bytes:
            return tenant
        if self.max_bytes is not None and \
                self.bytes_used > self.max_bytes:
            return ""                   # global breach: any tenant
        return None

    def _evict_to_budget(self, tenant: str) -> None:
        """Evict LRU-first LEAVES (unpinned, childless) until budgets
        hold.  A pass that frees nothing ends the loop: pinned bytes
        may legitimately exceed the budget (a block pinned by a live
        slot is never evicted), and interior blocks become leaves as
        their subtrees drain on later passes."""
        while True:
            scope = self._over_budget(tenant)
            if scope is None:
                return
            victim = None
            for node in self._nodes.values():
                if node.refs or node.children:
                    continue
                if scope and node.tenant != scope:
                    continue
                victim = node
                break
            if victim is None:
                # all-pinned pressure (ISSUE 17 satellite): every
                # evictable leaf is session-pinned.  With a host tier
                # attached, route the pressure into DEMOTION — unpin
                # and demote the oldest session's chain, then retry —
                # instead of refusing the insert outright.
                if self._host is not None and \
                        self._demote_oldest_session(scope):
                    continue
                return
            self._evict(victim)

    def _demote_oldest_session(self, scope: str | None) -> int:
        """Demote the oldest session handle (scope-matched on a
        tenant breach) to the host tier; returns blocks freed from
        the device (0 ends the caller's pressure loop — remaining
        pins belong to live requests, not idle sessions)."""
        for tenant, sid in self._sessions:
            if scope and tenant != scope:
                continue
            return self.demote_sessions([(tenant, sid)])
        return 0

    def demote_sessions(self, pairs) -> int:
        """Batch demotion matching SessionTable's on_expired /
        on_demoted callback shape ([(tenant, sid), ...]) — the
        expiry/demotion wheel's KV trigger (ISSUE 17).  Releases each
        session's pin, then walks its chain LEAF→ROOT demoting blocks
        to the host tier; a block still pinned by a live request or
        shared with another chain ends the walk (it stays device-
        resident — demotion never breaks a reader).  Without a host
        store this degrades to release_sessions (unpin only).
        Returns device blocks demoted."""
        demoted = 0
        for tenant, sid in pairs:
            keys = self._sessions.pop(
                (str(tenant or "default"), str(sid)), None)
            if keys is None:
                continue
            self.release(keys)
            self.stats["session_released"] += 1
            if self._ledger is not None:
                self._ledger.event("session_demote")
            if self._host is None:
                continue
            for key in reversed(keys):
                node = self._nodes.get(key)
                if node is None:
                    continue    # already demoted/evicted; walk on up
                if node.refs or node.children:
                    break       # pinned or shared below: stays hot
                self._evict(node)
                demoted += 1
        return demoted

    def _evict(self, node: _PrefixBlock) -> None:
        del self._nodes[node.key]
        if node.pool_id is not None:
            # demote-not-forget (ISSUE 17): with a host tier attached
            # the rows copy down BEFORE the pool ref goes — the chain
            # key survives in HostBlockStore and the promoter can
            # re-land it; only a host-budget refusal makes this a
            # true eviction
            if self._host is not None:
                k_rows, v_rows = self._pool.block_rows(node.pool_id)
                if self._host.put_from_device(
                        node.tenant, node.parent, node.key,
                        k_rows, v_rows, node.nbytes):
                    self.stats["demoted"] += 1
            # paged: the cache's ref goes; the pool block frees when
            # no slot table still aliases it
            self._pool.release_blocks([node.pool_id],
                                      tenant=node.tenant)
        elif self._ledger is not None:
            self._ledger.device_delta(node.tenant, -node.nbytes,
                                      "cache_evict")
        parent = self._nodes.get(node.parent)
        if parent is not None:
            parent.children.discard(node.key)
        self.bytes_used -= node.nbytes
        remaining = self._tenant_bytes.get(node.tenant, 0) - node.nbytes
        if remaining > 0:
            self._tenant_bytes[node.tenant] = remaining
        else:
            self._tenant_bytes.pop(node.tenant, None)
        self.stats["evictions"] += 1
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        self._gauge_bytes.set(self.bytes_used)
        self._gauge_blocks.set(len(self._nodes))

    def __len__(self) -> int:
        return len(self._nodes)

    # -- session-resident conversation KV (ISSUE 13 / PR 10 residue c) -----
    def session_store(self, tenant: str, sid: str, tokens) -> tuple:
        """Pin the longest cached chain for `tokens` under a
        (tenant, sid) handle — the finished turn's history, registered
        so the session's blocks survive eviction between turns.
        Replaces (and releases) the session's previous handle.
        Returns (leaf key | None, pinned tokens)."""
        self.session_release(tenant, sid)
        keys, hit = self.match(tenant, tokens)
        if not keys:
            return None, 0
        for key in keys:
            self._nodes[key].refs += 1
            self._nodes.move_to_end(key)
        self._sessions[(str(tenant or "default"), str(sid))] = keys
        self.stats["session_handles"] += 1
        if self._ledger is not None:
            self._ledger.event("session_pin")
        return keys[-1], hit

    def session_release(self, tenant: str, sid: str) -> bool:
        """Drop a session's pin (SessionTable lease expiry / demotion
        path): the chain stays cached but becomes evictable."""
        keys = self._sessions.pop(
            (str(tenant or "default"), str(sid)), None)
        if keys is None:
            return False
        self.release(keys)
        self.stats["session_released"] += 1
        return True

    def session_tokens(self, tenant: str, sid: str) -> int:
        keys = self._sessions.get(
            (str(tenant or "default"), str(sid)), ())
        return len(keys) * self.block_tokens

    def release_sessions(self, keys) -> None:
        """Batch form matching SessionTable's on_expired/on_demoted
        callback shape: [(tenant, sid), ...]."""
        for tenant, sid in keys:
            self.session_release(tenant, sid)

    def sessions(self) -> list:
        """Live session handles, oldest-pinned first:
        [(tenant, sid), ...] — the drain migrator's enumeration
        surface (ISSUE 19)."""
        return list(self._sessions)

    def purge(self, demote: bool = True) -> int:
        """Evict everything evictable: release every session pin,
        then strip the tree leaf-first until only request-pinned
        nodes remain.  The drain endgame (ISSUE 19): after migration
        shipped the chains, the source purges with demote=False — a
        host-tier copy of state another runtime now owns would be
        dead weight — and the drain leak audit asserts the pool
        reaches zero live blocks.  Returns nodes evicted."""
        for tenant, sid in list(self._sessions):
            self.session_release(tenant, sid)
        host = self._host
        if not demote:
            self._host = None
        evicted = 0
        try:
            progress = True
            while progress:
                progress = False
                for node in list(self._nodes.values()):
                    if node.refs or node.children:
                        continue
                    self._evict(node)
                    evicted += 1
                    progress = True
        finally:
            self._host = host
        return evicted


def _slot_attention(layer, config: LlamaConfig, x, cos, sin,
                    k_cache, v_cache, lengths, write_mask):
    """One-token attention for all slots at per-slot positions.

    x: [S, 1, dim]; k_cache/v_cache: [S, H_kv, T, D]; lengths: [S] —
    tokens already in each slot's context (the new token's position).
    write_mask: [S] bool — only these slots commit their K/V write.  A
    mid-prefill slot's stale `lengths` entry points INTO the prompt
    region its extend chunks are writing; an unmasked write would
    corrupt it from the decode scan running between chunks.

    The cache's time axis T is NOT max_seq: the decoder allocates the
    smallest block multiple covering the longest active context and
    grows/shrinks the allocation between rounds (see
    ContinuousDecoder._fit_caches).  Decode is HBM-bound, so the step
    streams exactly the bytes the workload needs — an in-program
    slice of a max_seq cache was measured to MATERIALIZE the slice
    per layer per step (scatter output feeding a dot can't fuse),
    tripling the attention bytes."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q, k, v = _project_qkv(layer, config, x)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)

    # write this token's K/V at each slot's own cursor — as a masked
    # select, not a scatter: a per-slot-index scatter defeats XLA's
    # in-place/fusion analysis inside the scan, and the full-cache
    # select was measured ~12% faster per step at the serving shape
    hit = (jnp.arange(k_cache.shape[2])[None, None, :, None] ==
           lengths[:, None, None, None]) & \
        write_mask[:, None, None, None]             # [S,1,T,1]
    k_cache = jnp.where(hit, k[:, :, 0][:, :, None], k_cache)
    v_cache = jnp.where(hit, v[:, :, 0][:, :, None], v_cache)

    # attend over each slot's valid prefix (inclusive of the new token).
    # GQA via a grouped einsum against the SHARED KV — materializing
    # repeated caches (jnp.repeat) costs group× HBM and halves the slot
    # capacity that fits on a chip.  Scores run as bf16×bf16 MXU
    # matmuls with f32 ACCUMULATION (preferred_element_type) — an
    # explicit f32 upcast of the cache would double the HBM bytes of
    # the read, which is the dominant cost of the step.
    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group, num_q, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    if ATTENTION_IMPL == "online":
        out = _online_decode_attention(q_grouped, k_cache, v_cache,
                                       lengths, scale)
    elif ATTENTION_IMPL == "vpu":
        # broadcast-multiply + reduce instead of MXU matmuls: the
        # per-(slot, kv-head) matmul is M=group (tiny) — issue-rate
        # bound on the MXU; the VPU variant streams the same bytes as
        # fused elementwise reductions
        valid = (jnp.arange(k_cache.shape[2])[None] <=
                 lengths[:, None])[:, None, None]        # [S,1,1,T]
        q_sq = q_grouped[:, :, :, 0]                     # [S,kv,G,D]
        scores = jnp.sum(
            q_sq[:, :, :, None, :].astype(jnp.float32) *
            k_cache[:, :, None, :, :].astype(jnp.float32),
            axis=-1) * scale                             # [S,kv,G,T]
        scores = jnp.where(valid, scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1)
        out = jnp.sum(
            weights[..., None] *
            v_cache[:, :, None, :, :].astype(jnp.float32),
            axis=3)[:, :, :, None, :]                    # [S,kv,G,1,D]
    else:
        valid = (jnp.arange(k_cache.shape[2])[None] <=
                 lengths[:, None])[:, None, None, None]  # [S,1,1,1,T]
        scores = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_cache,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid, scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
        out = jnp.einsum("skgqt,sktd->skgqd", weights, v_cache,
                         preferred_element_type=jnp.float32)
    out = out.reshape(slots_n, num_heads, num_q, head_dim).astype(x.dtype)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_cache, v_cache)


def _kv_planes(cache, dtype):
    """(dot-operand values, fold scale or None) for a main-cache leaf.
    int8 caches (layers.quantize_kv_cache) keep the int8 buffer as the
    dot operand — the convert fuses, nothing re-materializes — and
    hand back the per-(slot, head, position) scale for folding into
    scores (K) and weights (V): the same fold discipline as
    layers.mha's quantized cross-KV, at serving's per-position
    grain."""
    if isinstance(cache, dict):
        return cache["q"].astype(dtype), cache["s"]
    return cache, None


def _cache_time(cache) -> int:
    """Time-axis extent of a main-cache leaf (array or int8 dict)."""
    return (cache["q"] if isinstance(cache, dict) else cache).shape[2]


def _grouped_block_attention(layer, config: LlamaConfig, x, cos, sin,
                             k_cache, v_cache, k_side, v_side,
                             entry_lengths, lengths, write_index,
                             side_valid):
    """Shared core of the block-KV decode attentions: project QKV for
    the [S, W] block at per-slot positions `lengths`, write this
    block's K/V into the side buffers at `write_index`, and attend
    over read-only main cache (positions < entry_lengths — causally
    visible to every query) + side entries selected by the caller's
    `side_valid` mask ([S,1,1,W,P]-broadcastable).  The ONE place the
    greedy numerics live: the plain scan (W=1) and the speculative
    verify (W=1+k) must stay the same computation or the
    greedy-equivalence invariant breaks.  int8 main caches
    (layers.quantize_kv_cache) keep the int8 buffer as the dot operand
    and fold their per-(slot, head, position) scales into the main
    scores (K) and weights (V); the side buffers stay in the compute
    dtype (they are one round wide — quantizing them would save
    nothing and cost an int8 round-trip every step)."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q, k, v = _project_qkv(layer, config, x)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)
    k_side = jax.lax.dynamic_update_slice_in_dim(k_side, k, write_index,
                                                 axis=2)
    v_side = jax.lax.dynamic_update_slice_in_dim(v_side, v, write_index,
                                                 axis=2)

    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group, num_q, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    k_main, k_fold = _kv_planes(k_cache, x.dtype)
    v_main, v_fold = _kv_planes(v_cache, x.dtype)
    main_t = k_main.shape[2]
    main_valid = (jnp.arange(main_t)[None] <
                  entry_lengths[:, None])[:, None, None, None]
    scores_main = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_main,
                             preferred_element_type=jnp.float32) * scale
    if k_fold is not None:
        scores_main = scores_main * k_fold[:, :, None, None, :]
    scores_side = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_side,
                             preferred_element_type=jnp.float32) * scale
    scores = jnp.concatenate(
        [jnp.where(main_valid, scores_main, -1e30),
         jnp.where(side_valid, scores_side, -1e30)], axis=-1)
    weights = jax.nn.softmax(scores, axis=-1)
    w_main = weights[..., :main_t]
    if v_fold is not None:
        w_main = w_main * v_fold[:, :, None, None, :]
    out = jnp.einsum("skgqt,sktd->skgqd", w_main.astype(v_main.dtype),
                     v_main, preferred_element_type=jnp.float32) + \
        jnp.einsum("skgqt,sktd->skgqd",
                   weights[..., main_t:].astype(v_side.dtype), v_side,
                   preferred_element_type=jnp.float32)
    out = out.reshape(slots_n, num_heads, num_q, head_dim).astype(x.dtype)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_side, v_side)


def _slot_attention_block(layer, config: LlamaConfig, x, cos, sin,
                          k_cache, v_cache, k_side, v_side,
                          entry_lengths, lengths, step_index):
    """Block-KV decode attention: the main cache is read-only (tokens
    [0, entry_lengths) per slot); this round's tokens live in the side
    buffers at scan indices [0, step_index].  The new token's K/V is
    written to side[:, :, step_index] — a slot-uniform index, so XLA
    keeps the update in place instead of rewriting the whole cache."""
    side_positions = jnp.arange(k_side.shape[2])
    side_valid = ((side_positions[None] <= step_index) &
                  (side_positions[None] <
                   (lengths - entry_lengths + 1)[:, None])
                  )[:, None, None, None]
    return _grouped_block_attention(layer, config, x, cos, sin,
                                    k_cache, v_cache, k_side, v_side,
                                    entry_lengths, lengths, step_index,
                                    side_valid)


def _slot_attention_spec(layer, config: LlamaConfig, x, cos, sin,
                         k_cache, v_cache, k_side, v_side, pos_side,
                         entry_lengths, lengths, base):
    """Widened block-KV attention for the speculative verify step: `x`
    carries w = 1 + speculate_k tokens per slot at absolute positions
    lengths + [0, w).  The round's tokens live in the side buffers
    tagged with their ABSOLUTE cache positions (`pos_side` — rejected
    drafts are invalidated to an out-of-bounds position and never
    attended), so causality inside and across verify blocks is one
    comparison: pos_side <= q_pos."""
    width = x.shape[1]
    q_pos = lengths[:, None] + jnp.arange(width)[None]       # [S, w]
    side_valid = (pos_side[:, None, :] <=
                  q_pos[:, :, None])[:, None, None]      # [S,1,1,w,P]
    return _grouped_block_attention(layer, config, x, cos, sin,
                                    k_cache, v_cache, k_side, v_side,
                                    entry_lengths, lengths, base,
                                    side_valid)


def _fuse_decode_projections(params):
    """Opt-in serving transform: concatenate each layer's q/k/v weight
    matrices into one [dim, (Hq+2Hkv)*D] matmul and gate/up into one
    [dim, 2*ffn].  The decode step's activations are [S, 1, dim], so
    its ~14 projections per layer are tiny-M matmuls whose cost is
    issue/scheduling, not FLOPs — the W8 wash (see quantize_linear)
    showed weight BYTES aren't the binding constraint, so this halves
    the op COUNT instead.  Measured r5 at the 1b/256-slot shape
    (tools/ab_w8.py AB_MODE=fuse): device step 11.27 → 11.68 ms,
    +3.6% — a DEAD END on this toolchain (XLA already schedules the
    separate matmuls; the fused output's split costs more than the
    saved issues).  Kept opt-in as the recorded negative result, like
    serving's other measured dead ends.

    Tree shape after the transform: attn gains a "qkv" copy while
    q/k/v REMAIN (the prefill/extend attention goes through
    layers.mha, which needs them; _param_bytes excludes the duplicate
    so traffic stats stay honest); gate/up are REPLACED by "gate_up"
    outright, because every FFN path routes through llama_ffn →
    _swiglu, which prefers the fused form.  Biases are asserted
    absent — silently dropping one would corrupt outputs.  Outputs
    are not bit-identical to the unfused step (different f32
    accumulation tiling), so this stays opt-in and A/B-gated."""
    new_layers = []
    for layer in params["layers"]:
        layer = dict(layer)
        attn = dict(layer["attn"])
        # hard errors, not asserts: python -O strips asserts and a
        # silently-dropped bias corrupts every output (ADVICE r5)
        if any("b" in attn[k] for k in ("q", "k", "v")):
            raise ValueError(
                "fuse_projections drops linear biases; refusing")
        attn["qkv"] = {"w": jnp.concatenate(
            [attn["q"]["w"], attn["k"]["w"], attn["v"]["w"]], axis=1)}
        layer["attn"] = attn
        if "gate" in layer:
            if "b" in layer["gate"] or "b" in layer["up"]:
                raise ValueError(
                    "fuse_projections drops FFN biases; refusing")
            layer["gate_up"] = {"w": jnp.concatenate(
                [layer["gate"]["w"], layer["up"]["w"]], axis=1)}
            del layer["gate"], layer["up"]
        new_layers.append(layer)
    return {**params, "layers": new_layers}


def _project_qkv(layer, config: LlamaConfig, x):
    """q/k/v for the decode step: one fused matmul when the layer
    carries the _fuse_decode_projections form, else the canonical
    three."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    attn = layer["attn"]
    if "qkv" in attn:
        qkv = L.linear(attn["qkv"], x)
        q_dim = num_heads * config.head_dim
        kv_dim = num_kv * config.head_dim
        q = L._split_heads(qkv[..., :q_dim], num_heads)
        k = L._split_heads(qkv[..., q_dim:q_dim + kv_dim], num_kv)
        v = L._split_heads(qkv[..., q_dim + kv_dim:], num_kv)
    else:
        q = L._split_heads(L.linear(attn["q"], x), num_heads)
        k = L._split_heads(L.linear(attn["k"], x), num_kv)
        v = L._split_heads(L.linear(attn["v"], x), num_kv)
    return q, k, v


def _token_block_argmax(params, config: LlamaConfig, token_block,
                        attend):
    """Shared transformer pass over a [S, W] token block: `attend(i,
    layer, normed)` supplies each layer's attention output (and owns
    the cache-write strategy).  Returns the per-position argmax
    [S, W] — bf16 operand reads (an f32 UPCAST of the [dim, vocab]
    head would double the step's largest weight read), f32
    accumulation KEPT f32 into the argmax: rounding the logits to
    bf16 first can flip near-ties against the f32 oracle.  W is 1 for
    the plain decode step and 1 + speculate_k for the verify step."""
    x = L.embedding(params["embed"], token_block).astype(config.dtype)
    for i, layer in enumerate(params["layers"]):
        x = x + attend(i, layer, L.rms_norm(layer["ln_attn"], x))
        normed = L.rms_norm(layer["ln_mlp"], x)
        # dense SwiGLU or MoE per the config — MoE llama serves
        # through the same continuous-batching step
        x = x + llama_ffn(layer, config, normed)
    x = L.rms_norm(params["ln_out"], x)
    logits = L.linear_logits(params["lm_head"], x)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _build_step(config: LlamaConfig):
    """One decode iteration for every slot; jitted once, caches donated
    so the slot buffers update in place on device.  Params are an
    ARGUMENT, not a closure capture — captured trees get baked into the
    compiled program as constants (gigabytes for real checkpoints,
    duplicated per recompile).  Since ISSUE 7 the step does NOT return
    the entry tokens: deferred admits resolve from the admit program's
    own output at the next round's sync, so the decode scan carries
    nothing on behalf of prefill."""
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)

    def one_token(params, tokens, lengths, active, k_caches, v_caches):
        new_k, new_v = [], []

        def attend(i, layer, normed):
            attn_out, k_c, v_c = _slot_attention(
                layer, config, normed, cos, sin, k_caches[i],
                v_caches[i], lengths, active)
            new_k.append(k_c)
            new_v.append(v_c)
            return attn_out

        next_tokens = _token_block_argmax(params, config,
                                          tokens[:, None], attend)[:, 0]
        return next_tokens, new_k, new_v

    def step_k(params, tokens, lengths, active, budgets, k_caches,
               v_caches, num_steps, eos):
        """lax.scan of `num_steps` iterations; returns tokens emitted
        [K, S] plus the per-step active mask [K, S] (True where the
        emitted token is real output).  A slot retires INSIDE the scan
        the moment it emits `eos` or exhausts its `budgets` entry —
        retired slots stop growing their context and their later
        emissions are discarded by the host, so a request finishing at
        step 1 of a 32-step round no longer pollutes its cache or
        miscounts as useful work."""
        def body(carry, _):
            tokens, lengths, active, budgets, k_caches, v_caches = carry
            next_tokens, k_caches, v_caches = one_token(
                params, tokens, lengths, active, k_caches, v_caches)
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            budgets = jnp.where(active, budgets - 1, budgets)
            still = active & (budgets > 0) & (next_tokens != eos)
            return ((next_tokens, lengths, still, budgets, k_caches,
                     v_caches), (next_tokens, active))

        (tokens, lengths, active, budgets, k_caches, v_caches), \
            (emitted, emitted_active) = jax.lax.scan(
                body, (tokens, lengths, active, budgets, k_caches,
                       v_caches), None, length=num_steps)
        return (emitted, emitted_active, tokens, lengths,
                k_caches, v_caches)

    def step_k_block(params, tokens, lengths, active, budgets,
                     k_caches, v_caches, num_steps, eos):
        """Block-KV variant of step_k: the main caches stay READ-ONLY
        through the scan (closed over, never carried), this round's
        K/V land in [S, H, num_steps, D] side buffers at the scan
        index, and one per-slot merge runs after the scan.  Removes
        the per-step full-cache writes that made each step touch the
        KV ~4x (measured slope 37.9 us/T vs a 10.2 read-only floor).
        int8 main caches (kv_cache_dtype="int8") are read via the
        scale fold and the merge quantizes the side rows ONCE per
        round — the scan itself never touches int8 encode."""
        entry_lengths = lengths
        entry_active = active
        slots_n = tokens.shape[0]
        side_shape = (slots_n, config.num_kv_heads, num_steps,
                      config.head_dim)
        k_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]

        def body(carry, step_index):
            tokens, lengths, active, budgets, k_sides, v_sides = carry
            new_k, new_v = [], []

            def attend(i, layer, normed):
                attn_out, k_s, v_s = _slot_attention_block(
                    layer, config, normed, cos, sin, k_caches[i],
                    v_caches[i], k_sides[i], v_sides[i],
                    entry_lengths, lengths, step_index)
                new_k.append(k_s)
                new_v.append(v_s)
                return attn_out

            next_tokens = _token_block_argmax(
                params, config, tokens[:, None], attend)[:, 0]
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            budgets = jnp.where(active, budgets - 1, budgets)
            still = active & (budgets > 0) & (next_tokens != eos)
            return ((next_tokens, lengths, still, budgets, new_k,
                     new_v), (next_tokens, active))

        (tokens, lengths, active, budgets, k_sides, v_sides), \
            (emitted, emitted_active) = jax.lax.scan(
                body, (tokens, lengths, active, budgets, k_sides,
                       v_sides), jnp.arange(num_steps))

        # one merge per round: each slot's side tokens scatter into the
        # main cache at its round-entry offset.  Rows past a slot's
        # actual take are garbage landing at positions beyond its
        # length — dead cells, overwritten before they are ever
        # attended (same invariant as the admit scatter's padding).
        # Slots INACTIVE at round entry must not merge at all: a
        # mid-prefill slot's stale length points INTO the prompt its
        # extend chunks are writing (the same corruption the select
        # mode's write_mask guards against).
        merge_at = jnp.minimum(entry_lengths,
                               _cache_time(k_caches[0]) - num_steps)
        keep = entry_active[:, None, None, None]
        keep_s = entry_active[:, None, None]

        def merge(cache, side):
            if isinstance(cache, dict):
                quant = L.quantize_kv_cache(side)
                new_q = jax.vmap(
                    lambda row, srow, off: jax.lax.dynamic_update_slice(
                        row, srow, (0, off, 0)))(cache["q"], quant["q"],
                                                 merge_at)
                new_s = jax.vmap(
                    lambda row, srow, off: jax.lax.dynamic_update_slice(
                        row, srow, (0, off)))(cache["s"], quant["s"],
                                              merge_at)
                return {"q": jnp.where(keep, new_q, cache["q"]),
                        "s": jnp.where(keep_s, new_s, cache["s"])}
            updated = jax.vmap(
                lambda row, srow, off: jax.lax.dynamic_update_slice(
                    row, srow, (0, off, 0)))(cache, side, merge_at)
            return jnp.where(keep, updated, cache)

        new_k_caches = [merge(k_caches[i], k_sides[i])
                        for i in range(config.num_layers)]
        new_v_caches = [merge(v_caches[i], v_sides[i])
                        for i in range(config.num_layers)]
        return (emitted, emitted_active, tokens, lengths,
                new_k_caches, new_v_caches)

    return jax.jit(step_k_block if KV_WRITE == "block" else step_k,
                   static_argnames=("num_steps", "eos"),
                   donate_argnames=("k_caches", "v_caches"))


@functools.lru_cache(maxsize=16)
def _step_for(config: LlamaConfig, kv_write: str, attention_impl: str):
    """Process-wide cache of compiled step builders: decoders sharing
    a config share ONE jit object, so the XLA executables inside it
    (keyed by shapes / static args) are reused across instances —
    rebuilding a decoder, or building several in one process (tests,
    A/B tools, multi-tenant serving), pays no recompile.  Keyed on the
    module toggles too, so tools that flip serving.KV_WRITE /
    ATTENTION_IMPL (ab_decode_attention) still get the variant they
    set."""
    return _build_step(config)


# invalid side-buffer / context position: far past any legal cache
# index, so pos-based causal masks fail and scatter merges drop it
# (mode="drop") instead of corrupting a live row
_POS_INVALID = 1 << 30


def _spec_scan_body(config: LlamaConfig, cos, sin, k_spec: int,
                    ngram: int, params, eos, k_caches, v_caches,
                    entry_lengths, attention=None):
    """The speculative drafting/verify/acceptance scan body, shared
    VERBATIM by the dense (_build_spec_step) and paged
    (serving_paged._build_paged_spec_step) builders — like the
    attention bodies, ONE copy is what keeps the paged/dense
    bit-parity invariant safe from a fix landing on only one side.
    The builders differ only in how k_caches/v_caches are obtained
    (dense slot caches vs per-round pool gathers) and how the
    consumed side entries merge back at scan exit.

    `attention` is the verify attention seam (default
    _slot_attention_spec over slot-major caches); the paged pallas
    kernel path passes _kernel_attention_spec with k_caches/v_caches
    holding the raw pool leaves — the draft/accept machinery around
    it stays this one copy either way."""
    width = k_spec + 1
    if attention is None:
        attention = _slot_attention_spec
    slots_n = entry_lengths.shape[0]
    col = jnp.arange(width)[None]                        # [1, w]
    row = jnp.arange(slots_n)[:, None]                   # [S, 1]

    def draft(context, tokens, lengths):
        """Prompt-lookup drafts [S, k_spec]: match the last `ngram`
        tokens (the pending token + ngram-1 history tokens) at every
        history position, take the LATEST hit, and propose the tokens
        that followed it.  A miss proposes zeros — certain rejection,
        which costs nothing extra: the verify block runs at width
        1 + k_spec regardless, and acceptance never affects WHICH
        tokens are emitted, only how many per iteration."""
        ctx_len = context.shape[1]
        pos = jnp.arange(ctx_len)[None]                  # [1, C]
        hit = (pos >= ngram - 1) & (pos < lengths[:, None]) & \
            (context == tokens[:, None])
        for i in range(1, ngram):
            prev = jnp.take_along_axis(
                context, jnp.maximum(lengths[:, None] - i, 0), axis=1)
            # roll never wraps into the valid region: hit requires
            # pos >= ngram-1 >= i
            hit = hit & (jnp.roll(context, i, axis=1) == prev)
        # prefer the latest hit whose continuation is FULLY written
        # history (k real tokens follow it); fall back to the latest
        # with at least one — a frontier hit would draft unwritten
        # garbage and waste the verify width on certain rejections
        full = hit & (pos <= lengths[:, None] - 1 - k_spec)
        some = hit & (pos < lengths[:, None] - 1)
        best_full = jnp.max(jnp.where(full, pos, -1), axis=1)
        best_some = jnp.max(jnp.where(some, pos, -1), axis=1)
        best = jnp.where(best_full >= 0, best_full, best_some)  # [S]
        take = jnp.clip(best[:, None] + 1 + jnp.arange(k_spec)[None],
                        0, ctx_len - 1)
        drafts = jnp.take_along_axis(context, take, axis=1)
        return jnp.where(best[:, None] >= 0, drafts, 0)

    def body(carry, step_index):
        (tokens, lengths, active, budgets, context, k_sides,
         v_sides, pos_side) = carry
        drafts = draft(context, tokens, lengths)
        seq = jnp.concatenate([tokens[:, None], drafts], axis=1)
        base = step_index * width
        q_pos = lengths[:, None] + col                   # [S, w]
        # provisional: the whole block is live while it attends to
        # itself; rejected entries are invalidated after acceptance
        pos_side = jax.lax.dynamic_update_slice(pos_side, q_pos,
                                                (0, base))
        new_k, new_v = [], []

        def attend(i, layer, normed):
            attn_out, k_s, v_s = attention(
                layer, config, normed, cos, sin, k_caches[i],
                v_caches[i], k_sides[i], v_sides[i], pos_side,
                entry_lengths, lengths, base)
            new_k.append(k_s)
            new_v.append(v_s)
            return attn_out

        block_argmax = _token_block_argmax(params, config, seq,
                                           attend)      # [S, w]
        k_sides, v_sides = new_k, new_v
        # greedy acceptance: argmax after consuming seq[:j] must
        # reproduce draft j; the first miss takes the model's own
        # token (always emitted — that is the non-speculative step)
        match = (drafts == block_argmax[:, :-1])
        accepted = jnp.sum(jnp.cumprod(match.astype(jnp.int32),
                                       axis=1), axis=1)  # [S]
        can = (col <= accepted[:, None]) & \
            (col < budgets[:, None]) & active[:, None]
        stop = (block_argmax == eos) & can
        keep = jnp.cumprod(1 - stop.astype(jnp.int32), axis=1)
        keep_excl = jnp.concatenate(
            [jnp.ones((slots_n, 1), jnp.int32), keep[:, :-1]],
            axis=1)
        emit = can & (keep_excl > 0)
        emitted_n = jnp.sum(emit, axis=1).astype(jnp.int32)
        last = jnp.take_along_axis(
            block_argmax, jnp.maximum(emitted_n - 1, 0)[:, None],
            axis=1)[:, 0]
        tokens = jnp.where(emitted_n > 0, last, tokens)
        # context gets the whole block for active slots: entries
        # past the consumed run are garbage BEYOND the new length,
        # overwritten by the next iteration before the drafter
        # (masked to pos < length) could ever read them
        ctx_pos = jnp.where(active[:, None], q_pos, _POS_INVALID)
        context = context.at[row, ctx_pos].set(seq, mode="drop")
        lengths = lengths + emitted_n
        budgets = budgets - emitted_n
        active = active & (budgets > 0) & \
            ~jnp.any(stop & emit, axis=1)
        final_pos = jnp.where(col < emitted_n[:, None], q_pos,
                              _POS_INVALID)
        pos_side = jax.lax.dynamic_update_slice(pos_side, final_pos,
                                                (0, base))
        return ((tokens, lengths, active, budgets, context,
                 k_sides, v_sides, pos_side),
                (block_argmax, emit))

    return body


def _build_spec_step(config: LlamaConfig, k_spec: int, ngram: int):
    """Self-speculative decode scan (speculate_k): each iteration
    drafts `k_spec` tokens per slot by prompt lookup — an n-gram match
    against the slot's OWN device-side context buffer, no second
    model (Leviathan et al. 2023 acceptance over a self-drafter) —
    then scores the (1 + k_spec)-token block in ONE widened forward
    and advances each slot by its accepted run.  Greedy acceptance:
    draft j survives iff the model's argmax after consuming tokens
    < j equals it, and the first miss is replaced by the model's own
    argmax — so the emitted stream is PROVABLY the non-speculative
    greedy stream; speculation only changes how many tokens one
    weight-stream yields (the decode step is HBM-bound: the widened
    matmuls re-read the same weights once).

    Block-KV discipline with absolute positions: the main cache stays
    read-only through the scan; the round's tokens land in side
    buffers tagged `pos_side` (rejected drafts invalidated to
    _POS_INVALID) and scatter-merge into the main cache once per
    round, out-of-bounds entries dropping on the floor."""
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)
    width = k_spec + 1

    def spec_step(params, tokens, lengths, active, budgets, context,
                  k_caches, v_caches, num_steps, eos):
        entry_lengths = lengths
        slots_n = tokens.shape[0]
        side_len = num_steps * width
        side_shape = (slots_n, config.num_kv_heads, side_len,
                      config.head_dim)
        k_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        pos_side = jnp.full((slots_n, side_len), _POS_INVALID,
                            jnp.int32)
        body = _spec_scan_body(config, cos, sin, k_spec, ngram,
                               params, eos, k_caches, v_caches,
                               entry_lengths)

        (tokens, lengths, active, budgets, context, k_sides, v_sides,
         pos_side), (emitted, emit_mask) = jax.lax.scan(
            body, (tokens, lengths, active, budgets, context, k_sides,
                   v_sides, pos_side), jnp.arange(num_steps))

        # scatter-merge: each consumed side entry lands at its absolute
        # position; _POS_INVALID entries (rejected drafts, inactive
        # slots, mid-prefill slots) drop instead of clamping into a
        # live row
        def merge(cache, side):
            if isinstance(cache, dict):
                quant = L.quantize_kv_cache(side)
                new_q = jax.vmap(
                    lambda c, s, p: c.at[:, p, :].set(s, mode="drop"))(
                    cache["q"], quant["q"], pos_side)
                new_s = jax.vmap(
                    lambda c, s, p: c.at[:, p].set(s, mode="drop"))(
                    cache["s"], quant["s"], pos_side)
                return {"q": new_q, "s": new_s}
            return jax.vmap(
                lambda c, s, p: c.at[:, p, :].set(s, mode="drop"))(
                cache, side, pos_side)

        new_k_caches = [merge(k_caches[i], k_sides[i])
                        for i in range(config.num_layers)]
        new_v_caches = [merge(v_caches[i], v_sides[i])
                        for i in range(config.num_layers)]
        return (emitted, emit_mask, tokens, lengths, context,
                new_k_caches, new_v_caches)

    return jax.jit(spec_step, static_argnames=("num_steps", "eos"),
                   donate_argnames=("context", "k_caches", "v_caches"))


@functools.lru_cache(maxsize=16)
def _spec_step_for(config: LlamaConfig, k_spec: int, ngram: int,
                   kv_write: str):
    """Same process-wide sharing as _step_for, for the speculative
    variant (kv_write in the key for symmetry — the builder requires
    block mode, enforced at construction)."""
    return _build_spec_step(config, k_spec, ngram)


class ContinuousDecoder:
    """Iteration-level scheduler over a fixed slot pool.

    submit() enqueues a request; drive it from the event engine
    (attach()) or call pump() manually.  Each pump round, decode-first:
    dispatch steps_per_sync decode iterations, dispatch prefill work
    (bucketed admits + chunk extends) BEHIND the scan so it runs in
    the host's sync gap, sync the emitted tokens plus earlier rounds'
    admit outputs, retire EOS/max-length slots through their
    callbacks.  Opt-in levers: kv_cache_dtype="int8" (half the cache
    read of the HBM-bound step), speculate_k=k (multi-token decoding
    via self-drafted prompt lookup, greedy-equivalent), weight_quant,
    fuse_projections."""

    def __init__(self, params, config: LlamaConfig, max_slots: int = 8,
                 max_seq: int | None = None, eos_token: int | None = None,
                 prefill_buckets=(32, 128), steps_per_sync: int = 4,
                 t_block: int = 256, prefill_chunk: int | None = None,
                 prefill_budget: int | None = None,
                 weight_quant: bool = False,
                 fuse_projections: bool = False,
                 kv_cache_dtype: str | None = None,
                 speculate_k: int = 0, speculate_ngram: int = 2,
                 name: str = "decoder", registry=None,
                 prefix_cache: PrefixKVCache | None = None,
                 paged_kv: bool = False, kv_block: int = 32):
        self.config = config
        # int8 KV cache (ISSUE 7): the slot caches store int8 values
        # with per-(slot, head, position) f32 scales
        # (layers.quantize_kv_cache).  Admits/extends write quantized
        # rows off the decode critical path; the decode scan reads the
        # int8 buffer as the dot operand and FOLDS the scales into
        # scores/weights — the HBM-bound step's dominant read halves.
        # Greedy outputs are NOT bit-identical to the full-precision
        # cache (int8 rounding of stored K/V), so the mode is opt-in
        # like weight_quant.
        dtype_norm = (kv_cache_dtype or "native").lower()
        if dtype_norm not in ("native", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be None/'native'/'int8', got "
                f"{kv_cache_dtype!r}")
        self.kv_int8 = dtype_norm == "int8"
        if self.kv_int8 and KV_WRITE != "block":
            raise ValueError(
                "kv_cache_dtype='int8' requires the block KV write "
                "mode (AIKO_DECODE_KV=block): the select mode rewrites "
                "the whole cache per step, which would re-encode int8 "
                "every iteration")
        # self-speculative decoding (ISSUE 7): each scan iteration
        # drafts speculate_k tokens by prompt lookup over a device-side
        # context buffer and verifies the widened block in one forward;
        # greedy acceptance makes the emitted stream identical to the
        # non-speculative path.  The side buffers grow to
        # steps_per_sync * (1 + speculate_k) entries — size
        # steps_per_sync for the same per-round token output, not on
        # top of it.
        self.speculate_k = int(speculate_k or 0)
        if self.speculate_k < 0:
            raise ValueError(f"speculate_k must be >= 0, got "
                             f"{speculate_k}")
        self.speculate_ngram = int(speculate_ngram)
        if self.speculate_k and self.speculate_ngram < 1:
            raise ValueError("speculate_ngram must be >= 1")
        if self.speculate_k and KV_WRITE != "block":
            raise ValueError(
                "speculate_k requires the block KV write mode "
                "(AIKO_DECODE_KV=block)")
        # weight-only int8 (W8A16): every linear's weight tree-rewritten
        # to {w8, s} once here — linear()/linear_logits consume it
        # transparently across prefill, chunked extends, and the
        # decode scan.  Measured r5 (tools/ab_w8.py, 1b/256 slots):
        # device step −2.6%, closed loop a wash — a MEMORY lever
        # (1.24 GB of weights freed for more KV slots), not a speed
        # lever; see layers.quantize_linear for the numbers.  Greedy
        # outputs are NOT bit-identical to bf16 (int8 rounding), and
        # MoE routers are excluded (top-k flips).
        if fuse_projections:
            params = _fuse_decode_projections(params)
        if weight_quant:
            params = L.quantize_linear_tree(params)
        self.weight_quant = bool(weight_quant)
        self.fuse_projections = bool(fuse_projections)
        self.params = params
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq_len
        self.eos_token = eos_token
        self.steps_per_sync = steps_per_sync
        # chunked prefill: prompts longer than the largest bucket are
        # admitted to a slot immediately but their prefill runs
        # `prefill_chunk` tokens per pump round (a compiled cache-extend
        # program), so one long prompt stalls every active decode slot
        # by at most ~one chunk instead of its full length — the
        # classic inter-token-latency spike under prompt-heavy load.
        # Also lifts the prompt-length cap from the largest bucket to
        # max_seq.  None = single-shot bucketed prefill only.
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and not \
                (1 <= self.prefill_chunk <= self.max_seq - 1):
            # fail at construction, not mid-serving with a wedged slot
            raise ValueError(
                f"prefill_chunk must be in [1, {self.max_seq - 1}], "
                f"got {self.prefill_chunk}")
        # per-round prefill token budget: bucketed admits stop (FIFO,
        # no reordering) and chunk advances are rationed once a round
        # has dispatched this much prefill work.  None = unbounded.
        self.prefill_budget = int(prefill_budget) if prefill_budget \
            else None
        # granularity of the attention time-axis cap: each round reads
        # cache[:, :, :t_cap] with t_cap the smallest multiple of
        # t_block covering the longest active context (one compiled
        # program per distinct t_cap — max_seq/t_block variants)
        self.t_block = max(1, int(t_block))
        # buckets beyond the cache's time axis would blow up the admit
        # scatter — clamp, dedupe, keep sorted
        self.prefill_buckets = tuple(sorted(
            {min(int(b), self.max_seq - 1) for b in prefill_buckets}))
        self.logger = get_logger(f"serving.{name}")
        self.on_idle = None          # hook: fires when the last slot
                                     # retires and nothing is pending

        # paged KV (ISSUE 15): the slot caches become ONE refcounted
        # block pool plus per-slot int32 block tables — a prefix hit
        # aliases cached blocks into the table (zero copy), harvest is
        # a refcount bump, the disaggregated install lands once.  The
        # compiled step gathers a slot-major view from the pool and
        # runs the SAME attention bodies at the same shapes, so greedy
        # output is bit-identical to the dense cache (the parity
        # matrix in tests/test_paged_kv.py asserts it across int8 /
        # chunked / spec / mid-stream / disagg).  Dense stays the A/B
        # behind AIKO_BENCH_LLAMA_PAGED=off.
        self.paged = bool(paged_kv)
        if self.paged and KV_WRITE != "block":
            raise ValueError(
                "paged_kv requires the block KV write mode "
                "(AIKO_DECODE_KV=block): the select mode rewrites the "
                "whole cache inside the scan, which a block pool "
                "cannot express")
        self.kv_block = int(prefix_cache.block_tokens) \
            if prefix_cache is not None else int(kv_block)
        if self.paged and self.kv_block < 1:
            raise ValueError(
                f"kv_block must be >= 1, got {kv_block}")

        # the cache TIME axis is allocated at the workload, not at
        # max_seq: it grows/shrinks in t_block steps to cover the
        # longest active context (_fit_caches).  HBM capacity AND
        # per-step bandwidth then scale with actual occupancy — a
        # max_seq allocation makes every decode step stream max_seq
        # worth of cache (an in-program slice doesn't help: it
        # materializes, measured 3× attention bytes).
        self._cache_t = min(self.t_block, self.max_seq)

        # prefix/KV reuse cache (ISSUE 13): hash-addressed block
        # sharing across requests and sessions.  The cache stores rows
        # in THIS decoder's storage layout (int8 dicts when kv_int8 —
        # a hit is a bytes win too); bind() enforces layout agreement
        # when several decoders share one cache.  Harvest at retire,
        # longest-match at admit, copy-in via _prefix_copy_fn_for.
        self.prefix_cache = prefix_cache
        self._ledger = None             # KV memory ledger (ISSUE 20)
        item = jnp.dtype(config.dtype).itemsize
        # the layout tuple is the geometry handshake for binding AND
        # for the disaggregated wire — a cacheless paged decoder still
        # needs it for the direct slot-table install (ISSUE 15)
        self._kv_layout = (config.num_layers, config.num_kv_heads,
                           config.head_dim, str(config.dtype),
                           self.kv_int8, self.kv_block, item)
        if prefix_cache is not None:
            prefix_cache.bind(self._kv_layout, paged=self.paged)
            if not self.paged and prefix_cache.paged:
                raise ValueError(
                    "prefix cache holds paged (pool-resident) blocks; "
                    "a dense decoder cannot bind it")

        if self.paged:
            from .serving_paged import BlockPool
            block = self.kv_block
            # table width covers the worst-case extent _fit_caches can
            # reach (max_seq + block-mode merge headroom)
            headroom = 0 if self.speculate_k else steps_per_sync
            self._table_blocks = -(-(self.max_seq + headroom) // block)
            initial = max_slots * (-(-self._cache_t // block))
            if prefix_cache is not None and prefix_cache.paged:
                # a decoder sharing an already-attached cache ADOPTS
                # its pool (attach_pool's one-pool-per-cache contract;
                # bind() above proved the geometry agrees) and reserves
                # its own slot coverage on top of what's resident.
                self.pool = prefix_cache.pool
                self.pool.reserve(self.pool.num_blocks - 1 + initial)
            else:
                self.pool = BlockPool(
                    self.config, block, self.kv_int8,
                    initial_blocks=initial,
                    grow_blocks=max(
                        1, max_slots * self.t_block // block),
                    name=name, registry=registry)
                if prefix_cache is not None:
                    prefix_cache.attach_pool(self.pool)
                    if prefix_cache.max_bytes:
                        # anticipate the cache's pool residency up
                        # front: a pool capacity change retraces every
                        # compiled program that touches it, so steady
                        # state should be reachable without mid-serving
                        # growth.  Bounded by one full-max_seq slot
                        # population — the same worst case the dense
                        # cache could reach.
                        anticipated = min(
                            prefix_cache.max_bytes
                            // self.pool.block_nbytes,
                            max_slots * (-(-self.max_seq // block)))
                        self.pool.reserve(initial + anticipated)
            self._tables_np = np.zeros(
                (max_slots, self._table_blocks), np.int32)
            # reused per-round gather buffer for admit/extend table
            # rows: the pump hot path must not allocate a fresh host
            # array every batch (lint-hot-alloc); consumers copy it
            # to device with jnp.array before the next round reuses it
            self._tables_scratch = np.zeros_like(self._tables_np)
            self._tables_dirty = True
            self._tables_dev = None
            self._tables_dev_nb = -1
            # per-slot owned/aliased pool block ids, in table order
            self._slot_blocks: list[list] = \
                [[] for _ in range(max_slots)]
            self._k = None
            self._v = None
        else:
            self.pool = None
            self._k = self._zero_caches()
            self._v = self._zero_caches()
        self._tokens = jnp.zeros((max_slots,), jnp.int32)
        self._lengths = jnp.zeros((max_slots,), jnp.int32)
        # device-side token history per slot, written by admits /
        # extends / the verify scan — what the speculative drafter
        # matches against.  A [1, 1] stub when speculation is off so
        # the admit/extend programs keep ONE signature either way
        # (threaded through and returned unchanged).
        self._context = jnp.zeros(
            (max_slots, self.max_seq) if self.speculate_k else (1, 1),
            jnp.int32)
        self._resize_fns: dict = {}

        self._prefix_pad = None         # lazy zero pad block (copy-in)
        # measured host dispatch seconds per prefill token (EWMA): the
        # prompt-cost term of estimated_admit_wait, which prefix hits
        # credit away (ISSUE 13 satellite)
        self._prefill_token_ewma: float | None = None

        # the paged pallas-kernel toggle is latched here — builder
        # cache keys include it, so oracle and kernel decoders coexist
        # in one process (parity tests build one of each)
        self.paged_kernel = bool(self.paged and
                                 ATTENTION_IMPL == "paged_kernel")
        if self.paged:
            from .serving_paged import (_paged_spec_step_for,
                                        _paged_step_for)
            self._step = _paged_spec_step_for(
                config, self.speculate_k, self.speculate_ngram,
                self.paged_kernel) \
                if self.speculate_k \
                else _paged_step_for(config, self.paged_kernel)
        else:
            self._step = _spec_step_for(config, self.speculate_k,
                                        self.speculate_ngram,
                                        KV_WRITE) \
                if self.speculate_k else _step_for(config, KV_WRITE,
                                                   ATTENTION_IMPL)
        # in-flight prefix dedup window (ISSUE 14 satellite): leading
        # block key -> the request currently prefilling that chain.
        # Bounded by the slot pool: entries unregister at early
        # harvest or retire, and only admitted requests register.
        self._inflight_chains: dict[str, DecodeRequest] = {}
        self._prefill_fns: dict = {}
        self._slots: list[DecodeRequest | None] = [None] * max_slots
        self._pending: list[DecodeRequest] = []
        # admit/extend output stash: (firsts device array, [(row,
        # request), ...]) per dispatch — resolved at the NEXT round's
        # sync, by which point the prefill program has run behind the
        # decode scan (single in-order device stream), so the fetch
        # never stalls the round
        self._admit_waves: list = []
        self._timer = None
        # preallocated per-round host buffers: pump/_round_plan are the
        # per-step hot path (graft-check lint-hot-alloc polices them)
        self._active_np = np.zeros((max_slots,), bool)
        self._budgets_np = np.zeros((max_slots,), np.int32)
        # HBM-traffic model for roofline reporting: every decode step
        # streams the full weight set (embed excluded — it's a gather
        # of S rows) plus the capped KV read
        itemsize = jnp.dtype(config.dtype).itemsize
        # fused qkv copies (fuse_projections) duplicate q/k/v byte-for
        # -byte — exclude them so bytes_moved counts what one step
        # actually streams, not both forms
        self._param_bytes = int(sum(
            int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if "embed" not in str(path[0]) and
            not any("qkv" in str(part) for part in path)))
        # int8 cache: D int8 values + one f32 scale per (slot, head,
        # position) — ~(D+4)/(2D) of the bf16 bytes
        per_position = (config.head_dim + 4) if self.kv_int8 \
            else config.head_dim * itemsize
        self._kv_bytes_per_t = (2 * config.num_layers * max_slots *
                                config.num_kv_heads * per_position)
        # cumulative decode-loop counters, mirrored onto the process
        # metrics registry (serving_decoder_total{kind=...}) so the
        # bench and the dashboard metrics pane read the SAME numbers
        # the decoder increments (ISSUE 5).  tokens_decode /
        # tokens_prefill split what the old single token flow hid:
        # decode-scan emissions vs prompt tokens prefilled — the
        # overhead ISSUE 7 moves off the decode round is exactly their
        # ratio.
        # decode-round phase profiler (ISSUE 11): every pump round's
        # wall time attributed to named phases (plan / scan dispatch /
        # admit+extend dispatch / host sync / wave resolve / deliver),
        # with the modeled HBM bytes charged to the phase that explains
        # them — the roofline gap decomposes instead of being one
        # opaque overhead number.  Always on: the mark API is one
        # perf_counter read per boundary.
        from .observe.journey import JourneyLog
        from .observe.metrics import MirroredStats, default_registry
        from .observe.profiler import PhaseProfiler
        self.profiler = PhaseProfiler(name)
        self._registry = registry or default_registry()
        # request journeys + mergeable SLO sketches (ISSUE 12): every
        # request gets a RequestJourney correlated to the ambient
        # TraceContext, and TTFT/ITL observations land in per-tenant
        # DDSketch families (serving_{ttft,itl}_seconds{decoder,tenant})
        # whose retained-snapshot form MERGES across processes — the
        # fleet-true percentile surface the health plane alerts on,
        # with the worst requests' trace ids as exemplars.
        self.journeys = JourneyLog(name=name, proc=name,
                                   registry=self._registry)
        self._slo_sketches: dict = {}
        self.stats = MirroredStats(
            {"steps": 0, "rounds": 0, "completed": 0,
             "prefills": 0, "occupancy_sum": 0.0,
             "prefill_s": 0.0, "decode_s": 0.0,
             "useful_steps": 0, "wasted_steps": 0,
             "tokens_decode": 0, "tokens_prefill": 0,
             "spec_proposed": 0, "spec_accepted": 0,
             "accepted_per_step": 0.0,
             "bytes_moved": 0, "prefill_chunks": 0,
             "chunk_admits": 0, "prefix_admits": 0,
             "round_prefill_tokens_max": 0,
             "admission_shed": 0,
             "dedup_deferred": 0, "dedup_shared": 0,
             # paged A/B surfaces (ISSUE 15): bytes a prefix hit
             # copied into the slot (paged: 0 — aliasing), bytes
             # harvest copied out at retire (paged: 0 — refcount
             # bump), and copy-on-extend events (paged only: a write
             # into a SHARED block copies it first)
             "prefix_copy_bytes": 0, "harvest_copy_bytes": 0,
             "cow_copies": 0, "cow_copy_bytes": 0,
             "install_misaligned": 0,
             # graceful drain (ISSUE 19): submissions refused while
             # draining, requests handed back for re-routing, and
             # deadline checkpoints that harvested a live slot's
             # chain instead of letting it finish
             "drain_refused": 0, "drain_evacuated": 0,
             "drain_checkpoints": 0},
            metric="serving_decoder_total",
            help="continuous-decoder events by kind",
            # levels and time-sums stay dict-only: a high-water mark or
            # a seconds accumulator inside an events-by-kind counter
            # family would make rate()/sum() over the family meaningless
            registry=self._registry,
            skip=("occupancy_sum", "prefill_s", "decode_s",
                  "accepted_per_step", "round_prefill_tokens_max",
                  "prefix_copy_bytes", "harvest_copy_bytes",
                  "cow_copy_bytes"))
        # SLO samples (seconds): TTFT per request, mean inter-token
        # latency per retired request, and each request's worst
        # inter-sync stall — the number chunked prefill bounds
        self.ttft_samples: deque = deque(maxlen=8192)
        self.itl_samples: deque = deque(maxlen=8192)
        self.gap_samples: deque = deque(maxlen=8192)
        self._round_prefill_tokens = 0
        # EWMA of recent working-round wall time (alpha 0.3), fed by
        # pump(): the deadline-aware admission estimate's time base
        self._round_ewma: float | None = None
        # graceful drain (ISSUE 19): armed by drain() — submit()
        # refuses new work, pump() checkpoints in-flight slots when
        # the deadline passes, and the completion callback fires once
        # when the decoder reaches idle with every live chain
        # harvested.  The gauge is the autoscaler's shrink-safety
        # signal: live slots + queued requests, published per decoder
        # so a fleet shrink can refuse a victim that still holds work.
        self._draining = False
        self._drained = False
        self._drain_deadline: float | None = None
        self._drain_evacuate = None
        self._drain_complete = None
        self._gauge_active = self._registry.gauge(
            "serving_active_slots",
            "live decode slots + queued requests (the drain/shrink "
            "in-flight safety signal)", labels={"decoder": name})

    # -- public API --------------------------------------------------------
    def estimated_admit_wait(self, prompt=None,
                             tenant: str = "") -> float | None:
        """Coarse time-to-first-token wait estimate for the NEXT
        submitted request: at least one working round when a slot is
        free, scaled by the backlog's share of the slot pool when all
        slots are taken.  Deliberately a cheap lower-bound heuristic —
        it exists to shed requests that are grossly doomed under
        overload (the deadline-aware admission gate, ISSUE 9), not to
        predict TTFT; None until a round has been measured, because
        admission must not drop work on a number it doesn't have.

        With `prompt`, the estimate adds that prompt's prefill cost at
        the measured per-token dispatch rate, CREDITING expected
        prefix-cache hits (a pure block-key probe, no side effects) —
        a cached-heavy tenant's real admit cost is near the round
        floor, and shedding or autoscaling on the cold re-prefill
        number would over-shed/over-scale it (ISSUE 13)."""
        if self._round_ewma is None:
            return None
        free = sum(1 for request in self._slots if request is None)
        waiting = len(self._pending)
        if waiting < free:
            wait = self._round_ewma
        else:
            wait = self._round_ewma * \
                (1.0 + (waiting - free + 1) / max(1, self.max_slots))
        if prompt is not None and self._prefill_token_ewma:
            uncached = len(prompt)
            if self.prefix_cache is not None and len(prompt) > 1:
                _, hit = self.prefix_cache.match(
                    tenant, prompt, limit=len(prompt) - 1)
                uncached -= hit
                if hit < len(prompt) - 1 and self.prefix_cache.tiered:
                    # admission-probe promotion kick (ISSUE 17): the
                    # probe knows this prompt is coming before its
                    # admit round — start re-landing its host-tier
                    # chain tail now (non-blocking)
                    self.prefix_cache.prefetch(tenant, prompt)
            wait += uncached * self._prefill_token_ewma
        return wait

    def _note_prefill_rate(self, tokens: int, elapsed: float) -> None:
        """Fold one prefill dispatch's (tokens, wall) into the
        per-token EWMA the admission estimate charges prompts at.
        Asymmetric on purpose: a LOWER rate is taken outright while a
        higher one is damped and clamped — dispatch walls that include
        a jit compile (first sight of a (chunk, width, cache_t) shape)
        are orders of magnitude above the real cost, and an EWMA that
        believed them would shed deadline-carrying prompts on a number
        that is compiler overhead, not serving cost.  One clean round
        snaps the estimate back to the measured floor."""
        if tokens <= 0 or elapsed <= 0.0:
            return
        rate = elapsed / tokens
        current = self._prefill_token_ewma
        if current is None or rate < current:
            self._prefill_token_ewma = rate
        else:
            self._prefill_token_ewma = \
                0.7 * current + 0.3 * min(rate, 10.0 * current)

    def _slo_sketch(self, kind: str, tenant: str,
                    prefill: str | None = None):
        """Per-(kind, tenant[, prefill]) mergeable SLO sketch, lazily
        registered: serving_{kind}_seconds{decoder, tenant[, prefill]}
        (ISSUE 12).  Tenant is a BOUNDED label (tenant names come from
        serving policy, not request identity — lint-metric-label's
        discipline); `prefill` splits the TTFT population into
        cached/cold (ISSUE 13) so the SLO report and the conversation
        bench can quote both."""
        key = (kind, tenant, prefill)
        sketch = self._slo_sketches.get(key)
        if sketch is None:
            labels = {"decoder": self.journeys.name,
                      "tenant": tenant or "default"}
            if prefill is not None:
                labels["prefill"] = prefill
            sketch = self._registry.sketch(
                f"serving_{kind}_seconds",
                f"per-request {kind} seconds (mergeable quantile "
                f"sketch with worst-request trace-id exemplars)",
                labels=labels)
            self._slo_sketches[key] = sketch
        return sketch

    def submit(self, request_id: str, prompt, max_new_tokens: int,
               callback, deadline: float | None = None,
               tenant: str | None = None,
               prefill_label: str | None = None,
               kv_blocks: tuple | None = None,
               progress_callback=None) -> bool:
        """Enqueue one request; returns False when deadline-aware
        admission rejected it instead (the callback is NOT invoked —
        the caller owns the refusal).  `deadline` (absolute,
        time.monotonic seconds) is the request's END-TO-END completion
        target — the frame deadline the serving walk carries, crossed
        into this clock domain (PE_LlamaAgent does the conversion).
        `tenant`, when given, overrides the admission note's tenant —
        the caller that also keys session KV handles (PE_LlamaAgent)
        passes the SAME normalized key here, so harvested blocks and
        session pins land under one tenant root (ISSUE 13).
        Admission uses the estimated admit wait (a time-to-FIRST-token
        bound) as its necessary condition: a request that cannot even
        reach its first token inside the budget is refused NOW, so the
        caller fails over or degrades instead of queueing doomed work
        (ISSUE 9); the journey's deadline margin is judged at
        completion against the same end-to-end target (ISSUE 12).

        Every submission opens a RequestJourney (ISSUE 12) correlated
        to the AMBIENT TraceContext — the serving walk runs under the
        caller's context, so the journey's spans join the same trace as
        the wire hop — and claims the pipeline admission note (verdict
        + measured fair-queue wait) posted for that trace id."""
        from .observe.journey import RequestJourney, take_admission_note
        from .observe.tracing import current_trace
        now = time.monotonic()
        context = current_trace()
        note = take_admission_note(context.trace_id) \
            if context is not None else None
        journey = RequestJourney(
            request_id, now,
            trace_id=context.trace_id if context is not None else "",
            parent_span_id=context.span_id
            if context is not None else "",
            tenant=tenant if tenant is not None
            else (note or {}).get("tenant", ""),
            tier=(note or {}).get("tier", 1),
            deadline=deadline,
            admission_verdict=(note or {}).get("verdict", ""),
            admission_wait_s=(note or {}).get("queue_wait_s"),
            prompt_tokens=len(prompt))
        if self._draining:
            # drain armed (ISSUE 19): no new admissions — the caller
            # re-routes to a healthy runtime (pipeline failover) or
            # the drain destination.  Counted AND journeyed so the
            # soak can assert the refusal path and a trace shows why
            # this request bounced.
            self.stats["drain_refused"] += 1
            self.journeys.finish(journey, time.monotonic(),
                                 outcome="drained")
            return False
        # keep the TAIL on overflow (recent context matters most).
        # Without chunked prefill the largest bucket is a hard cap (an
        # oversized prompt would blow up _admit's scatter); with it,
        # long prompts stream in chunks and the cap is max_seq itself.
        # Normalized BEFORE admission so the wait estimate's prefill
        # term (and its prefix-cache probe) sees the prompt that will
        # actually admit.
        if self.prefill_chunk:
            limit = self.max_seq - 1
        else:
            limit = min(self.max_seq - 1, self.prefill_buckets[-1])
        # empty prompts would seed generation from a pad position —
        # normalize to a single pad token at position 0
        prompt = [int(t) for t in prompt] or [0]
        truncated = len(prompt) > limit
        prompt = prompt[-limit:]
        if self.prefix_cache is not None and len(prompt) > 1 and \
                self.prefix_cache.tiered:
            # submit-time promotion kick (ISSUE 17): the admit round
            # is at least one pump tick away — a prefetch kicked here
            # overlaps the whole queue wait, so the admit probe finds
            # the chain staged (or already resident) instead of
            # paying the H2D inline
            self.prefix_cache.prefetch(journey.tenant, prompt)
        if deadline is not None:
            wait = self.estimated_admit_wait(prompt=prompt,
                                             tenant=journey.tenant)
            if wait is not None and now + wait >= float(deadline):
                self.stats["admission_shed"] += 1
                self.journeys.finish(journey, time.monotonic(),
                                     outcome="shed")
                return False
        if prefill_label:
            # population override (ISSUE 14): a remote-prefilled
            # request is "cached" mechanically (the shipped chain
            # hits) but belongs to its own TTFT/journey population
            journey.prefill_label = str(prefill_label)
        request = DecodeRequest(
            request_id, prompt, int(max_new_tokens), callback,
            submit_time=now, journey=journey, deadline=deadline,
            tenant=journey.tenant,
            prefill_label=str(prefill_label or ""),
            progress_callback=progress_callback)
        if kv_blocks:
            # direct slot-table install (ISSUE 15 satellite): the
            # caller pre-installed pool blocks covering the prompt's
            # leading tokens (install_shipped_blocks); admit aliases
            # them into the slot's table and prefills only the suffix.
            # At least one suffix token must remain to produce the
            # first output, so a whole-prompt cover drops its final
            # block back to the pool here.  Ownership transfers on
            # acceptance only — a shed above returned False with the
            # ids untouched, so the caller's release stays balanced.
            if not self.paged:
                raise ValueError(
                    "kv_blocks install needs a paged decoder")
            covered, ids = int(kv_blocks[0]), list(kv_blocks[1])
            if truncated:
                # the ids cover the ORIGINAL prompt's head — exactly
                # the tokens the tail-truncation above removed — so
                # aliasing them would attend to KV for a different
                # prompt and silently emit wrong tokens.  In-repo
                # callers cap with serving_disagg._prompt_cap BEFORE
                # installing (this never fires on that path); a direct
                # API caller pays a cold prefill instead.
                self.logger.warning(
                    "kv_blocks install for %s dropped: prompt "
                    "exceeds the admit cap %d (%d-token cover); "
                    "cold prefill", request_id, limit, covered)
                self.stats["install_misaligned"] += 1
                self.pool.release_blocks(ids, tenant=journey.tenant)
            else:
                block = self.kv_block
                usable = min(covered, len(ids) * block,
                             ((len(prompt) - 1) // block) * block)
                keep = max(0, usable // block)
                if len(ids) > keep:
                    self.pool.release_blocks(ids[keep:],
                                             tenant=journey.tenant)
                request.kv_block_ids = ids[:keep]
                request.prefix_hit = keep * block
                request.prefix_probed = True
        self._pending.append(request)
        self._note_active()
        return True

    def attach_ledger(self, ledger) -> None:
        """Wire the KV memory ledger (ISSUE 20) through this
        decoder's storage stack: the prefix cache fans it out to its
        pool and host tiers; a cacheless paged decoder attaches the
        pool directly.  Dense slot caches are preallocated arrays —
        nothing per-tenant to account without a prefix cache."""
        self._ledger = ledger
        if self.prefix_cache is not None:
            self.prefix_cache.attach_ledger(ledger)
        elif self.paged and ledger is not None:
            self.pool.attach_ledger(ledger)

    @property
    def ledger(self):
        return self._ledger

    def attach(self, engine, period: float = 0.002) -> int:
        # idempotent: re-attaching while already pumping (e.g. a stream
        # reopens during a deferred teardown) must not orphan the
        # first timer
        if self._timer is None:
            self._timer = engine.add_timer_handler(self.pump, period)
        return self._timer

    @property
    def attached(self) -> bool:
        return self._timer is not None

    def detach(self, engine) -> None:
        if self._timer is not None:
            engine.remove_timer_handler(self._timer)
            self._timer = None

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.active_count == 0 and not self._pending

    def _note_active(self) -> None:
        self._gauge_active.set(self.active_count + len(self._pending))

    # -- graceful drain (ISSUE 19) -----------------------------------------
    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def drained(self) -> bool:
        return self._drained

    def drain(self, deadline: float | None = None,
              on_evacuate=None, on_complete=None) -> list:
        """Arm a graceful wind-down: stop admitting, let in-flight
        slots finish (or checkpoint them at the first round boundary
        past `deadline`, relative seconds), harvest every live chain
        into the prefix cache, and fire `on_complete(self)` once when
        the decoder is idle.  Queued (never-admitted) requests are
        evacuated NOW and returned as plain descriptors — request_id,
        prompt, generated-so-far, max_new_tokens, callback, deadline,
        tenant — for the caller to re-submit elsewhere; checkpointed
        in-flight slots evacuate the same way through `on_evacuate`.
        Without an evacuation route a checkpointed request's callback
        is invoked with whatever generated so far — degraded, never
        silently dropped.  Idempotent: re-arming tightens the deadline
        but never un-drains (resume() does that)."""
        now = time.monotonic()
        self._draining = True
        self._drained = False
        self._drain_deadline = None if deadline is None \
            else now + float(deadline)
        if on_evacuate is not None:
            self._drain_evacuate = on_evacuate
        if on_complete is not None:
            self._drain_complete = on_complete
        pending, self._pending = self._pending, []
        evacuated = [self._evacuate(request, now) for request in pending]
        if self.idle:
            self._drain_finish()
        self._note_active()
        return evacuated

    def resume(self) -> None:
        """Re-open admission after a drain (planned-restart rollback,
        tests): clears the drain latch; the decoder serves again."""
        self._draining = False
        self._drained = False
        self._drain_deadline = None
        self._drain_evacuate = None
        self._drain_complete = None

    def _evacuate(self, request: DecodeRequest, now: float) -> dict:
        """Close one request's journey as evacuated and hand back a
        re-submittable descriptor (prompt + generated so far: the
        continuation's prompt on the next runtime)."""
        if request.inflight_key and \
                self._inflight_chains.get(request.inflight_key) \
                is request:
            # a queued dedup leader leaves with its registration —
            # otherwise a post-resume duplicate waits forever on a
            # chain nobody is prefilling
            self._inflight_chains.pop(request.inflight_key, None)
            request.inflight_key = ""
        self.stats["drain_evacuated"] += 1
        if request.journey is not None:
            self.journeys.finish(request.journey, now,
                                 outcome="evacuated")
            request.journey = None
        return {"request_id": request.request_id,
                "prompt": list(request.prompt),
                "generated": list(request.generated or []),
                "max_new_tokens": int(request.max_new_tokens),
                "callback": request.callback,
                "deadline": request.deadline,
                "tenant": request.tenant}

    def _drain_checkpoint(self) -> None:
        """Deadline checkpoint, at a round boundary: every live slot
        harvests the complete blocks of its written context into the
        prefix cache (mid-prefill slots harvest [0, prefill_pos); the
        decode slots drop the LAST generated token — its KV row is
        only written when it is fed back next round), then evacuates
        with its partial generation.  The re-submitted continuation
        prefix-hits the harvested chain instead of re-prefilling."""
        now = time.monotonic()
        for slot in range(self.max_slots):
            request = self._slots[slot]
            if request is None:
                continue
            if self.prefix_cache is not None:
                try:
                    if request.prefilling:
                        self.harvest_progress(request)
                    else:
                        context = list(request.prompt) + \
                            list(request.generated or [])
                        self._harvest_rows(slot, request.tenant,
                                           context[:-1])
                except Exception:
                    self.logger.exception(
                        "drain checkpoint harvest failed for %s",
                        request.request_id)
                if request.prefix_nodes:
                    self.prefix_cache.release(request.prefix_nodes)
                    request.prefix_nodes = []
            if self.paged:
                self._release_slot_blocks(slot)
            self._slots[slot] = None
            self.stats["drain_checkpoints"] += 1
            descriptor = self._evacuate(request, now)
            if self._drain_evacuate is not None:
                try:
                    self._drain_evacuate(descriptor)
                except Exception:
                    self.logger.exception(
                        "drain evacuation failed for %s",
                        request.request_id)
            else:
                try:
                    request.callback(request.request_id,
                                     descriptor["generated"])
                except Exception:
                    self.logger.exception("callback failed for %s",
                                          request.request_id)
        self._note_active()

    def _drain_finish(self) -> None:
        self._drained = True
        self._drain_deadline = None
        callback, self._drain_complete = self._drain_complete, None
        if callback is not None:
            try:
                callback(self)
            except Exception:
                self.logger.exception("drain completion callback "
                                      "failed")

    # -- scheduling --------------------------------------------------------
    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.prefill_buckets[-1]

    def _admit_fn(self, bucket: int, width: int):
        """Compiled once per (bucket, admit-width): ONE program runs the
        stacked prefill for up to `width` prompts AND scatters their
        K/V prefixes, first tokens, and lengths into the slot buffers
        on device.  The host syncs a single [width] token array per
        group — not one round-trip per request (the per-request admit
        was a throughput cliff under bursty arrivals on thin links).
        Shared process-wide via _admit_fn_for, like the decode step."""
        key = (bucket, width)
        if key not in self._prefill_fns:
            if self.paged:
                from .serving_paged import _paged_admit_fn_for
                self._prefill_fns[key] = _paged_admit_fn_for(
                    self.config, bucket, width, self.kv_int8,
                    bool(self.speculate_k))
            else:
                self._prefill_fns[key] = _admit_fn_for(
                    self.config, bucket, width, self.kv_int8,
                    bool(self.speculate_k))
        return self._prefill_fns[key]

    def _extend_fn(self, chunk: int, width: int):
        """Compiled once per (chunk, admit-width): advances up to
        `width` mid-prefill slots by one `chunk`-token piece of their
        prompt — see _extend_fn_for.  Shared process-wide.  The chunk
        is prefill_chunk for chunked admits; prefix-hit suffixes
        without a global prefill_chunk use a pow2-sized chunk of their
        own (bounded compile variants)."""
        key = ("extend", chunk, width)
        if key not in self._prefill_fns:
            # compile-cache boundary: builder runs once per (chunk,
            # width); allocs inside it are trace-time, not per-round
            if self.paged:
                from .serving_paged import _paged_extend_fn_for
                self._prefill_fns[key] = _paged_extend_fn_for(  # graft: disable=lint-hot-alloc
                    self.config, chunk, width, self.kv_int8,
                    bool(self.speculate_k), self.paged_kernel)
            else:
                self._prefill_fns[key] = _extend_fn_for(  # graft: disable=lint-hot-alloc
                    self.config, chunk, width, self.kv_int8,
                    bool(self.speculate_k))
        return self._prefill_fns[key]

    def _advance_prefills(self) -> None:
        """Run one prompt chunk for mid-prefill slots (batched, pow2
        widths).  Slots closest to completion go first so in-flight
        prompts finish (and start emitting) sooner; prefill_budget
        rations how many rows advance per round.  Prefix-hit admits
        (ISSUE 13) stream their uncached SUFFIX through the same
        machinery: with prefill_chunk set they ride the normal chunk
        size, without it each suffix runs as one pow2-sized chunk."""
        rows = [s for s in range(self.max_slots)
                if self._slots[s] is not None
                and self._slots[s].prefilling]
        if not rows:
            return
        rows.sort(key=lambda s: len(self._slots[s].prompt) -
                  self._slots[s].prefill_pos)      # fewest remaining first
        # the extend writes up to offset+chunk; never let a decode-side
        # shrink cut below it (grow-only: max with current size)
        need = 0
        spent = self._round_prefill_tokens
        planned = 0
        plans_by_chunk: dict[int, list] = {}
        for slot in rows:
            request = self._slots[slot]
            total = len(request.prompt)
            remaining = total - request.prefill_pos
            chunk = self.prefill_chunk or min(
                self._next_pow2(max(1, remaining)), self.max_seq - 1)
            if self.prefill_budget is not None and planned and \
                    spent + chunk > self.prefill_budget:
                break          # ration; first row always progresses
            spent += chunk
            planned += 1
            if remaining > chunk:
                offset, finish = request.prefill_pos, False
            else:
                # final chunk slides BACK to end exactly at the prompt
                # tail: the overlap recomputes identical K/V
                # (idempotent) and offset+chunk stays <= total, so the
                # cache never needs to grow past the prompt itself —
                # EXCEPT below a prefix-cache hit, whose rows must not
                # be recomputed (the savings are the point): anchor at
                # the written boundary and pad forward instead (the
                # garbage tail past the prompt is dead cells, same as
                # the shorter-than-chunk admit)
                offset = max(0, total - chunk)
                if offset < request.prefix_hit:
                    # ...but never let the write extent leave the
                    # cache: near the seq cap the forward pad would
                    # exceed max_seq, where _fit_caches clamps and the
                    # extend's dynamic_update_slice would CLAMP the
                    # start index — silently shifting rows onto wrong
                    # positions.  Sliding back into the cached region
                    # there is the correct fallback: the overlap
                    # recompute is idempotent (same program, same
                    # offset, same prefix bytes as the donor's own
                    # final chunk).
                    offset = min(request.prefill_pos,
                                 self.max_seq - chunk)
                finish = True
            plans_by_chunk.setdefault(chunk, []).append(
                (slot, request, offset, finish))
            # the write extent is always offset+chunk (a prompt shorter
            # than one chunk pads — the garbage tail is overwritten by
            # decode tokens before it is ever attended)
            need = max(need, offset + chunk)
        if not plans_by_chunk:
            return
        self._fit_caches(max(need, self._cache_t))
        start = time.perf_counter()
        before = self.stats["tokens_prefill"]
        for chunk, plans in plans_by_chunk.items():
            while plans:
                width = min(self.max_slots, self._next_pow2(len(plans)))
                batch, plans = plans[:width], plans[width:]
                self._extend_group(chunk, width, batch)
        elapsed = time.perf_counter() - start
        self.stats["prefill_s"] += elapsed
        self._note_prefill_rate(self.stats["tokens_prefill"] - before,
                                elapsed)

    def _extend_group(self, chunk: int, width: int, batch: list) -> None:
        n = len(batch)
        slots = [slot for slot, *_ in batch]
        used = set(slots)
        spare = [s for s in range(self.max_slots) if s not in used]
        pad_slots = spare[:width - n]
        # per-round staging vectors: rewritten in full every batch and
        # handed straight to jnp.asarray — alloc cost is noise next to
        # the device transfer they feed (unlike the table gather below,
        # which reuses self._tables_scratch)
        chunk_tokens = np.zeros((width, chunk), np.int32)  # graft: disable=lint-hot-alloc
        offsets = np.zeros((width,), np.int32)  # graft: disable=lint-hot-alloc
        final_idx = np.zeros((width,), np.int32)  # graft: disable=lint-hot-alloc
        valid = np.zeros((width,), bool)  # graft: disable=lint-hot-alloc
        finish_arr = np.zeros((width,), bool)  # graft: disable=lint-hot-alloc
        for j, (slot, request, offset, finish) in enumerate(batch):
            piece = request.prompt[offset:offset + chunk]
            chunk_tokens[j, :len(piece)] = piece
            offsets[j] = offset
            final_idx[j] = len(request.prompt) - 1 - offset if finish \
                else 0
            valid[j] = True
            finish_arr[j] = finish
        if self.paged:
            # copy-on-extend (ISSUE 15): the chunk writes positions
            # [offset, offset+chunk) — any SHARED block there (the
            # near-seq-cap slide-back into a cached region) copies to
            # a fresh block first, so aliased readers keep their rows.
            # The recompute that follows is idempotent, so parity
            # holds either way; the copy preserves the ALIASED chain.
            pairs = []
            for slot, request, offset, finish in batch:
                self._ensure_coverage(slot, offset + chunk)
                pairs.extend(self._copy_on_write(slot, offset,
                                                 offset + chunk))
            if pairs:
                copied = self.pool.copy_blocks(
                    [src for src, _ in pairs],
                    [dst for _, dst in pairs])
                self.stats["cow_copies"] += len(pairs)
                self.stats["cow_copy_bytes"] += copied
            nbt = -(-self._cache_t // self.kv_block)
            tables_rows = self._tables_scratch[:width, :nbt]
            for j, slot in enumerate(slots):
                tables_rows[j] = self._tables_np[slot, :nbt]
            tables_rows[len(slots):] = 0  # pad rows must stay null
            (firsts, k_pools, v_pools, self._tokens, self._lengths,
             self._context) = self._extend_fn(chunk, width)(
                self.params, self.pool.k_pools, self.pool.v_pools,
                self._tokens, self._lengths, self._context,
                jnp.asarray(chunk_tokens), jnp.asarray(offsets),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid), jnp.asarray(finish_arr),
                jnp.asarray(final_idx), jnp.array(tables_rows),
                t_cap=self._cache_t)
            self.pool.k_pools, self.pool.v_pools = k_pools, v_pools
        else:
            (firsts, self._k, self._v, self._tokens, self._lengths,
             self._context) = self._extend_fn(chunk, width)(
                self.params, self._k, self._v, self._tokens,
                self._lengths, self._context, jnp.asarray(chunk_tokens),
                jnp.asarray(offsets),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid), jnp.asarray(finish_arr),
                jnp.asarray(final_idx))
        # HBM model for the extend program: weight stream + per-row
        # prefix read (dequantize up to offset) + chunk write
        row_bytes = self._kv_bytes_per_t // self.max_slots
        self.profiler.add_bytes(
            "extend_dispatch",
            self._param_bytes + sum(
                (offset + chunk) * row_bytes
                for _, _, offset, _ in batch))
        wave = []
        for j, (slot, request, offset, finish) in enumerate(batch):
            new_pos = len(request.prompt) if finish else offset + chunk
            self.stats["tokens_prefill"] += max(
                0, new_pos - request.prefill_pos)
            request.prefill_pos = new_pos
            if request.progress_callback is not None:
                # chunk streaming (ISSUE 17): the runtime harvests +
                # ships the chunk's finished blocks NOW — paged
                # harvest is a refcount bump, so this stays a host-
                # side table walk on the prefill hot path
                try:
                    request.progress_callback(request, bool(finish))
                except Exception:
                    self.logger.exception(
                        "progress callback failed for %s",
                        request.request_id)
            if request.journey is not None:
                request.journey.wave("extend")
            if finish:
                request.prefilling = False
                request.generated = []    # first token owed (wave)
                wave.append((j, request))
            self.stats["prefill_chunks"] += 1
            self._round_prefill_tokens += chunk
        if wave:
            # the finish rows' first tokens resolve at the NEXT round's
            # sync — the extend program runs behind the decode scan
            self._admit_waves.append((firsts, wave))

    @staticmethod
    def _next_pow2(n: int) -> int:
        return 1 << max(0, (n - 1).bit_length())

    # -- paged block tables (ISSUE 15) -------------------------------------
    def _ensure_coverage(self, slot: int, upto: int,
                         tenant: str | None = None) -> None:
        """Extend `slot`'s block table to cover positions [0, upto):
        allocate fresh pool blocks for the uncovered tail.  A no-op
        when already covered — the common decode round allocates one
        block only when the context crosses a block boundary.
        `tenant` attributes the allocation in the KV ledger; it
        defaults from the slot's request (admit-group callers pass it
        explicitly — the slot is not assigned yet there)."""
        block = self.kv_block
        need = min(-(-max(0, upto) // block), self._table_blocks)
        owned = self._slot_blocks[slot]
        if len(owned) >= need:
            return
        if tenant is None:
            request = self._slots[slot]
            tenant = request.tenant if request is not None else ""
        fresh = self.pool.alloc_blocks(need - len(owned),
                                       tenant=tenant)
        row = self._tables_np[slot]
        for j, block_id in enumerate(fresh, start=len(owned)):
            row[j] = block_id
        owned.extend(fresh)
        self._tables_dirty = True

    def _copy_on_write(self, slot: int, start: int, stop: int) -> list:
        """Make every block covering positions [start, stop) of `slot`
        exclusively owned before a write lands there: a SHARED block
        (refs > 1 — aliased by the prefix cache or another slot) is
        copied to a fresh block and the table repointed, so aliased
        readers never observe the mutation.  Returns (src, dst) pairs
        for the batched device copy.  The near-seq-cap final-chunk
        slide-back into a cached region is the one live writer of
        shared blocks; the common extend writes only owned tail
        blocks and copies nothing."""
        block = self.kv_block
        owned = self._slot_blocks[slot]
        row = self._tables_np[slot]
        request = self._slots[slot]
        tenant = request.tenant if request is not None else ""
        pairs = []
        for j in range(start // block,
                       min(-(-stop // block), len(owned))):
            old = owned[j]
            if self.pool.refs(old) <= 1:
                continue
            new = self.pool.alloc_blocks(1, tenant=tenant)[0]
            pairs.append((old, new))
            owned[j] = new
            row[j] = new
            self.pool.release_blocks([old], tenant=tenant)
            self._tables_dirty = True
        return pairs

    def _prepare_round_tables(self, occupied, num_steps: int):
        """Round prologue for the paged scan: extend every scanned
        slot's table to cover the positions this round's merge can
        write (entry length + num_steps tokens — per verify-block
        width in speculative mode), then hand back the device table
        slice at the current gather width."""
        per_step = 1 + self.speculate_k
        cap = self.max_seq if self.speculate_k \
            else self.max_seq + self.steps_per_sync
        for slot in occupied:
            request = self._slots[slot]
            owed = 0 if request.generated else 1
            current = len(request.prompt) + len(request.generated) \
                + owed
            self._ensure_coverage(
                slot, min(current + num_steps * per_step, cap))
        return self._tables_device(-(-self._cache_t // self.kv_block))

    def _tables_device(self, nb: int):
        """The device block-table slice [S, nb] the compiled programs
        gather through; rebuilt only when the host tables changed or
        the gather width moved (one small int32 transfer)."""
        if self._tables_dirty or nb != self._tables_dev_nb:
            self._tables_dev = jnp.asarray(self._tables_np[:, :nb])
            self._tables_dev_nb = nb
            self._tables_dirty = False
        return self._tables_dev

    def _release_slot_blocks(self, slot: int,
                             tenant: str | None = None) -> None:
        """Drop the slot's refs on every table block at retire.
        Blocks the harvest registered stay alive through the cache's
        own refs; purely-owned blocks return to the free list.
        `tenant` attributes the release in the KV ledger; it defaults
        from the slot's request (the admit-group unwind passes it —
        the slot was never assigned there)."""
        owned = self._slot_blocks[slot]
        if owned:
            if tenant is None:
                request = self._slots[slot]
                tenant = request.tenant if request is not None else ""
            self.pool.release_blocks(owned, tenant=tenant)
            self._slot_blocks[slot] = []
            self._tables_np[slot, :len(owned)] = 0
            self._tables_dirty = True

    def kv_wire_layout(self) -> tuple:
        """The storage layout as wire-safe string fields — what a
        cacheless paged decoder matches a KV transfer's declared donor
        layout against (PrefixKVCache.wire_layout's twin)."""
        return tuple(str(f) for f in self._kv_layout)

    def install_shipped_blocks(self, tokens, start_block: int,
                               blocks, tenant: str = "") -> tuple:
        """Direct slot-table install (ISSUE 15 satellite): write
        shipped chain blocks straight into fresh pool blocks and hand
        the ids to the caller for submit(..) via DecodeRequest
        aliasing — the cacheless decode pool's KV landing (no
        PrefixKVCache required).  Returns (covered_tokens, ids) for
        THESE blocks; ownership of the ids transfers to the caller
        (release on a refused submit).  `start_block` > 0 is the
        chunk-streamed accumulation path (ISSUE 17): the caller holds
        the ids for blocks [0, start_block) from earlier chunks and
        owns contiguity (the client's ordered-cursor guard) — this
        method only installs and sizes the given span.  Raises
        ValueError on geometry mismatch, before any row lands."""
        if not self.paged:
            raise ValueError(
                "install_shipped_blocks needs a paged decoder")
        start = int(start_block)
        if start < 0:
            raise ValueError(f"negative start_block {start}")
        block = self.kv_block
        count = min(len(blocks),
                    max(0, len(tokens) // block - start))
        entries = blocks[:count]
        for entry in entries:
            check_block_geometry(self._kv_layout, block, entry)
        if not entries:
            return 0, []
        ids = self.pool.alloc_blocks(len(entries), tenant=tenant)
        layers = self.config.num_layers
        self.pool.write_blocks(
            ids,
            [_stack_block_leaves([entry["k"][i] for entry in entries])
             for i in range(layers)],
            [_stack_block_leaves([entry["v"][i] for entry in entries])
             for i in range(layers)])
        return count * block, ids

    def _zero_caches(self, t: int | None = None) -> list:
        """Fresh per-layer slot caches at time extent `t` (default: the
        current serving extent) in the decoder's storage layout — plain
        [S, H, T, D] arrays, or {"q" int8, "s" f32 [S, H, T]} dicts in
        int8 mode."""
        config = self.config
        shape = (self.max_slots, config.num_kv_heads,
                 t or self._cache_t, config.head_dim)
        if self.kv_int8:
            return [{"q": jnp.zeros(shape, jnp.int8),
                     "s": jnp.zeros(shape[:3], jnp.float32)}
                    for _ in range(config.num_layers)]
        return [jnp.zeros(shape, config.dtype)
                for _ in range(config.num_layers)]

    def kv_cache_bytes(self) -> int:
        """Bytes currently allocated to the slot KV caches (values +
        scales) — the number kv_cache_dtype='int8' halves.  In paged
        mode this models the POOL: block arrays plus the int32
        tables (ISSUE 15) — shared prefixes are counted once, which is
        the capacity win block aliasing buys."""
        if self.paged:
            return self.pool.nbytes() + int(self._tables_np.nbytes)
        return int(sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for cache in self._k + self._v
            for leaf in jax.tree_util.tree_leaves(cache)))

    def _fit_caches(self, required_t: int) -> None:
        """Resize the cache time axis to the t_block multiple covering
        `required_t` (clamped to max_seq — plus steps_per_sync scratch
        headroom in block-KV mode, so a round-end side-buffer merge
        near the seq cap never clamps into a misaligned overwrite;
        the headroom cells are never attended.  The speculative merge
        scatters at absolute positions with out-of-bounds drop, so it
        needs no headroom).  A grow pads with zeros, a shrink slices —
        one whole-cache copy, amortized over the many rounds run at
        the new size.  No-op when already sized."""
        if self.speculate_k or KV_WRITE != "block":
            cap = self.max_seq
        else:
            cap = self.max_seq + self.steps_per_sync
        new_t = min(cap, -(-required_t // self.t_block) * self.t_block)
        if new_t == self._cache_t:
            return
        if self.paged:
            # the pool allocates per block on demand; only the gather
            # width (and with it the step's streamed bytes) tracks the
            # workload here — no device copy at all
            self._cache_t = new_t
            return
        key = (self._cache_t, new_t)
        if key not in self._resize_fns:
            if new_t > self._cache_t:
                pad = new_t - self._cache_t

                def grow_leaf(c, pad=pad):
                    # time axis is axis 2 for values [S,H,T,D] AND
                    # scales [S,H,T]
                    spec = [(0, 0)] * c.ndim
                    spec[2] = (0, pad)
                    return jnp.pad(c, spec)

                def resize(caches):
                    return [jax.tree.map(grow_leaf, c) for c in caches]
            else:
                def resize(caches, t=new_t):
                    return [jax.tree.map(lambda c: c[:, :, :t], cache)
                            for cache in caches]
            self._resize_fns[key] = jax.jit(resize,
                                            donate_argnums=(0,))
        self._k = self._resize_fns[key](self._k)
        self._v = self._resize_fns[key](self._v)
        self._cache_t = new_t

    def _admit_pending(self) -> None:
        """Admit as many pending requests as there are free slots, in
        FIFO order.  With a prefix cache bound (ISSUE 13), each request
        is longest-prefix-matched FIRST: a hit claims a slot, copies
        the cached K/V chain in (no forward pass), and streams only the
        uncached suffix via _advance_prefills.  Cold short prompts go
        through bucketed single-shot prefill groups; cold prompts
        longer than the largest bucket (only when prefill_chunk is set)
        claim a slot here and stream in chunks.  With prefill_budget
        set, bucketed admission stops for the round once the budget is
        spent — arrivals defer rather than stall active decode slots
        (prefix copies are exempt: they move bytes, not FLOPs)."""
        if self.prefix_cache is not None and \
                self.prefix_cache.promotions_ready:
            # land staged async promotions FIRST (ISSUE 17): a
            # prefetch kicked rounds ago becomes a plain cache hit
            # for the probes below — the hot-session admit stays a
            # table edit
            self.prefix_cache.poll_promotions()
        free = [s for s in range(self.max_slots)
                if self._slots[s] is None]
        if not free or not self._pending:
            return
        groups: dict[int, list[DecodeRequest]] = {}
        chunked: list[DecodeRequest] = []
        cached: list[DecodeRequest] = []
        deferred: list[DecodeRequest] = []      # in-flight dedup waits
        taken = 0
        index = 0
        pending = self._pending
        while index < len(pending):
            request = pending[index]
            if taken >= len(free):
                break
            if request.kv_block_ids:
                # direct slot-table install (ISSUE 15): the blocks are
                # already pool-resident — admit is a table edit plus
                # the suffix prefill, no cache probe involved
                cached.append(request)
                taken += 1
                index += 1
                continue
            if self.prefix_cache is not None and request.dedup_wait:
                # in-flight prefix dedup window (ISSUE 14 satellite,
                # PR 13 residue d): this request deferred behind a
                # same-batch duplicate whose prompt is prefilling NOW.
                # Its leader's prompt blocks land at the leader's
                # FIRST TOKEN (early harvest below), so the wait is a
                # couple of rounds, not a generation; a leader that
                # left without inserting (budget refusal, failure)
                # releases the follower to prefill cold.
                if self.prefix_cache.has(request.dedup_wait) or \
                        request.dedup_wait not in self._inflight_chains:
                    if self.prefix_cache.has(request.dedup_wait):
                        self.stats["dedup_shared"] += 1
                    request.dedup_wait = ""     # probe sees the truth
                else:
                    deferred.append(request)    # keeps its FIFO rank,
                    index += 1                  # consumes no slot
                    continue
            if self.prefix_cache is not None and \
                    not request.prefix_probed:
                if self.prefix_cache.tiered:
                    # sync promotion fallback (ISSUE 17): whatever of
                    # this prompt's chain still lives on the host
                    # tier must be device-resident BEFORE the probe —
                    # a staged prefetch installs instantly, an
                    # unkicked one stages inline; either way the
                    # acquire below sees the full chain
                    self.prefix_cache.promote_for(
                        request.tenant, request.prompt)
                block = self.prefix_cache.block_tokens
                if len(request.prompt) > block:
                    lead = self.prefix_cache.keys_for(
                        request.tenant, request.prompt[:block])[0]
                    leader = self._inflight_chains.get(lead)
                    if leader is not None and leader is not request \
                            and not self.prefix_cache.has(lead):
                        if leader.generated and leader.slot >= 0 and \
                                self._slots[leader.slot] is leader:
                            # the leader is PAST its first token: its
                            # prompt rows are device-written, so
                            # harvest NOW and let this request probe a
                            # hit this very round — a follower that
                            # arrives mid-generation must not wait out
                            # the leader's whole generation (review
                            # finding: dedup_hot is only consulted at
                            # the leader's first token)
                            try:
                                self._prefix_harvest_prompt(
                                    leader.slot, leader)
                            except Exception:
                                self.logger.exception(
                                    "late prompt harvest failed for "
                                    "%s", leader.request_id)
                        if not self.prefix_cache.has(lead):
                            # duplicate of an in-flight prompt: wait
                            # for the leader's early prompt harvest
                            # (at its first token) instead of missing
                            # the cache and prefilling it twice — the
                            # probe (and its hit/miss metrics) runs
                            # once, at the real admit
                            leader.dedup_hot = True
                            request.dedup_wait = lead
                            self.stats["dedup_deferred"] += 1
                            deferred.append(request)
                            index += 1
                            continue
                        self.stats["dedup_shared"] += 1
                request.prefix_probed = True
                keys, hit = self.prefix_cache.acquire(
                    request.tenant, request.prompt,
                    limit=len(request.prompt) - 1)
                if hit:
                    request.prefix_nodes = list(keys)
                    request.prefix_hit = hit
            if request.prefix_hit:
                cached.append(request)
            elif self.prefill_chunk and \
                    len(request.prompt) > self.prefill_buckets[-1]:
                chunked.append(request)
            else:
                bucket = self._bucket_for(len(request.prompt))
                if self.prefill_budget is not None and \
                        self._round_prefill_tokens > 0 and \
                        self._round_prefill_tokens + bucket > \
                        self.prefill_budget:
                    break        # FIFO: defer, don't reorder past it
                self._round_prefill_tokens += bucket
                groups.setdefault(bucket, []).append(request)
            if self.prefix_cache is not None and \
                    not request.prefix_hit and \
                    len(request.prompt) >= self.prefix_cache.block_tokens:
                # cold prompt with >= 1 complete block: register as a
                # potential dedup leader until its blocks are cached
                # (early harvest) or it retires
                request.inflight_key = self.prefix_cache.keys_for(
                    request.tenant,
                    request.prompt[:self.prefix_cache.block_tokens])[0]
                self._inflight_chains[request.inflight_key] = request
            taken += 1
            index += 1
        self._pending = deferred + pending[index:]
        admit_t = time.monotonic() if (chunked or groups or cached) \
            else 0.0
        if cached:
            self._fit_caches(max(max(self._prefix_write_len(r)
                                     for r in cached), self._cache_t))
            start = time.perf_counter()
            for request in cached:
                self._prefix_admit(free.pop(0), request, admit_t)
            self.stats["prefill_s"] += time.perf_counter() - start
        for request in chunked:
            slot = free.pop(0)
            request.slot = slot
            request.prefilling = True
            request.prefill_pos = 0
            self._slots[slot] = request
            self.stats["chunk_admits"] += 1
            if request.journey is not None:
                request.journey.admitted(admit_t, slot, "chunk-admit")
        if not groups:
            return
        # grow-only here (admits scatter [:bucket]); the round planner
        # owns shrinking, with full knowledge of every active context
        self._fit_caches(max(max(groups), self._cache_t))
        start = time.perf_counter()
        before = self.stats["tokens_prefill"]
        for bucket, requests in groups.items():
            while requests:
                width = min(self.max_slots,
                            self._next_pow2(len(requests)))
                chunk, requests = requests[:width], requests[width:]
                self._admit_group(bucket, width, chunk, free)
        elapsed = time.perf_counter() - start
        self.stats["prefill_s"] += elapsed
        self._note_prefill_rate(self.stats["tokens_prefill"] - before,
                                elapsed)

    # -- prefix/KV reuse (ISSUE 13) ----------------------------------------
    def _prefix_write_len(self, request: DecodeRequest) -> int:
        """Copy-in write extent for a hit: the chain's tokens padded up
        to a pow2 block count (bounded compile variants), capped at
        max_seq — near the cap the exact length compiles instead.
        (Paged admits move no KV rows at all; this extent then sizes
        only the speculative-context seed.)"""
        blocks = request.prefix_hit // self.kv_block
        padded = self._next_pow2(blocks) * self.kv_block
        return padded if padded <= self.max_seq else request.prefix_hit

    def _prefix_zero_block(self):
        """One shared zero pad block in the cache storage layout."""
        if self._prefix_pad is None:
            config = self.config
            shape = (config.num_kv_heads,
                     self.prefix_cache.block_tokens, config.head_dim)
            # memoized: allocates exactly once, then every pad reuses
            # the cached block
            if self.kv_int8:
                self._prefix_pad = {  # graft: disable=lint-hot-alloc
                    "q": jnp.zeros(shape, jnp.int8),
                    "s": jnp.zeros(shape[:2], jnp.float32)}
            else:
                self._prefix_pad = jnp.zeros(shape, config.dtype)  # graft: disable=lint-hot-alloc
        return self._prefix_pad

    def _prefix_admit(self, slot: int, request: DecodeRequest,
                      admit_t: float) -> None:
        """Admit a prefix-hit request: copy the pinned chain's K/V rows
        into the slot cache (one scatter program, queued behind the
        decode scan like every other prefill dispatch), seed the
        speculative context with the cached prompt tokens, and leave
        the slot mid-prefill at the hit boundary — _advance_prefills
        runs the uncached suffix, and the finish extend produces the
        first token exactly like a chunked admit.

        PAGED (ISSUE 15): no rows move at all — the chain's pool
        blocks alias into the slot's table (retain refs, host-side
        edit), the one device write left being the speculative-context
        seed.  prefix_copy_bytes stays 0; that delta vs the dense copy
        is the A/B the bench quotes."""
        if self.paged:
            self._prefix_admit_paged(slot, request, admit_t)
            return
        cache = self.prefix_cache
        config = self.config
        t_write = self._prefix_write_len(request)
        pad = (t_write - request.prefix_hit) // cache.block_tokens
        chain = cache.nodes(request.prefix_nodes)
        k_rows, v_rows = [], []
        for i in range(config.num_layers):
            k_blocks = [node.k_rows[i] for node in chain]
            v_blocks = [node.v_rows[i] for node in chain]
            if pad:
                zero = self._prefix_zero_block()
                k_blocks = k_blocks + [zero] * pad
                v_blocks = v_blocks + [zero] * pad
            k_rows.append(L.concat_kv_rows(k_blocks))
            v_rows.append(L.concat_kv_rows(v_blocks))
        # one context-row stage per prefix admit, straight to device
        ctx = np.zeros((t_write,), np.int32)  # graft: disable=lint-hot-alloc
        ctx[:request.prefix_hit] = request.prompt[:request.prefix_hit]
        fn = _prefix_copy_fn_for(config, t_write, self.kv_int8,
                                 bool(self.speculate_k))
        self._k, self._v, self._context = fn(
            self._k, self._v, self._context, k_rows, v_rows,
            jnp.asarray(slot, jnp.int32), jnp.asarray(ctx))
        # the copy writes t_write rows of K+V per layer — bytes, the
        # whole point: no weight stream, no FLOPs
        copy_bytes = t_write * self._kv_bytes_per_t // self.max_slots
        self.profiler.add_bytes("admit_dispatch", copy_bytes)
        self.stats["prefix_copy_bytes"] += copy_bytes
        request.slot = slot
        request.prefilling = True
        request.prefill_pos = request.prefix_hit
        self._slots[slot] = request
        self.stats["prefix_admits"] += 1
        if request.journey is not None:
            request.journey.prefix_hit_tokens = request.prefix_hit
            request.journey.admitted(admit_t, slot, "prefix-admit")

    def _prefix_admit_paged(self, slot: int, request: DecodeRequest,
                            admit_t: float) -> None:
        """Paged hit admit: alias the chain's pool blocks into the
        slot's block table.  Cache hits retain one pool ref per block
        for the slot; direct installs (kv_block_ids) transfer the
        caller's refs outright.  Zero KV bytes move — only the
        speculative drafter's context buffer still needs the cached
        prompt tokens written."""
        block = self.kv_block
        count = request.prefix_hit // block
        if request.kv_block_ids:
            ids = request.kv_block_ids[:count]
            request.kv_block_ids = []
        else:
            chain = self.prefix_cache.nodes(request.prefix_nodes)
            ids = [node.pool_id for node in chain[:count]]
            self.pool.retain(ids)
        row = self._tables_np[slot]
        for j, block_id in enumerate(ids):
            row[j] = block_id
        self._slot_blocks[slot] = list(ids)
        self._tables_dirty = True
        if self.speculate_k:
            t_write = self._prefix_write_len(request)
            # one context-row stage per prefix admit, straight to device
            ctx = np.zeros((t_write,), np.int32)  # graft: disable=lint-hot-alloc
            ctx[:request.prefix_hit] = \
                request.prompt[:request.prefix_hit]
            from .serving_paged import _paged_ctx_fn_for
            self._context = _paged_ctx_fn_for(t_write)(
                self._context, jnp.asarray(slot, jnp.int32),
                jnp.asarray(ctx))
            self.profiler.add_bytes("admit_dispatch", t_write * 4)
        request.slot = slot
        request.prefilling = True
        request.prefill_pos = request.prefix_hit
        self._slots[slot] = request
        self.stats["prefix_admits"] += 1
        if request.journey is not None:
            request.journey.prefix_hit_tokens = request.prefix_hit
            request.journey.admitted(admit_t, slot, "prefix-admit")

    def _prefix_harvest(self, slot: int, request: DecodeRequest) -> None:
        """Register a retiring request's K/V rows as cache blocks: the
        prompt plus every generated token but the LAST (an emitted
        token's K/V lands only when it is consumed as the next input,
        so the final token's rows are never written).  Already-cached
        blocks are skipped by key — no device work; the chain extends
        the request's own hit, so a conversation's next turn
        longest-matches its entire history (ISSUE 13)."""
        self._harvest_rows(slot, request.tenant,
                           list(request.prompt) +
                           [int(t) for t in request.generated[:-1]])

    def _prefix_harvest_prompt(self, slot: int,
                               request: DecodeRequest) -> None:
        """Early prompt harvest (ISSUE 14 satellite, PR 13 residue d):
        the moment a dedup-hot leader's first token resolves, its
        prompt rows are device-written — insert the prompt blocks NOW
        so same-batch duplicates share the prefill instead of waiting
        for the whole generation to retire.  The generated tokens
        still harvest at retire, as before."""
        self._harvest_rows(slot, request.tenant, list(request.prompt))
        request.dedup_hot = False
        if request.inflight_key and \
                self._inflight_chains.get(request.inflight_key) \
                is request:
            self._inflight_chains.pop(request.inflight_key, None)
            request.inflight_key = ""

    def harvest_progress(self, request: DecodeRequest) -> int:
        """Mid-prefill prompt harvest (ISSUE 17): register the
        complete blocks written so far ([0, prefill_pos)) with the
        prefix cache NOW, without waiting for retire — the chunk-
        streaming shipper reads them the moment the chunk's extend is
        dispatched.  Idempotent (already-cached keys skip); returns
        complete prompt blocks at the current position."""
        if self.prefix_cache is None or request.slot < 0 or \
                self._slots[request.slot] is not request:
            return 0
        pos = int(request.prefill_pos)
        self._harvest_rows(request.slot, request.tenant,
                           list(request.prompt[:pos]))
        return pos // self.prefix_cache.block_tokens

    def _harvest_rows(self, slot: int, tenant: str, tokens) -> None:
        cache = self.prefix_cache
        block = cache.block_tokens
        count = len(tokens) // block
        if count == 0:
            return
        keys = cache.keys_for(tenant, tokens[:count * block])
        start = 0
        while start < count and cache.has(keys[start]):
            start += 1
        if start >= count:
            return
        if self.paged:
            # zero-copy harvest (ISSUE 15): the slot's own pool blocks
            # BECOME the cache entries — retain + record key, no row
            # movement (the dense path's slice-out copy AND the hit's
            # later copy-in are both gone; the double write was
            # ROADMAP item 3 residue c)
            owned = self._slot_blocks[slot]
            parent = keys[start - 1] if start else ""
            for j in range(start, min(count, len(owned))):
                if not cache.insert_block(tenant, parent, keys[j],
                                          owned[j]):
                    break    # budget refused: stop, or children dangle
                parent = keys[j]
            return
        base, end = start * block, count * block
        layers = self.config.num_layers
        k_splits = [L.split_kv_blocks(
            L.slice_kv_rows(self._k[i], slot, base, end), block)
            for i in range(layers)]
        v_splits = [L.split_kv_blocks(
            L.slice_kv_rows(self._v[i], slot, base, end), block)
            for i in range(layers)]
        self.stats["harvest_copy_bytes"] += \
            (end - base) * self._kv_bytes_per_t // self.max_slots
        parent = keys[start - 1] if start else ""
        for j in range(start, count):
            inserted = cache.insert(
                tenant, parent, keys[j],
                [k_splits[i][j - start] for i in range(layers)],
                [v_splits[i][j - start] for i in range(layers)])
            if not inserted:
                break        # budget refused: stop, or children dangle
            parent = keys[j]

    def _admit_group(self, bucket: int, width: int,
                     chunk: list, free: list) -> None:
        n = len(chunk)
        slots = [free.pop(0) for _ in range(n)]
        # pad rows need DISTINCT slot ids (scatter order is unspecified
        # on collision): remaining free slots first, then occupied ones
        # — either way the pad row rewrites that slot's own content
        used = set(slots)
        spare = [s for s in range(self.max_slots) if s not in used]
        pad_slots = spare[:width - n]
        # per-admit staging vectors: same discipline as _extend_group —
        # rewritten in full, fed straight to jnp.asarray, alloc cost is
        # noise next to the transfer (table gather reuses scratch)
        prompts = np.zeros((width, bucket), np.int32)  # graft: disable=lint-hot-alloc
        true_lens = np.zeros((width,), np.int32)  # graft: disable=lint-hot-alloc
        valid = np.zeros((width,), bool)  # graft: disable=lint-hot-alloc
        for j, request in enumerate(chunk):
            prompts[j, :len(request.prompt)] = request.prompt
            true_lens[j] = len(request.prompt)
            valid[j] = True
        if self.paged:
            # each admitted slot gets fresh pool blocks padded to the
            # block boundary (dead cells past the prompt, same
            # invariant as the dense scatter's padding); pad rows stay
            # all-null and their writes drop inside the program
            nbb = -(-bucket // self.kv_block)
            tables_rows = self._tables_scratch[:width, :nbb]
            try:
                for j, slot in enumerate(slots):
                    self._ensure_coverage(slot, nbb * self.kv_block,
                                          tenant=chunk[j].tenant)
                    tables_rows[j] = self._tables_np[slot, :nbb]
            except Exception:
                # pool growth refused (HBM exhaustion, injected chaos
                # fault) before any slot was assigned: release what
                # the aborted wave already claimed and put the chunk
                # back at the HEAD of the queue — the escalation path
                # (alert -> drain) then evacuates these requests as
                # descriptors instead of silently losing them
                for slot, request in zip(slots, chunk):
                    self._release_slot_blocks(slot,
                                              tenant=request.tenant)
                free[:0] = slots
                self._pending[:0] = chunk
                raise
            tables_rows[len(slots):] = 0  # pad rows must stay null
            (firsts, k_pools, v_pools, self._tokens, self._lengths,
             self._context) = self._admit_fn(bucket, width)(
                self.params, self.pool.k_pools, self.pool.v_pools,
                self._tokens, self._lengths, self._context,
                jnp.asarray(prompts), jnp.asarray(true_lens),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid), jnp.array(tables_rows))
            self.pool.k_pools, self.pool.v_pools = k_pools, v_pools
        else:
            (firsts, self._k, self._v, self._tokens, self._lengths,
             self._context) = self._admit_fn(bucket, width)(
                self.params, self._k, self._v, self._tokens,
                self._lengths, self._context, jnp.asarray(prompts),
                jnp.asarray(true_lens),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid))
        # NO host sync here: the dispatch is async and queued BEHIND
        # this round's decode scan — fetching `firsts` now would stall
        # the host on prefill.  The request is live (slot assigned)
        # with its first token OWED; the stashed wave resolves it at
        # the NEXT round's sync, by which point the admit program has
        # run in the gap between scans.
        # HBM model for the admit program (executes in the sync gap
        # behind the scan; bytes attributed to the dispatching phase):
        # one weight stream plus the quantized/raw K+V rows written
        # for `width` slots over `bucket` positions
        self.profiler.add_bytes(
            "admit_dispatch",
            self._param_bytes +
            width * bucket * self._kv_bytes_per_t // self.max_slots)
        wave = []
        admit_t = time.monotonic()
        for j, request in enumerate(chunk):
            request.slot = slots[j]
            request.generated = []            # first token pending
            self._slots[slots[j]] = request
            self.stats["prefills"] += 1
            self.stats["tokens_prefill"] += len(request.prompt)
            if request.journey is not None:
                request.journey.admitted(admit_t, slots[j], "admit")
            wave.append((j, request))
        self._admit_waves.append((firsts, wave))

    def _finished(self, request: DecodeRequest, token: int) -> bool:
        return (self.eos_token is not None and token == self.eos_token) \
            or len(request.generated) >= request.max_new_tokens \
            or len(request.prompt) + len(request.generated) >= \
            self.max_seq - 1

    def _retire(self, slot: int) -> None:
        request = self._slots[slot]
        journey = request.journey
        if request.inflight_key and \
                self._inflight_chains.get(request.inflight_key) \
                is request:
            # dedup-leader registration ends with the request; a
            # follower still waiting re-probes and goes cold if the
            # harvest below is refused by the byte budget
            self._inflight_chains.pop(request.inflight_key, None)
        if self.prefix_cache is not None:
            # harvest BEFORE releasing the request's own pins: the hit
            # chain must stay resident while the new blocks link to it
            try:
                self._prefix_harvest(slot, request)
            except Exception:
                self.logger.exception("prefix harvest failed for %s",
                                      request.request_id)
            if request.prefix_nodes:
                self.prefix_cache.release(request.prefix_nodes)
                request.prefix_nodes = []
        if self.paged:
            # after the harvest retained what it keeps: drop the
            # slot's refs — cache-held blocks live on, purely-owned
            # ones return to the free list (the drain leak audit
            # asserts this reaches zero live blocks)
            self._release_slot_blocks(slot)
        self._slots[slot] = None
        self._note_active()
        self.stats["completed"] += 1
        count = len(request.generated)
        if count >= 2 and request.last_time > request.first_time:
            itl = (request.last_time - request.first_time) / (count - 1)
            self.itl_samples.append(itl)
            self._slo_sketch(
                "itl", journey.tenant if journey else "").observe(
                itl, exemplar=(journey.trace_id or request.request_id)
                if journey else None)
        if request.max_gap > 0:
            self.gap_samples.append(request.max_gap)
        if journey is not None:
            # completion closes the journey: deadline margin computed,
            # outcome counted per tenant, spans emitted under the
            # frame's trace id (flight-dumpable)
            self.journeys.finish(journey, request.last_time
                                 or time.monotonic())
        generated = request.generated
        if self.eos_token is not None and generated and \
                generated[-1] == self.eos_token:
            generated = generated[:-1]
        try:
            request.callback(request.request_id, generated)
        except Exception:
            self.logger.exception("callback failed for %s",
                                  request.request_id)

    def _round_plan(self, occupied) -> tuple:   # graft: hot-path
        """(num_steps, required_t, budgets): how long to run before the
        next host sync, the cache time-axis extent this round needs,
        and how many tokens each slot may still emit.

        num_steps is retire-aligned: with requests waiting, the round
        ends near the earliest slot retirement so the freed slot
        refills immediately instead of burning MXU lanes on a finished
        request.  With an empty queue it runs to the longest remaining
        budget — early exit would free lanes nothing is waiting for.
        The value is pow2-CEILed (jit cache stays at log2 variants;
        the in-scan budget mask absorbs the overshoot) — flooring
        would instead fragment a cycle's tail into extra host syncs,
        and a sync round-trip costs ~100 ms through a tunneled
        device."""
        budgets = self._budgets_np                # preallocated (hot)
        budgets.fill(0)
        max_len = 0
        # tokens one scan iteration can yield: 1, or the whole
        # speculative block when every draft lands
        per_step = 1 + self.speculate_k
        for slot in occupied:
            request = self._slots[slot]
            # a just-admitted slot still OWES its first token (resolved
            # from its admit wave at this round's sync): account for it
            # now or the device generates one extra token per request
            # that the host discards — phantom "useful" work
            owed = 0 if request.generated else 1
            generated = len(request.generated) + owed
            current = len(request.prompt) + generated
            # budget 0 is legal: a deferred admit whose OWED first token
            # already satisfies the request (max_new_tokens=1, or prompt
            # at the seq cap) needs no scan at all — pump() masks it out
            # so its extra device emissions are never counted as useful
            budgets[slot] = max(0, min(
                request.max_new_tokens - generated,
                self.max_seq - 1 - current))
            max_len = max(max_len, current)
        remaining = budgets[occupied]
        cap = int(remaining.min()) if self._pending \
            else int(remaining.max())
        num_steps = min(self.steps_per_sync,
                        self._next_pow2(max(1, -(-cap // per_step))))
        return (num_steps, max_len + num_steps * per_step + 1, budgets)

    def pump(self) -> None:   # graft: hot-path
        """One scheduling round, decode-first (ISSUE 7): dispatch the
        decode scan, THEN dispatch prefill work (admits + chunk
        extends) so it queues behind the scan on the device's in-order
        stream and executes while the host syncs the scan and resolves
        tokens — a decode round's sync never waits on prefill.  First
        tokens of slots admitted in EARLIER rounds resolve from their
        stashed admit outputs (device-complete by now), then this
        round's scan emissions deliver, then retirements fire."""
        if self._draining and not self._drained:
            # drain tick (ISSUE 19), at the round boundary: past the
            # deadline every live slot checkpoints (harvest + evacuate)
            # instead of decoding on; once idle the drain completes —
            # exactly once, before any new round is planned
            if self._drain_deadline is not None and \
                    time.monotonic() >= self._drain_deadline and \
                    self.active_count:
                self._drain_checkpoint()
            if self.idle:
                self._drain_finish()
        self._round_prefill_tokens = 0
        profiler = self.profiler
        profiler.begin_round()
        round_start = time.perf_counter()
        # mid-prefill slots hold a slot but don't decode yet
        active = self._active_np                  # preallocated (hot)
        any_active = False
        for slot in range(self.max_slots):
            request = self._slots[slot]
            live = request is not None and not request.prefilling
            active[slot] = live
            any_active = any_active or live
        waves_due = self._admit_waves
        self._admit_waves = []
        scanned = False
        if any_active:
            occupied = [s for s in range(self.max_slots) if active[s]]
            num_steps, required_t, budgets = self._round_plan(occupied)
            # never shrink the cache below a mid-prefill slot's written
            # extent — the decode slots alone may need less
            for request in self._slots:
                if request is not None and request.prefilling:
                    required_t = max(required_t, request.prefill_pos)
            self._fit_caches(required_t)
            # a slot with budget 0 (request satisfied by its owed first
            # token) needs no decode: masking it out of the scan keeps
            # its discarded emissions out of useful_steps
            scan_active = active & (budgets > 0)
            scanned = bool(scan_active.any())
        profiler.mark("plan")
        if scanned:
            self.stats["rounds"] += 1
            self.stats["occupancy_sum"] += float(active.mean())
            decode_start = time.perf_counter()
            eos = -1 if self.eos_token is None else int(self.eos_token)
            if self.paged:
                # every scanned slot's table must own the blocks this
                # round's merge will write (the common round allocates
                # only at block-boundary crossings); then one small
                # int32 transfer refreshes the device tables if dirty
                tables = self._prepare_round_tables(occupied,
                                                    num_steps)
                if self.speculate_k:
                    (emitted, emit_mask, self._tokens, self._lengths,
                     self._context, k_pools, v_pools) = self._step(
                        self.params, self._tokens, self._lengths,
                        jnp.array(scan_active), jnp.array(budgets),
                        self._context, self.pool.k_pools,
                        self.pool.v_pools, tables,
                        num_steps=num_steps, eos=eos,
                        t_cap=self._cache_t)
                else:
                    (emitted, emitted_active, self._tokens,
                     self._lengths, k_pools, v_pools) = self._step(
                        self.params, self._tokens, self._lengths,
                        jnp.array(scan_active), jnp.array(budgets),
                        self.pool.k_pools, self.pool.v_pools, tables,
                        num_steps=num_steps, eos=eos,
                        t_cap=self._cache_t)
                self.pool.k_pools, self.pool.v_pools = k_pools, v_pools
            elif self.speculate_k:
                (emitted, emit_mask, self._tokens, self._lengths,
                 self._context, self._k, self._v) = self._step(
                    self.params, self._tokens, self._lengths,
                    jnp.array(scan_active), jnp.array(budgets),
                    self._context, self._k, self._v,
                    num_steps=num_steps, eos=eos)
            else:
                (emitted, emitted_active, self._tokens, self._lengths,
                 self._k, self._v) = self._step(
                    self.params, self._tokens, self._lengths,
                    jnp.array(scan_active), jnp.array(budgets),
                    self._k, self._v, num_steps=num_steps, eos=eos)
            self.stats["steps"] += num_steps
            profiler.mark("spec_verify" if self.speculate_k
                          else "scan_dispatch")
        # prefill rides BETWEEN decode scans: dispatched after the scan,
        # it runs on device while the host below waits out the scan
        # sync and walks the emissions — off the decode critical path,
        # rationed by prefill_budget
        self._admit_pending()
        profiler.mark("admit_dispatch")
        self._advance_prefills()
        profiler.mark("extend_dispatch")
        if self._round_prefill_tokens > \
                self.stats["round_prefill_tokens_max"]:
            self.stats["round_prefill_tokens_max"] = \
                self._round_prefill_tokens
        # ONE host transfer for the whole round: scan sync arrays AND
        # every due admit wave's firsts ride one device_get — separate
        # np.asarray calls pay one tunnel round trip each (~115 ms on
        # a tunneled bench chip), per wave per round
        wave_firsts = [firsts for firsts, _ in waves_due]
        if scanned:
            if self.speculate_k:
                emitted, emit_mask, wave_firsts = jax.device_get(
                    (emitted, emit_mask, wave_firsts))
            else:
                emitted, emitted_active, wave_firsts = jax.device_get(
                    (emitted, emitted_active, wave_firsts))
            self.stats["decode_s"] += time.perf_counter() - decode_start
            round_bytes = num_steps * (
                self._param_bytes + self._kv_bytes_per_t * self._cache_t)
            self.stats["bytes_moved"] += round_bytes
            # the scan's device bytes execute under the sync wall —
            # host_sync is the phase whose duration they explain
            profiler.add_bytes("host_sync", round_bytes)
        elif wave_firsts:
            wave_firsts = jax.device_get(wave_firsts)
        profiler.mark("host_sync")
        # resolve deferred admits from EARLIER rounds: their prefill
        # programs ran before this round's scan on the in-order device
        # stream, so the fetch never waits on fresh work
        now = time.monotonic()
        for firsts, (_, wave) in zip(wave_firsts, waves_due):
            for j, request in wave:
                if self._slots[request.slot] is request and \
                        not request.generated:
                    self._deliver(request.slot, int(firsts[j]), now)
        profiler.mark("wave_resolve")
        if scanned:
            if self.speculate_k:
                self._deliver_spec(emitted, emit_mask, occupied,
                                   num_steps, now)
            else:
                # useful/wasted account DEVICE work (scan emissions the
                # host meant to use); tokens_decode counts what was
                # actually DELIVERED — they differ when a wave-resolved
                # first token retires the slot before its scan
                # emissions land (EOS as prefill argmax)
                useful = int(emitted_active[:, occupied].sum())
                self.stats["useful_steps"] += useful
                self.stats["wasted_steps"] += \
                    num_steps * len(occupied) - useful
                delivered = 0
                for k in range(emitted.shape[0]):
                    for slot in occupied:
                        request = self._slots[slot]
                        if request is None or not emitted_active[k, slot]:
                            continue
                        self._deliver(slot, int(emitted[k, slot]), now)
                        delivered += 1
                self.stats["tokens_decode"] += delivered
            profiler.mark("deliver")
        if scanned or wave_firsts or self._round_prefill_tokens:
            # working rounds only: idle pump ticks would drag the EWMA
            # toward the timer period and break the admission estimate
            # (and would dilute the profiler's phase attribution the
            # same way — idle ticks are abandoned, not committed)
            elapsed = time.perf_counter() - round_start
            self._round_ewma = elapsed if self._round_ewma is None \
                else 0.7 * self._round_ewma + 0.3 * elapsed
            profiler.commit_round()
        else:
            profiler.abandon_round()
            if self.pool is not None and self.idle:
                # idle-watermark pool release (ISSUE 16 satellite):
                # a shrink retraces the paged program family, so it
                # only ever fires on an idle tick — never inside a
                # serving window
                self.pool.maybe_shrink()
        if self.idle and self.on_idle is not None:
            self.on_idle()

    def _deliver_spec(self, emitted, emit_mask, occupied,
                      num_steps: int, now: float) -> None:
        """Walk a speculative round's [K, S, 1+k] emissions: per slot,
        the masked tokens in (iteration, position) order are exactly
        the greedy stream.  Also settles the speculation counters —
        spec_proposed/spec_accepted feed accept_rate(), and
        accepted_per_step is the mean tokens one verify iteration
        yielded (1.0 = speculation never helped)."""
        counts = emit_mask.sum(axis=2)[:, occupied]     # [K, |occ|]
        verify_steps = int((counts > 0).sum())
        self.stats["useful_steps"] += verify_steps
        self.stats["wasted_steps"] += \
            num_steps * len(occupied) - verify_steps
        self.stats["spec_proposed"] += self.speculate_k * verify_steps
        self.stats["spec_accepted"] += int(
            np.maximum(counts - 1, 0).sum())
        # tokens_decode counts DELIVERED tokens (a wave-resolved EOS
        # first token can retire the slot before its scan emissions
        # land — those are device work, not token flow)
        delivered = 0
        for slot in occupied:
            mask_slot = emit_mask[:, slot, :]
            if not mask_slot.any():
                continue
            for token in emitted[:, slot, :][mask_slot]:
                request = self._slots[slot]
                if request is None:
                    break                 # retired mid-burst (EOS)
                self._deliver(slot, int(token), now)
                delivered += 1
        self.stats["tokens_decode"] += delivered
        if self.stats["useful_steps"]:
            # mean tokens one emitting verify iteration yielded —
            # derived straight from the two source counters so it can
            # never drift from them
            self.stats["accepted_per_step"] = (
                self.stats["tokens_decode"] /
                self.stats["useful_steps"])

    def accept_rate(self) -> float:
        """Fraction of proposed draft tokens the verify step accepted
        (speculation quality; 0.0 when speculation is off or no drafts
        were scored)."""
        proposed = self.stats["spec_proposed"]
        return self.stats["spec_accepted"] / proposed if proposed \
            else 0.0

    def _deliver(self, slot: int, token: int, now: float) -> None:
        """Append one resolved token, stamping SLO timestamps: tokens
        land in per-sync bursts, so TTFT is submit→first burst and the
        stall metric is the worst gap BETWEEN bursts (same-burst tokens
        contribute no gap)."""
        request = self._slots[slot]
        journey = request.journey
        if not request.generated:
            request.first_time = now
            ttft = now - request.submit_time
            self.ttft_samples.append(ttft)
            # mergeable SLO surface (ISSUE 12): the same number the
            # deque keeps, but fleet-mergeable and carrying the worst
            # requests' trace ids as exemplars.  Split the population
            # by the prefill label (ISSUE 13/14): cached/cold from the
            # prefix probe, or an explicit override ("remote" for
            # disaggregated prefill) so each serving mode's attainment
            # is quotable on its own — a cache or a prefill pool that
            # only helps one population must not hide behind a blended
            # percentile.
            self._slo_sketch(
                "ttft", journey.tenant if journey else "",
                request.prefill_label or
                ("cached" if request.prefix_hit else "cold")).observe(
                ttft, exemplar=(journey.trace_id or request.request_id)
                if journey else None)
            if request.dedup_hot and self.prefix_cache is not None:
                # a same-batch duplicate is waiting on this prompt:
                # its rows are device-written now (the first token
                # resolved), so harvest them early instead of at
                # retire (ISSUE 14 satellite)
                try:
                    self._prefix_harvest_prompt(slot, request)
                except Exception:
                    self.logger.exception(
                        "early prompt harvest failed for %s",
                        request.request_id)
        elif now > request.last_time:
            request.max_gap = max(request.max_gap,
                                  now - request.last_time)
        if journey is not None:
            journey.token(now)
        request.generated.append(token)
        request.last_time = now
        if self._finished(request, token):
            self._retire(slot)

    def slo_stats(self) -> dict:
        """Measured per-request latency SLOs (milliseconds): TTFT
        (submit → first token burst), per-request mean inter-token
        latency, and the p95 of each request's worst inter-burst stall
        (what chunked prefill bounds)."""
        def pct(samples, q):
            return float(np.percentile(np.fromiter(samples, float),
                                       q)) * 1000.0 if samples else None
        return {
            "ttft_p50_ms": pct(self.ttft_samples, 50),
            "ttft_p95_ms": pct(self.ttft_samples, 95),
            "itl_p50_ms": pct(self.itl_samples, 50),
            "itl_p95_ms": pct(self.itl_samples, 95),
            "stall_p95_ms": pct(self.gap_samples, 95),
            "ttft_count": len(self.ttft_samples),
            "itl_count": len(self.itl_samples),
        }

    def slo_sketch_stats(self, prefill: str | None = None,
                         tenant: str | None = None) -> dict:
        """The SAME latency SLOs as slo_stats, but read from the
        mergeable sketches (ISSUE 12): p50/p95/p99 per kind merged
        across this decoder's tenants, plus the worst exemplar ids.
        This is the form the bench artifact quotes (lat_llama_ttft_*)
        — fleet-aggregatable, with per-request attribution behind
        every percentile.  `prefill` ("cached"/"cold"/"remote")
        restricts the TTFT merge to one population (ISSUE 13/14 — the
        conversation and disagg rungs' A/B surfaces); ITL has no
        prefill split.  `tenant` restricts BOTH kinds to one tenant's
        sketches (the disagg rung isolates its decode-stream ITL from
        the burst population this way)."""
        from .observe.sketch import merge_sketches
        out: dict = {}
        for kind in ("ttft", "itl"):
            merged = merge_sketches(
                sketch for (sketch_kind, sketch_tenant, sketch_prefill),
                sketch in self._slo_sketches.items()
                if sketch_kind == kind and
                (tenant is None or sketch_tenant == tenant) and
                (prefill is None or kind != "ttft" or
                 sketch_prefill == prefill))
            for q, suffix in ((0.5, "p50"), (0.95, "p95"),
                              (0.99, "p99")):
                value = merged.quantile(q) if merged is not None \
                    else None
                out[f"{kind}_{suffix}_ms"] = \
                    None if value is None else value * 1000.0
            out[f"{kind}_exemplars"] = [] if merged is None else \
                [e[1] for e in merged.worst_exemplars(4)]
        return out

    def clear_slo_sketches(self) -> None:
        """Drop sketch observations and exemplars (bench warmup
        boundary — compile-time TTFTs must not contaminate the
        measured percentiles, same rule as the sample deques)."""
        for sketch in self._slo_sketches.values():
            sketch.clear()

    def wasted_fraction(self) -> float:
        total = self.stats["useful_steps"] + self.stats["wasted_steps"]
        return self.stats["wasted_steps"] / total if total else 0.0

    def mean_occupancy(self) -> float:
        rounds = max(self.stats["rounds"], 1)
        return self.stats["occupancy_sum"] / rounds


@functools.lru_cache(maxsize=64)
def _admit_fn_for(config: LlamaConfig, bucket: int, width: int,
                  kv_int8: bool, speculative: bool):
    """Builder behind ContinuousDecoder._admit_fn (process-wide cache:
    decoders sharing a geometry share the jit object and its compiled
    executables)."""
    from .models.llama import init_llama_caches, llama_hidden

    def admit(params, k_caches, v_caches, tokens, lengths, context,
              prompts, true_lens, slots, valid):
        # prompts: [A, bucket]; slots: [A] DISTINCT slot ids (pad
        # rows point at other distinct slots and write back their
        # own current content — a no-op); valid: [A] bool.
        caches = init_llama_caches(config, width, bucket)
        hidden, caches = llama_hidden(params, config, prompts, caches)
        idx = jnp.maximum(true_lens - 1, 0)
        # select each prompt's last position BEFORE the vocab
        # projection: full prefill logits are [A, bucket, vocab] —
        # gigabytes at serving widths
        last_hidden = jnp.take_along_axis(
            hidden, idx[:, None, None], axis=1)[:, 0]
        last = L.linear_logits(params["lm_head"], last_hidden)
        firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
        mask = valid[:, None, None, None]
        mask_s = valid[:, None, None]
        for i, cache in enumerate(caches):
            if kv_int8:
                # quantize the exact prefill K/V once, scatter the
                # int8 rows + per-(row, head, position) scales
                kq = L.quantize_kv_cache(cache["k"])
                vq = L.quantize_kv_cache(cache["v"])
                k_caches[i] = {
                    "q": k_caches[i]["q"].at[slots, :, :bucket].set(
                        jnp.where(mask, kq["q"],
                                  k_caches[i]["q"][slots]
                                  [:, :, :bucket])),
                    "s": k_caches[i]["s"].at[slots, :, :bucket].set(
                        jnp.where(mask_s, kq["s"],
                                  k_caches[i]["s"][slots]
                                  [:, :, :bucket]))}
                v_caches[i] = {
                    "q": v_caches[i]["q"].at[slots, :, :bucket].set(
                        jnp.where(mask, vq["q"],
                                  v_caches[i]["q"][slots]
                                  [:, :, :bucket])),
                    "s": v_caches[i]["s"].at[slots, :, :bucket].set(
                        jnp.where(mask_s, vq["s"],
                                  v_caches[i]["s"][slots]
                                  [:, :, :bucket]))}
            else:
                cur_k = k_caches[i][slots][:, :, :bucket]
                cur_v = v_caches[i][slots][:, :, :bucket]
                k_caches[i] = k_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["k"], cur_k))
                v_caches[i] = v_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["v"], cur_v))
        tokens = tokens.at[slots].set(
            jnp.where(valid, firsts, tokens[slots]))
        lengths = lengths.at[slots].set(
            jnp.where(valid, true_lens, lengths[slots]))
        if speculative:
            # seed the drafter's history with the prompt itself
            context = context.at[slots, :bucket].set(
                jnp.where(valid[:, None], prompts,
                          context[slots][:, :bucket]))
        return firsts, k_caches, v_caches, tokens, lengths, context

    return jax.jit(
        admit, donate_argnames=("k_caches", "v_caches", "tokens",
                                "lengths", "context"))


@functools.lru_cache(maxsize=64)
def _prefix_copy_fn_for(config: LlamaConfig, t_write: int,
                        kv_int8: bool, speculative: bool):
    """Builder for the prefix-hit admit copy: writes a cached chain's
    concatenated K/V rows into ONE slot's cache rows [0, t_write) and
    seeds the speculative context with the cached prompt tokens.
    Compiled once per (geometry, pow2-padded write length) — pad rows
    are zeros landing at positions >= the hit, dead cells under the
    same overwrite-before-attend invariant as the admit scatter's
    padding.  No forward pass at all: a full-block hit costs one
    scatter where a cold admit costs a prefill."""

    def copy(k_caches, v_caches, context, k_rows, v_rows, slot,
             ctx_tokens):
        for i in range(config.num_layers):
            if kv_int8:
                k_caches[i] = {
                    "q": k_caches[i]["q"].at[slot, :, :t_write].set(
                        k_rows[i]["q"]),
                    "s": k_caches[i]["s"].at[slot, :, :t_write].set(
                        k_rows[i]["s"])}
                v_caches[i] = {
                    "q": v_caches[i]["q"].at[slot, :, :t_write].set(
                        v_rows[i]["q"]),
                    "s": v_caches[i]["s"].at[slot, :, :t_write].set(
                        v_rows[i]["s"])}
            else:
                k_caches[i] = k_caches[i].at[slot, :, :t_write].set(
                    k_rows[i])
                v_caches[i] = v_caches[i].at[slot, :, :t_write].set(
                    v_rows[i])
        if speculative:
            context = context.at[slot, :t_write].set(ctx_tokens)
        return k_caches, v_caches, context

    return jax.jit(copy, donate_argnames=("k_caches", "v_caches",
                                          "context"))


@functools.lru_cache(maxsize=64)
def _extend_fn_for(config: LlamaConfig, chunk_len: int, width: int,
                   kv_int8: bool, speculative: bool):
    """Builder behind ContinuousDecoder._extend_fn: advances up to
    `width` mid-prefill slots by one `chunk_len`-token chunk of their
    prompt — computes the chunk's K/V against the already-written
    cache prefix and scatters it in at each row's own offset.  Rows
    flagged `finish` also run the lm_head on their prompt's last
    position and land their first token + length in the device
    buffers, exactly like a single-shot admit — the first token then
    resolves from the stashed wave at the next round's sync.

    No reference counterpart: the reference's pipeline blocks a
    whole stream per frame (reference pipeline.py:650-712); chunked
    prefill is how an iteration-level scheduler keeps decode ITL
    flat under prompt-heavy load."""
    cos, sin = L.rope_frequencies(config.head_dim,
                                  config.max_seq_len,
                                  config.rope_theta)
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    group = num_heads // num_kv

    def extend(params, k_caches, v_caches, tokens, lengths, context,
               chunk_tokens, offsets, slots, valid, finish,
               final_idx):
        # chunk_tokens: [A, C]; offsets/slots/final_idx: [A];
        # valid/finish: [A] bool.  Pad rows (valid=False) point at
        # DISTINCT spare slots and write back their own content.
        x = L.embedding(params["embed"],
                        chunk_tokens).astype(config.dtype)
        t_cap = _cache_time(k_caches[0])
        # causal over prefix + chunk: query j (absolute position
        # offsets+j) sees cache positions <= offsets+j — earlier
        # chunks' rows are already in the cache, this chunk's are
        # written below before attending
        q_pos = offsets[:, None] + jnp.arange(chunk_len)[None, :]
        mask = (jnp.arange(t_cap)[None, None, :] <=
                q_pos[:, :, None])[:, None, None]   # [A,1,1,C,T]
        scale = 1.0 / jnp.sqrt(jnp.asarray(config.head_dim,
                                           jnp.float32))

        def write_rows(rows, chunk_kv, offs):
            # per-row dynamic_update_slice (vmapped): offsets stay
            # in-bounds by construction — the host slides a final
            # chunk BACK (recomputing overlap, idempotent) so
            # offset+C never exceeds the prompt length
            return jax.vmap(
                lambda row, kv, off: jax.lax.dynamic_update_slice(
                    row, kv, (0, off, 0)))(rows, chunk_kv, offs)

        def write_scales(rows, chunk_s, offs):
            return jax.vmap(
                lambda row, s, off: jax.lax.dynamic_update_slice(
                    row, s, (0, off)))(rows, chunk_s, offs)

        for i, layer in enumerate(params["layers"]):
            normed = L.rms_norm(layer["ln_attn"], x)
            q = L._split_heads(L.linear(layer["attn"]["q"], normed),
                               num_heads)
            k = L._split_heads(L.linear(layer["attn"]["k"], normed),
                               num_kv)
            v = L._split_heads(L.linear(layer["attn"]["v"], normed),
                               num_kv)
            q = L.apply_rope(q, cos, sin, offsets)
            k = L.apply_rope(k, cos, sin, offsets)
            if kv_int8:
                # attend over the DEQUANTIZED prefix (exactly the
                # int8-rounded values decode will read) + the
                # exact current chunk; store the chunk quantized.
                # Untouched positions keep their original q/s —
                # re-quantizing them would double-round.
                orig_kq = k_caches[i]["q"][slots]
                orig_ks = k_caches[i]["s"][slots]
                orig_vq = v_caches[i]["q"][slots]
                orig_vs = v_caches[i]["s"][slots]
                k_rows = write_rows(L.dequantize_kv_cache(
                    {"q": orig_kq, "s": orig_ks}, x.dtype), k, offsets)
                v_rows = write_rows(L.dequantize_kv_cache(
                    {"q": orig_vq, "s": orig_vs}, x.dtype), v, offsets)
            else:
                orig_k = k_caches[i][slots]    # [A, kv, T, D]
                orig_v = v_caches[i][slots]
                k_rows = write_rows(orig_k, k, offsets)
                v_rows = write_rows(orig_v, v, offsets)
            q_grouped = q.reshape(q.shape[0], num_kv, group,
                                  chunk_len, config.head_dim)
            scores = jnp.einsum(
                "akgcd,aktd->akgct", q_grouped, k_rows,
                preferred_element_type=jnp.float32) * scale
            scores = jnp.where(mask, scores, -1e30)
            weights = jax.nn.softmax(
                scores, axis=-1).astype(v_rows.dtype)
            out = jnp.einsum("akgct,aktd->akgcd", weights, v_rows,
                             preferred_element_type=jnp.float32)
            out = out.reshape(out.shape[0], num_heads, chunk_len,
                              config.head_dim).astype(x.dtype)
            x = x + L.linear(layer["attn"]["o"], L._merge_heads(out))
            x = x + llama_ffn(layer, config,
                              L.rms_norm(layer["ln_mlp"], x))
            keep = valid[:, None, None, None]
            if kv_int8:
                keep_s = valid[:, None, None]
                kq = L.quantize_kv_cache(k)
                vq = L.quantize_kv_cache(v)
                k_caches[i] = {
                    "q": k_caches[i]["q"].at[slots].set(
                        jnp.where(keep, write_rows(
                            orig_kq, kq["q"], offsets), orig_kq)),
                    "s": k_caches[i]["s"].at[slots].set(
                        jnp.where(keep_s, write_scales(
                            orig_ks, kq["s"], offsets), orig_ks))}
                v_caches[i] = {
                    "q": v_caches[i]["q"].at[slots].set(
                        jnp.where(keep, write_rows(
                            orig_vq, vq["q"], offsets), orig_vq)),
                    "s": v_caches[i]["s"].at[slots].set(
                        jnp.where(keep_s, write_scales(
                            orig_vs, vq["s"], offsets), orig_vs))}
            else:
                k_caches[i] = k_caches[i].at[slots].set(
                    jnp.where(keep, k_rows, orig_k))
                v_caches[i] = v_caches[i].at[slots].set(
                    jnp.where(keep, v_rows, orig_v))
        x = L.rms_norm(params["ln_out"], x)
        last_hidden = jnp.take_along_axis(
            x, final_idx[:, None, None], axis=1)[:, 0]
        last = L.linear_logits(params["lm_head"], last_hidden)
        firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
        apply = valid & finish
        tokens = tokens.at[slots].set(
            jnp.where(apply, firsts, tokens[slots]))
        lengths = lengths.at[slots].set(
            jnp.where(apply, offsets + final_idx + 1,
                      lengths[slots]))
        if speculative:
            ctx_rows = context[slots]               # [A, ctx]
            written = jax.vmap(
                lambda row, blk, off: jax.lax.dynamic_update_slice(
                    row, blk, (off,)))(ctx_rows, chunk_tokens,
                                       offsets)
            context = context.at[slots].set(
                jnp.where(valid[:, None], written, ctx_rows))
        return firsts, k_caches, v_caches, tokens, lengths, context

    return jax.jit(
        extend, donate_argnames=("k_caches", "v_caches", "tokens",
                                 "lengths", "context"))
