# Continuous batching for autoregressive decode: iteration-level
# scheduling of LLM generation on TPU.
#
# The BatchingScheduler (ops/batching.py) coalesces FIXED-size work —
# right for ASR chunks, wrong for generation, where requests finish at
# different steps and a fixed batch would idle the MXU on ragged tails.
# Here requests join and leave the running batch BETWEEN decode steps
# (the vLLM-style iteration-level discipline), built TPU-first:
#
#   * one compiled step function decodes one token for ALL slots —
#     [max_slots] is static, so XLA compiles exactly once; empty/done
#     slots compute garbage that is masked on the host (lane occupancy
#     is the scheduler's job, not the compiler's);
#   * per-slot KV caches live in one [S, H, T, D] buffer per layer with
#     per-slot lengths — no batch-global cursor, no reallocation;
#   * prefill is bucketed by prompt length (static shapes per bucket)
#     and scattered into a free slot's cache rows;
#   * K decode steps run per device round via lax.scan
#     (steps_per_sync), so the host syncs [K, S] tokens instead of
#     round-tripping per token — the tunnel/PCIe cost amortizes.
#
# The reference has no generation serving at all (its LLM hop is a
# blocking HTTP call: reference examples/speech/speech_elements.py:
# 155-172).  No counterpart file exists — this is TPU-native new build.

from __future__ import annotations

import dataclasses
import os
import time
from collections import deque
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from .models import layers as L
from .models.llama import LlamaConfig, llama_ffn
from .utils import get_logger

__all__ = ["ContinuousDecoder", "DecodeRequest", "measure_device_step"]


def measure_device_step(decoder, steps_per_sync: int = 64,
                        chains: int = 4) -> float:
    """Chained pure-device decode-step milliseconds for `decoder`'s
    compiled step at its serving shape: fresh zero caches, `chains`
    back-to-back rounds, ONE host sync at the end — separates device
    compute from the tunnel's ~0.1 s per-round dispatch+sync.  The
    single methodology behind the bench's llama_device_step_ms and
    tools/ab_w8.py, so the two cannot drift."""
    config = decoder.config
    slots = decoder.max_slots
    shape = (slots, config.num_kv_heads, decoder._cache_t,
             config.head_dim)
    k_probe = [jnp.zeros(shape, config.dtype)
               for _ in range(config.num_layers)]
    v_probe = [jnp.zeros(shape, config.dtype)
               for _ in range(config.num_layers)]
    tokens = jnp.ones((slots,), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    budgets = jnp.full((slots,), 1 << 30, jnp.int32)

    def chain(rounds):
        nonlocal k_probe, v_probe, tokens, lengths
        out = None
        for _ in range(rounds):
            out = decoder._step(decoder.params, tokens, lengths,
                                active, budgets, k_probe, v_probe,
                                num_steps=steps_per_sync, eos=-1)
            _, _, _, tokens, lengths, k_probe, v_probe = out
        np.asarray(out[0][-1])          # one sync for the chain
    chain(1)                             # warm (compile cache hit)
    start = time.perf_counter()
    chain(chains)
    return (time.perf_counter() - start) * 1000.0 / \
        (chains * steps_per_sync)

# decode attention inner loop for the "select" KV mode: "two_pass"
# (scores einsum + softmax + weights einsum), "online" (flash-style
# single sweep over time blocks with running max/sum — measured a
# wash, -1%), or "vpu" (broadcast-multiply reductions — measured 70%
# SLOWER; kept as the recorded dead end).  The "block" KV mode (the
# default) hardcodes the two-pass einsums — ATTENTION_IMPL has no
# effect there; tools/ab_decode_attention.py pins KV mode per case so
# the labels stay meaningful.
ATTENTION_IMPL = os.environ.get("AIKO_DECODE_ATTENTION", "two_pass")
# KV write strategy inside the decode scan:
#   "select" — masked full-cache select per step (r4 design);
#   "block"  — new tokens land in a small [S, H, num_steps, D] side
#              buffer at the SCAN index (uniform across slots, so XLA
#              updates in place) and merge into the main cache once per
#              round.  The main cache is READ-ONLY inside the scan.
# Measured motivation: step time vs cache size has a 37.9 us/T slope
# where the read-only floor is 10.2 us/T — the functional full-cache
# select makes XLA touch the KV ~4x per step (read for the select,
# write the full result, read again for attention, x K and V).  The
# side buffer removes every full-cache write from the hot loop:
# measured 14.6 -> 11.4 ms/step at the 1b/256-slot/cache-256 serving
# shape (slope 37.9 -> 16.1 us/T), identical tokens vs the oracle
# across the whole serving suite.  "select" remains available; it
# measures slightly better only below ~cache 180 (the merge+side
# fixed cost), where steps are cheap anyway.
KV_WRITE = os.environ.get("AIKO_DECODE_KV", "block")
_ONLINE_BLOCK = 256         # time-block per online-softmax sweep step


def _online_decode_attention(q_grouped, k_cache, v_cache, lengths,
                             scale):
    """Single-pass GQA decode attention: lax.scan over time blocks
    with a running (max, sum, accumulator) — the flash-attention
    recurrence expressed in plain XLA, so K and V stream through HBM
    exactly once instead of once per einsum pass.

    q_grouped: [S, Hkv, G, 1, D]; caches [S, Hkv, T, D]; lengths [S].
    Returns [S, Hkv, G, 1, D] f32."""
    slots_n, num_kv, group, num_q, head_dim = q_grouped.shape
    t_total = k_cache.shape[2]
    block = min(_ONLINE_BLOCK, t_total)
    num_blocks = -(-t_total // block)
    pad = num_blocks * block - t_total
    if pad:
        k_cache = jnp.pad(k_cache, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # [blocks, S, Hkv, block, D]: scan carries one block per step
    k_blocks = jnp.moveaxis(
        k_cache.reshape(slots_n, num_kv, num_blocks, block, head_dim),
        2, 0)
    v_blocks = jnp.moveaxis(
        v_cache.reshape(slots_n, num_kv, num_blocks, block, head_dim),
        2, 0)
    positions = jnp.arange(block)

    def body(carry, inputs):
        running_max, running_sum, acc = carry
        index, k_blk, v_blk = inputs
        t0 = index * block
        valid = ((t0 + positions)[None, :] <=
                 lengths[:, None])[:, None, None, None]   # [S,1,1,1,B]
        scores = jnp.einsum("skgqd,skbd->skgqb", q_grouped, k_blk,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid, scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        new_max = jnp.maximum(running_max, blk_max)
        # rescale the old accumulator into the new max's frame
        correction = jnp.exp(running_max - new_max)
        probs = jnp.exp(scores - new_max)
        new_sum = running_sum * correction + \
            jnp.sum(probs, axis=-1, keepdims=True)
        acc = acc * correction + jnp.einsum(
            "skgqb,skbd->skgqd", probs.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32)
        return (new_max, new_sum, acc), None

    init = (jnp.full((slots_n, num_kv, group, num_q, 1), -jnp.inf,
                     jnp.float32),
            jnp.zeros((slots_n, num_kv, group, num_q, 1), jnp.float32),
            jnp.zeros((slots_n, num_kv, group, num_q, head_dim),
                      jnp.float32))
    (final_max, final_sum, acc), _ = jax.lax.scan(
        body, init, (jnp.arange(num_blocks), k_blocks, v_blocks))
    return acc / jnp.maximum(final_sum, 1e-30)


@dataclasses.dataclass
class DecodeRequest:
    request_id: str
    prompt: list                      # token ids
    max_new_tokens: int
    callback: Callable                # callback(request_id, token_list)
    generated: list = dataclasses.field(default_factory=list)
    slot: int = -1
    # SLO timestamps (scheduler clock): TTFT = first_time - submit_time;
    # inter-token latency derives from (last_time - first_time) and the
    # per-sync max_gap (tokens arrive in sync bursts — the gap BETWEEN
    # syncs is what an admit stall inflates, so it is tracked per
    # request as the worst observed stall)
    submit_time: float = 0.0
    first_time: float = 0.0
    last_time: float = 0.0
    max_gap: float = 0.0
    # chunked-prefill progress: tokens of `prompt` already written to
    # the slot's KV cache; prefilling=True while chunks remain
    prefill_pos: int = 0
    prefilling: bool = False


def _slot_attention(layer, config: LlamaConfig, x, cos, sin,
                    k_cache, v_cache, lengths, write_mask):
    """One-token attention for all slots at per-slot positions.

    x: [S, 1, dim]; k_cache/v_cache: [S, H_kv, T, D]; lengths: [S] —
    tokens already in each slot's context (the new token's position).
    write_mask: [S] bool — only these slots commit their K/V write.  A
    mid-prefill slot's stale `lengths` entry points INTO the prompt
    region its extend chunks are writing; an unmasked write would
    corrupt it from the decode scan running between chunks.

    The cache's time axis T is NOT max_seq: the decoder allocates the
    smallest block multiple covering the longest active context and
    grows/shrinks the allocation between rounds (see
    ContinuousDecoder._fit_caches).  Decode is HBM-bound, so the step
    streams exactly the bytes the workload needs — an in-program
    slice of a max_seq cache was measured to MATERIALIZE the slice
    per layer per step (scatter output feeding a dot can't fuse),
    tripling the attention bytes."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q, k, v = _project_qkv(layer, config, x)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)

    # write this token's K/V at each slot's own cursor — as a masked
    # select, not a scatter: a per-slot-index scatter defeats XLA's
    # in-place/fusion analysis inside the scan, and the full-cache
    # select was measured ~12% faster per step at the serving shape
    hit = (jnp.arange(k_cache.shape[2])[None, None, :, None] ==
           lengths[:, None, None, None]) & \
        write_mask[:, None, None, None]             # [S,1,T,1]
    k_cache = jnp.where(hit, k[:, :, 0][:, :, None], k_cache)
    v_cache = jnp.where(hit, v[:, :, 0][:, :, None], v_cache)

    # attend over each slot's valid prefix (inclusive of the new token).
    # GQA via a grouped einsum against the SHARED KV — materializing
    # repeated caches (jnp.repeat) costs group× HBM and halves the slot
    # capacity that fits on a chip.  Scores run as bf16×bf16 MXU
    # matmuls with f32 ACCUMULATION (preferred_element_type) — an
    # explicit f32 upcast of the cache would double the HBM bytes of
    # the read, which is the dominant cost of the step.
    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group, num_q, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    if ATTENTION_IMPL == "online":
        out = _online_decode_attention(q_grouped, k_cache, v_cache,
                                       lengths, scale)
    elif ATTENTION_IMPL == "vpu":
        # broadcast-multiply + reduce instead of MXU matmuls: the
        # per-(slot, kv-head) matmul is M=group (tiny) — issue-rate
        # bound on the MXU; the VPU variant streams the same bytes as
        # fused elementwise reductions
        valid = (jnp.arange(k_cache.shape[2])[None] <=
                 lengths[:, None])[:, None, None]        # [S,1,1,T]
        q_sq = q_grouped[:, :, :, 0]                     # [S,kv,G,D]
        scores = jnp.sum(
            q_sq[:, :, :, None, :].astype(jnp.float32) *
            k_cache[:, :, None, :, :].astype(jnp.float32),
            axis=-1) * scale                             # [S,kv,G,T]
        scores = jnp.where(valid, scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1)
        out = jnp.sum(
            weights[..., None] *
            v_cache[:, :, None, :, :].astype(jnp.float32),
            axis=3)[:, :, :, None, :]                    # [S,kv,G,1,D]
    else:
        valid = (jnp.arange(k_cache.shape[2])[None] <=
                 lengths[:, None])[:, None, None, None]  # [S,1,1,1,T]
        scores = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_cache,
                            preferred_element_type=jnp.float32) * scale
        scores = jnp.where(valid, scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
        out = jnp.einsum("skgqt,sktd->skgqd", weights, v_cache,
                         preferred_element_type=jnp.float32)
    out = out.reshape(slots_n, num_heads, num_q, head_dim).astype(x.dtype)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_cache, v_cache)


def _slot_attention_block(layer, config: LlamaConfig, x, cos, sin,
                          k_cache, v_cache, k_side, v_side,
                          entry_lengths, lengths, step_index):
    """Block-KV decode attention: the main cache is read-only (tokens
    [0, entry_lengths) per slot); this round's tokens live in the side
    buffers at scan indices [0, step_index].  The new token's K/V is
    written to side[:, :, step_index] — a slot-uniform index, so XLA
    keeps the update in place instead of rewriting the whole cache."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    q, k, v = _project_qkv(layer, config, x)
    q = L.apply_rope(q, cos, sin, lengths)
    k = L.apply_rope(k, cos, sin, lengths)
    k_side = jax.lax.dynamic_update_slice_in_dim(k_side, k, step_index,
                                                 axis=2)
    v_side = jax.lax.dynamic_update_slice_in_dim(v_side, v, step_index,
                                                 axis=2)

    slots_n, num_q, head_dim = q.shape[0], q.shape[2], q.shape[3]
    group = num_heads // num_kv
    q_grouped = q.reshape(slots_n, num_kv, group, num_q, head_dim)
    scale = 1.0 / jnp.sqrt(jnp.asarray(head_dim, jnp.float32))
    main_valid = (jnp.arange(k_cache.shape[2])[None] <
                  entry_lengths[:, None])[:, None, None, None]
    side_positions = jnp.arange(k_side.shape[2])
    side_valid = ((side_positions[None] <= step_index) &
                  (side_positions[None] <
                   (lengths - entry_lengths + 1)[:, None])
                  )[:, None, None, None]
    scores_main = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_cache,
                             preferred_element_type=jnp.float32) * scale
    scores_side = jnp.einsum("skgqd,sktd->skgqt", q_grouped, k_side,
                             preferred_element_type=jnp.float32) * scale
    scores = jnp.concatenate(
        [jnp.where(main_valid, scores_main, -1e30),
         jnp.where(side_valid, scores_side, -1e30)], axis=-1)
    weights = jax.nn.softmax(scores, axis=-1).astype(v_cache.dtype)
    main_t = k_cache.shape[2]
    out = jnp.einsum("skgqt,sktd->skgqd", weights[..., :main_t],
                     v_cache, preferred_element_type=jnp.float32) + \
        jnp.einsum("skgqt,sktd->skgqd", weights[..., main_t:], v_side,
                   preferred_element_type=jnp.float32)
    out = out.reshape(slots_n, num_heads, num_q, head_dim).astype(x.dtype)
    return (L.linear(layer["attn"]["o"], L._merge_heads(out)),
            k_side, v_side)


def _fuse_decode_projections(params):
    """Opt-in serving transform: concatenate each layer's q/k/v weight
    matrices into one [dim, (Hq+2Hkv)*D] matmul and gate/up into one
    [dim, 2*ffn].  The decode step's activations are [S, 1, dim], so
    its ~14 projections per layer are tiny-M matmuls whose cost is
    issue/scheduling, not FLOPs — the W8 wash (see quantize_linear)
    showed weight BYTES aren't the binding constraint, so this halves
    the op COUNT instead.  Measured r5 at the 1b/256-slot shape
    (tools/ab_w8.py AB_MODE=fuse): device step 11.27 → 11.68 ms,
    +3.6% — a DEAD END on this toolchain (XLA already schedules the
    separate matmuls; the fused output's split costs more than the
    saved issues).  Kept opt-in as the recorded negative result, like
    serving's other measured dead ends.

    Tree shape after the transform: attn gains a "qkv" copy while
    q/k/v REMAIN (the prefill/extend attention goes through
    layers.mha, which needs them; _param_bytes excludes the duplicate
    so traffic stats stay honest); gate/up are REPLACED by "gate_up"
    outright, because every FFN path routes through llama_ffn →
    _swiglu, which prefers the fused form.  Biases are asserted
    absent — silently dropping one would corrupt outputs.  Outputs
    are not bit-identical to the unfused step (different f32
    accumulation tiling), so this stays opt-in and A/B-gated."""
    new_layers = []
    for layer in params["layers"]:
        layer = dict(layer)
        attn = dict(layer["attn"])
        # hard errors, not asserts: python -O strips asserts and a
        # silently-dropped bias corrupts every output (ADVICE r5)
        if any("b" in attn[k] for k in ("q", "k", "v")):
            raise ValueError(
                "fuse_projections drops linear biases; refusing")
        attn["qkv"] = {"w": jnp.concatenate(
            [attn["q"]["w"], attn["k"]["w"], attn["v"]["w"]], axis=1)}
        layer["attn"] = attn
        if "gate" in layer:
            if "b" in layer["gate"] or "b" in layer["up"]:
                raise ValueError(
                    "fuse_projections drops FFN biases; refusing")
            layer["gate_up"] = {"w": jnp.concatenate(
                [layer["gate"]["w"], layer["up"]["w"]], axis=1)}
            del layer["gate"], layer["up"]
        new_layers.append(layer)
    return {**params, "layers": new_layers}


def _project_qkv(layer, config: LlamaConfig, x):
    """q/k/v for the decode step: one fused matmul when the layer
    carries the _fuse_decode_projections form, else the canonical
    three."""
    num_heads, num_kv = config.num_heads, config.num_kv_heads
    attn = layer["attn"]
    if "qkv" in attn:
        qkv = L.linear(attn["qkv"], x)
        q_dim = num_heads * config.head_dim
        kv_dim = num_kv * config.head_dim
        q = L._split_heads(qkv[..., :q_dim], num_heads)
        k = L._split_heads(qkv[..., q_dim:q_dim + kv_dim], num_kv)
        v = L._split_heads(qkv[..., q_dim + kv_dim:], num_kv)
    else:
        q = L._split_heads(L.linear(attn["q"], x), num_heads)
        k = L._split_heads(L.linear(attn["k"], x), num_kv)
        v = L._split_heads(L.linear(attn["v"], x), num_kv)
    return q, k, v


def _build_step(config: LlamaConfig):
    """One decode iteration for every slot; jitted once, caches donated
    so the slot buffers update in place on device.  Params are an
    ARGUMENT, not a closure capture — captured trees get baked into the
    compiled program as constants (gigabytes for real checkpoints,
    duplicated per recompile)."""
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)

    def run_layers(params, tokens, attend):
        """Shared per-token transformer pass: `attend(i, layer,
        normed)` supplies each layer's attention output (and owns the
        cache-write strategy)."""
        x = L.embedding(params["embed"],
                        tokens[:, None]).astype(config.dtype)
        for i, layer in enumerate(params["layers"]):
            x = x + attend(i, layer, L.rms_norm(layer["ln_attn"], x))
            normed = L.rms_norm(layer["ln_mlp"], x)
            # dense SwiGLU or MoE per the config — MoE llama serves
            # through the same continuous-batching step
            x = x + llama_ffn(layer, config, normed)
        x = L.rms_norm(params["ln_out"], x)
        # bf16 operand reads (an f32 UPCAST of the [dim, vocab] head
        # would double the step's largest weight read), f32
        # accumulation KEPT f32 into the argmax — rounding the logits
        # to bf16 first can flip near-ties against the f32 oracle
        logits = L.linear_logits(params["lm_head"], x)
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)

    def one_token(params, tokens, lengths, active, k_caches, v_caches):
        new_k, new_v = [], []

        def attend(i, layer, normed):
            attn_out, k_c, v_c = _slot_attention(
                layer, config, normed, cos, sin, k_caches[i],
                v_caches[i], lengths, active)
            new_k.append(k_c)
            new_v.append(v_c)
            return attn_out

        next_tokens = run_layers(params, tokens, attend)
        return next_tokens, new_k, new_v

    def step_k(params, tokens, lengths, active, budgets, k_caches,
               v_caches, num_steps, eos):
        """lax.scan of `num_steps` iterations; returns tokens emitted
        [K, S] plus the per-step active mask [K, S] (True where the
        emitted token is real output).  A slot retires INSIDE the scan
        the moment it emits `eos` or exhausts its `budgets` entry —
        retired slots stop growing their context and their later
        emissions are discarded by the host, so a request finishing at
        step 1 of a 32-step round no longer pollutes its cache or
        miscounts as useful work."""
        def body(carry, _):
            tokens, lengths, active, budgets, k_caches, v_caches = carry
            next_tokens, k_caches, v_caches = one_token(
                params, tokens, lengths, active, k_caches, v_caches)
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            budgets = jnp.where(active, budgets - 1, budgets)
            still = active & (budgets > 0) & (next_tokens != eos)
            return ((next_tokens, lengths, still, budgets, k_caches,
                     v_caches), (next_tokens, active))

        tokens_in = tokens
        (tokens, lengths, active, budgets, k_caches, v_caches), \
            (emitted, emitted_active) = jax.lax.scan(
                body, (tokens, lengths, active, budgets, k_caches,
                       v_caches), None, length=num_steps)
        # tokens_in rides along so deferred admits resolve their first
        # token on THIS round's host sync instead of paying their own
        # device round-trip (see _admit_group)
        return (emitted, emitted_active, tokens_in, tokens, lengths,
                k_caches, v_caches)

    def step_k_block(params, tokens, lengths, active, budgets,
                     k_caches, v_caches, num_steps, eos):
        """Block-KV variant of step_k: the main caches stay READ-ONLY
        through the scan (closed over, never carried), this round's
        K/V land in [S, H, num_steps, D] side buffers at the scan
        index, and one per-slot merge runs after the scan.  Removes
        the per-step full-cache writes that made each step touch the
        KV ~4x (measured slope 37.9 us/T vs a 10.2 read-only floor)."""
        entry_lengths = lengths
        entry_active = active
        slots_n = tokens.shape[0]
        side_shape = (slots_n, config.num_kv_heads, num_steps,
                      config.head_dim)
        k_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]
        v_sides = [jnp.zeros(side_shape, config.dtype)
                   for _ in range(config.num_layers)]

        def body(carry, step_index):
            tokens, lengths, active, budgets, k_sides, v_sides = carry
            new_k, new_v = [], []

            def attend(i, layer, normed):
                attn_out, k_s, v_s = _slot_attention_block(
                    layer, config, normed, cos, sin, k_caches[i],
                    v_caches[i], k_sides[i], v_sides[i],
                    entry_lengths, lengths, step_index)
                new_k.append(k_s)
                new_v.append(v_s)
                return attn_out

            next_tokens = run_layers(params, tokens, attend)
            next_tokens = jnp.where(active, next_tokens, tokens)
            lengths = jnp.where(active, lengths + 1, lengths)
            budgets = jnp.where(active, budgets - 1, budgets)
            still = active & (budgets > 0) & (next_tokens != eos)
            return ((next_tokens, lengths, still, budgets, new_k,
                     new_v), (next_tokens, active))

        tokens_in = tokens
        (tokens, lengths, active, budgets, k_sides, v_sides), \
            (emitted, emitted_active) = jax.lax.scan(
                body, (tokens, lengths, active, budgets, k_sides,
                       v_sides), jnp.arange(num_steps))

        # one merge per round: each slot's side tokens scatter into the
        # main cache at its round-entry offset.  Rows past a slot's
        # actual take are garbage landing at positions beyond its
        # length — dead cells, overwritten before they are ever
        # attended (same invariant as the admit scatter's padding).
        # Slots INACTIVE at round entry must not merge at all: a
        # mid-prefill slot's stale length points INTO the prompt its
        # extend chunks are writing (the same corruption the select
        # mode's write_mask guards against).
        merge_at = jnp.minimum(entry_lengths,
                               k_caches[0].shape[2] - num_steps)
        keep = entry_active[:, None, None, None]

        def merge(cache, side):
            updated = jax.vmap(
                lambda row, srow, off: jax.lax.dynamic_update_slice(
                    row, srow, (0, off, 0)))(cache, side, merge_at)
            return jnp.where(keep, updated, cache)

        new_k_caches = [merge(k_caches[i], k_sides[i])
                        for i in range(config.num_layers)]
        new_v_caches = [merge(v_caches[i], v_sides[i])
                        for i in range(config.num_layers)]
        return (emitted, emitted_active, tokens_in, tokens, lengths,
                new_k_caches, new_v_caches)

    return jax.jit(step_k_block if KV_WRITE == "block" else step_k,
                   static_argnames=("num_steps", "eos"),
                   donate_argnames=("k_caches", "v_caches"))


class ContinuousDecoder:
    """Iteration-level scheduler over a fixed slot pool.

    submit() enqueues a request; drive it from the event engine
    (attach()) or call pump() manually.  Each pump round: admit pending
    prompts into free slots (bucketed prefill), run steps_per_sync
    decode iterations on device, sync the emitted tokens, retire
    EOS/max-length slots through their callbacks."""

    def __init__(self, params, config: LlamaConfig, max_slots: int = 8,
                 max_seq: int | None = None, eos_token: int | None = None,
                 prefill_buckets=(32, 128), steps_per_sync: int = 4,
                 t_block: int = 256, prefill_chunk: int | None = None,
                 prefill_budget: int | None = None,
                 weight_quant: bool = False,
                 fuse_projections: bool = False,
                 name: str = "decoder"):
        self.config = config
        # weight-only int8 (W8A16): every linear's weight tree-rewritten
        # to {w8, s} once here — linear()/linear_logits consume it
        # transparently across prefill, chunked extends, and the
        # decode scan.  Measured r5 (tools/ab_w8.py, 1b/256 slots):
        # device step −2.6%, closed loop a wash — a MEMORY lever
        # (1.24 GB of weights freed for more KV slots), not a speed
        # lever; see layers.quantize_linear for the numbers.  Greedy
        # outputs are NOT bit-identical to bf16 (int8 rounding), and
        # MoE routers are excluded (top-k flips).
        if fuse_projections:
            params = _fuse_decode_projections(params)
        if weight_quant:
            params = L.quantize_linear_tree(params)
        self.weight_quant = bool(weight_quant)
        self.fuse_projections = bool(fuse_projections)
        self.params = params
        self.max_slots = max_slots
        self.max_seq = max_seq or config.max_seq_len
        self.eos_token = eos_token
        self.steps_per_sync = steps_per_sync
        # chunked prefill: prompts longer than the largest bucket are
        # admitted to a slot immediately but their prefill runs
        # `prefill_chunk` tokens per pump round (a compiled cache-extend
        # program), so one long prompt stalls every active decode slot
        # by at most ~one chunk instead of its full length — the
        # classic inter-token-latency spike under prompt-heavy load.
        # Also lifts the prompt-length cap from the largest bucket to
        # max_seq.  None = single-shot bucketed prefill only.
        self.prefill_chunk = int(prefill_chunk) if prefill_chunk else None
        if self.prefill_chunk is not None and not \
                (1 <= self.prefill_chunk <= self.max_seq - 1):
            # fail at construction, not mid-serving with a wedged slot
            raise ValueError(
                f"prefill_chunk must be in [1, {self.max_seq - 1}], "
                f"got {self.prefill_chunk}")
        # per-round prefill token budget: bucketed admits stop (FIFO,
        # no reordering) and chunk advances are rationed once a round
        # has dispatched this much prefill work.  None = unbounded.
        self.prefill_budget = int(prefill_budget) if prefill_budget \
            else None
        # granularity of the attention time-axis cap: each round reads
        # cache[:, :, :t_cap] with t_cap the smallest multiple of
        # t_block covering the longest active context (one compiled
        # program per distinct t_cap — max_seq/t_block variants)
        self.t_block = max(1, int(t_block))
        # buckets beyond the cache's time axis would blow up the admit
        # scatter — clamp, dedupe, keep sorted
        self.prefill_buckets = tuple(sorted(
            {min(int(b), self.max_seq - 1) for b in prefill_buckets}))
        self.logger = get_logger(f"serving.{name}")
        self.on_idle = None          # hook: fires when the last slot
                                     # retires and nothing is pending

        # the cache TIME axis is allocated at the workload, not at
        # max_seq: it grows/shrinks in t_block steps to cover the
        # longest active context (_fit_caches).  HBM capacity AND
        # per-step bandwidth then scale with actual occupancy — a
        # max_seq allocation makes every decode step stream max_seq
        # worth of cache (an in-program slice doesn't help: it
        # materializes, measured 3× attention bytes).
        self._cache_t = min(self.t_block, self.max_seq)
        shape = (max_slots, config.num_kv_heads, self._cache_t,
                 config.head_dim)
        self._k = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        self._v = [jnp.zeros(shape, config.dtype)
                   for _ in range(config.num_layers)]
        self._tokens = jnp.zeros((max_slots,), jnp.int32)
        self._lengths = jnp.zeros((max_slots,), jnp.int32)
        self._resize_fns: dict = {}

        self._step = _build_step(config)
        self._prefill_fns: dict = {}
        self._slots: list[DecodeRequest | None] = [None] * max_slots
        self._pending: list[DecodeRequest] = []
        self._timer = None
        # HBM-traffic model for roofline reporting: every decode step
        # streams the full weight set (embed excluded — it's a gather
        # of S rows) plus the capped KV read
        itemsize = jnp.dtype(config.dtype).itemsize
        # fused qkv copies (fuse_projections) duplicate q/k/v byte-for
        # -byte — exclude them so bytes_moved counts what one step
        # actually streams, not both forms
        self._param_bytes = int(sum(
            int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if "embed" not in str(path[0]) and
            not any("qkv" in str(part) for part in path)))
        self._kv_bytes_per_t = (2 * config.num_layers * max_slots *
                                config.num_kv_heads * config.head_dim *
                                itemsize)
        # cumulative decode-loop counters, mirrored onto the process
        # metrics registry (serving_decoder_total{kind=...}) so the
        # bench and the dashboard metrics pane read the SAME numbers
        # the decoder increments (ISSUE 5)
        from .observe.metrics import MirroredStats
        self.stats = MirroredStats(
            {"steps": 0, "rounds": 0, "completed": 0,
             "prefills": 0, "occupancy_sum": 0.0,
             "prefill_s": 0.0, "decode_s": 0.0,
             "useful_steps": 0, "wasted_steps": 0,
             "bytes_moved": 0, "prefill_chunks": 0,
             "chunk_admits": 0, "round_prefill_tokens_max": 0},
            metric="serving_decoder_total",
            help="continuous-decoder events by kind",
            # levels and time-sums stay dict-only: a high-water mark or
            # a seconds accumulator inside an events-by-kind counter
            # family would make rate()/sum() over the family meaningless
            skip=("occupancy_sum", "prefill_s", "decode_s",
                  "round_prefill_tokens_max"))
        # SLO samples (seconds): TTFT per request, mean inter-token
        # latency per retired request, and each request's worst
        # inter-sync stall — the number chunked prefill bounds
        self.ttft_samples: deque = deque(maxlen=8192)
        self.itl_samples: deque = deque(maxlen=8192)
        self.gap_samples: deque = deque(maxlen=8192)
        self._round_prefill_tokens = 0

    # -- public API --------------------------------------------------------
    def submit(self, request_id: str, prompt, max_new_tokens: int,
               callback) -> None:
        # keep the TAIL on overflow (recent context matters most).
        # Without chunked prefill the largest bucket is a hard cap (an
        # oversized prompt would blow up _admit's scatter); with it,
        # long prompts stream in chunks and the cap is max_seq itself.
        if self.prefill_chunk:
            limit = self.max_seq - 1
        else:
            limit = min(self.max_seq - 1, self.prefill_buckets[-1])
        # empty prompts would seed generation from a pad position —
        # normalize to a single pad token at position 0
        prompt = ([int(t) for t in prompt] or [0])[-limit:]
        self._pending.append(DecodeRequest(
            request_id, prompt, int(max_new_tokens), callback,
            submit_time=time.monotonic()))

    def attach(self, engine, period: float = 0.002) -> int:
        # idempotent: re-attaching while already pumping (e.g. a stream
        # reopens during a deferred teardown) must not orphan the
        # first timer
        if self._timer is None:
            self._timer = engine.add_timer_handler(self.pump, period)
        return self._timer

    @property
    def attached(self) -> bool:
        return self._timer is not None

    def detach(self, engine) -> None:
        if self._timer is not None:
            engine.remove_timer_handler(self._timer)
            self._timer = None

    @property
    def active_count(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def idle(self) -> bool:
        return self.active_count == 0 and not self._pending

    # -- scheduling --------------------------------------------------------
    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.prefill_buckets[-1]

    def _admit_fn(self, bucket: int, width: int):
        """Compiled once per (bucket, admit-width): ONE program runs the
        stacked prefill for up to `width` prompts AND scatters their
        K/V prefixes, first tokens, and lengths into the slot buffers
        on device.  The host syncs a single [width] token array per
        group — not one round-trip per request (the per-request admit
        was a throughput cliff under bursty arrivals on thin links)."""
        key = (bucket, width)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        from .models.llama import init_llama_caches, llama_hidden

        def admit(params, k_caches, v_caches, tokens, lengths,
                  prompts, true_lens, slots, valid):
            # prompts: [A, bucket]; slots: [A] DISTINCT slot ids (pad
            # rows point at other distinct slots and write back their
            # own current content — a no-op); valid: [A] bool.
            caches = init_llama_caches(self.config, width, bucket)
            hidden, caches = llama_hidden(params, self.config,
                                          prompts, caches)
            idx = jnp.maximum(true_lens - 1, 0)
            # select each prompt's last position BEFORE the vocab
            # projection: full prefill logits are [A, bucket, vocab] —
            # gigabytes at serving widths
            last_hidden = jnp.take_along_axis(
                hidden, idx[:, None, None], axis=1)[:, 0]
            last = L.linear_logits(params["lm_head"], last_hidden)
            firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
            mask = valid[:, None, None, None]
            for i, cache in enumerate(caches):
                cur_k = k_caches[i][slots][:, :, :bucket]
                cur_v = v_caches[i][slots][:, :, :bucket]
                k_caches[i] = k_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["k"], cur_k))
                v_caches[i] = v_caches[i].at[slots, :, :bucket].set(
                    jnp.where(mask, cache["v"], cur_v))
            tokens = tokens.at[slots].set(
                jnp.where(valid, firsts, tokens[slots]))
            lengths = lengths.at[slots].set(
                jnp.where(valid, true_lens, lengths[slots]))
            return firsts, k_caches, v_caches, tokens, lengths

        compiled = jax.jit(
            admit, donate_argnames=("k_caches", "v_caches", "tokens",
                                    "lengths"))
        self._prefill_fns[key] = compiled
        return compiled

    def _extend_fn(self, width: int):
        """Compiled once per (chunk, admit-width, cache_t): advances up
        to `width` mid-prefill slots by one `prefill_chunk`-token chunk
        of their prompt — computes the chunk's K/V against the already
        -written cache prefix and scatters it in at each row's own
        offset.  Rows flagged `finish` also run the lm_head on their
        prompt's last position and land their first token + length in
        the device buffers, exactly like a single-shot admit — the
        first token then rides the next decode round's tokens_in sync.

        No reference counterpart: the reference's pipeline blocks a
        whole stream per frame (reference pipeline.py:650-712); chunked
        prefill is how an iteration-level scheduler keeps decode ITL
        flat under prompt-heavy load."""
        key = ("extend", width)
        if key in self._prefill_fns:
            return self._prefill_fns[key]
        config = self.config
        chunk_len = self.prefill_chunk
        cos, sin = L.rope_frequencies(config.head_dim,
                                      config.max_seq_len,
                                      config.rope_theta)
        num_heads, num_kv = config.num_heads, config.num_kv_heads
        group = num_heads // num_kv

        def extend(params, k_caches, v_caches, tokens, lengths,
                   chunk_tokens, offsets, slots, valid, finish,
                   final_idx):
            # chunk_tokens: [A, C]; offsets/slots/final_idx: [A];
            # valid/finish: [A] bool.  Pad rows (valid=False) point at
            # DISTINCT spare slots and write back their own content.
            x = L.embedding(params["embed"],
                            chunk_tokens).astype(config.dtype)
            t_cap = k_caches[0].shape[2]
            # causal over prefix + chunk: query j (absolute position
            # offsets+j) sees cache positions <= offsets+j — earlier
            # chunks' rows are already in the cache, this chunk's are
            # written below before attending
            q_pos = offsets[:, None] + jnp.arange(chunk_len)[None, :]
            mask = (jnp.arange(t_cap)[None, None, :] <=
                    q_pos[:, :, None])[:, None, None]   # [A,1,1,C,T]
            scale = 1.0 / jnp.sqrt(jnp.asarray(config.head_dim,
                                               jnp.float32))

            def write_rows(rows, chunk_kv, offs):
                # per-row dynamic_update_slice (vmapped): offsets stay
                # in-bounds by construction — the host slides a final
                # chunk BACK (recomputing overlap, idempotent) so
                # offset+C never exceeds the prompt length
                return jax.vmap(
                    lambda row, kv, off: jax.lax.dynamic_update_slice(
                        row, kv, (0, off, 0)))(rows, chunk_kv, offs)

            for i, layer in enumerate(params["layers"]):
                normed = L.rms_norm(layer["ln_attn"], x)
                q = L._split_heads(L.linear(layer["attn"]["q"], normed),
                                   num_heads)
                k = L._split_heads(L.linear(layer["attn"]["k"], normed),
                                   num_kv)
                v = L._split_heads(L.linear(layer["attn"]["v"], normed),
                                   num_kv)
                q = L.apply_rope(q, cos, sin, offsets)
                k = L.apply_rope(k, cos, sin, offsets)
                orig_k = k_caches[i][slots]        # [A, kv, T, D]
                orig_v = v_caches[i][slots]
                k_rows = write_rows(orig_k, k, offsets)
                v_rows = write_rows(orig_v, v, offsets)
                q_grouped = q.reshape(q.shape[0], num_kv, group,
                                      chunk_len, config.head_dim)
                scores = jnp.einsum(
                    "akgcd,aktd->akgct", q_grouped, k_rows,
                    preferred_element_type=jnp.float32) * scale
                scores = jnp.where(mask, scores, -1e30)
                weights = jax.nn.softmax(
                    scores, axis=-1).astype(v_rows.dtype)
                out = jnp.einsum("akgct,aktd->akgcd", weights, v_rows,
                                 preferred_element_type=jnp.float32)
                out = out.reshape(out.shape[0], num_heads, chunk_len,
                                  config.head_dim).astype(x.dtype)
                x = x + L.linear(layer["attn"]["o"], L._merge_heads(out))
                x = x + llama_ffn(layer, config,
                                  L.rms_norm(layer["ln_mlp"], x))
                keep = valid[:, None, None, None]
                k_caches[i] = k_caches[i].at[slots].set(
                    jnp.where(keep, k_rows, orig_k))
                v_caches[i] = v_caches[i].at[slots].set(
                    jnp.where(keep, v_rows, orig_v))
            x = L.rms_norm(params["ln_out"], x)
            last_hidden = jnp.take_along_axis(
                x, final_idx[:, None, None], axis=1)[:, 0]
            last = L.linear_logits(params["lm_head"], last_hidden)
            firsts = jnp.argmax(last, axis=-1).astype(jnp.int32)
            apply = valid & finish
            tokens = tokens.at[slots].set(
                jnp.where(apply, firsts, tokens[slots]))
            lengths = lengths.at[slots].set(
                jnp.where(apply, offsets + final_idx + 1,
                          lengths[slots]))
            return k_caches, v_caches, tokens, lengths

        compiled = jax.jit(
            extend, donate_argnames=("k_caches", "v_caches", "tokens",
                                     "lengths"))
        self._prefill_fns[key] = compiled
        return compiled

    def _advance_prefills(self) -> None:
        """Run one prompt chunk for mid-prefill slots (batched, pow2
        widths).  Slots closest to completion go first so in-flight
        prompts finish (and start emitting) sooner; prefill_budget
        rations how many rows advance per round."""
        if not self.prefill_chunk:
            return
        rows = [s for s in range(self.max_slots)
                if self._slots[s] is not None
                and self._slots[s].prefilling]
        if not rows:
            return
        chunk = self.prefill_chunk
        rows.sort(key=lambda s: len(self._slots[s].prompt) -
                  self._slots[s].prefill_pos)      # fewest remaining first
        if self.prefill_budget is not None:
            remaining = self.prefill_budget - self._round_prefill_tokens
            rows = rows[:max(1, remaining // chunk)]
        # the extend writes up to offset+chunk; never let a decode-side
        # shrink cut below it (grow-only: max with current size)
        need = 0
        plans = []
        for slot in rows:
            request = self._slots[slot]
            total = len(request.prompt)
            if total - request.prefill_pos > chunk:
                offset, finish = request.prefill_pos, False
            else:
                # final chunk slides BACK to end exactly at the prompt
                # tail: the overlap recomputes identical K/V
                # (idempotent) and offset+chunk stays <= total, so the
                # cache never needs to grow past the prompt itself
                offset, finish = max(0, total - chunk), True
            plans.append((slot, request, offset, finish))
            # the write extent is always offset+chunk (a prompt shorter
            # than one chunk pads — the garbage tail is overwritten by
            # decode tokens before it is ever attended)
            need = max(need, offset + chunk)
        self._fit_caches(max(need, self._cache_t))
        start = time.perf_counter()
        while plans:
            width = min(self.max_slots, self._next_pow2(len(plans)))
            batch, plans = plans[:width], plans[width:]
            self._extend_group(width, batch)
        self.stats["prefill_s"] += time.perf_counter() - start

    def _extend_group(self, width: int, batch: list) -> None:
        chunk = self.prefill_chunk
        n = len(batch)
        slots = [slot for slot, *_ in batch]
        used = set(slots)
        spare = [s for s in range(self.max_slots) if s not in used]
        pad_slots = spare[:width - n]
        chunk_tokens = np.zeros((width, chunk), np.int32)
        offsets = np.zeros((width,), np.int32)
        final_idx = np.zeros((width,), np.int32)
        valid = np.zeros((width,), bool)
        finish_arr = np.zeros((width,), bool)
        for j, (slot, request, offset, finish) in enumerate(batch):
            piece = request.prompt[offset:offset + chunk]
            chunk_tokens[j, :len(piece)] = piece
            offsets[j] = offset
            final_idx[j] = len(request.prompt) - 1 - offset if finish \
                else 0
            valid[j] = True
            finish_arr[j] = finish
        self._k, self._v, self._tokens, self._lengths = \
            self._extend_fn(width)(
                self.params, self._k, self._v, self._tokens,
                self._lengths, jnp.asarray(chunk_tokens),
                jnp.asarray(offsets),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid), jnp.asarray(finish_arr),
                jnp.asarray(final_idx))
        for slot, request, offset, finish in batch:
            request.prefill_pos = len(request.prompt) if finish \
                else offset + chunk
            if finish:
                request.prefilling = False
                request.generated = []    # first token owed (tokens_in)
            self.stats["prefill_chunks"] += 1
            self._round_prefill_tokens += chunk

    @staticmethod
    def _next_pow2(n: int) -> int:
        return 1 << max(0, (n - 1).bit_length())

    def _fit_caches(self, required_t: int) -> None:
        """Resize the cache time axis to the t_block multiple covering
        `required_t` (clamped to max_seq — plus steps_per_sync scratch
        headroom in block-KV mode, so a round-end side-buffer merge
        near the seq cap never clamps into a misaligned overwrite;
        the headroom cells are never attended).  A grow pads with
        zeros, a shrink slices — one whole-cache copy, amortized over
        the many rounds run at the new size.  No-op when already
        sized."""
        cap = self.max_seq + (self.steps_per_sync
                              if KV_WRITE == "block" else 0)
        new_t = min(cap, -(-required_t // self.t_block) * self.t_block)
        if new_t == self._cache_t:
            return
        key = (self._cache_t, new_t)
        if key not in self._resize_fns:
            if new_t > self._cache_t:
                pad = new_t - self._cache_t

                def resize(caches, pad=pad):
                    return [jnp.pad(c, ((0, 0), (0, 0), (0, pad),
                                        (0, 0))) for c in caches]
            else:
                def resize(caches, t=new_t):
                    return [c[:, :, :t] for c in caches]
            self._resize_fns[key] = jax.jit(resize,
                                            donate_argnums=(0,))
        self._k = self._resize_fns[key](self._k)
        self._v = self._resize_fns[key](self._v)
        self._cache_t = new_t

    def _admit_pending(self) -> None:
        """Admit as many pending requests as there are free slots, in
        FIFO order.  Short prompts go through bucketed single-shot
        prefill groups; prompts longer than the largest bucket (only
        when prefill_chunk is set) just claim a slot here and stream in
        via _advance_prefills.  With prefill_budget set, bucketed
        admission stops for the round once the budget is spent —
        arrivals defer rather than stall active decode slots."""
        free = [s for s in range(self.max_slots)
                if self._slots[s] is None]
        if not free or not self._pending:
            return
        groups: dict[int, list[DecodeRequest]] = {}
        chunked: list[DecodeRequest] = []
        taken = 0
        for request in self._pending:
            if taken >= len(free):
                break
            if self.prefill_chunk and \
                    len(request.prompt) > self.prefill_buckets[-1]:
                chunked.append(request)
            else:
                bucket = self._bucket_for(len(request.prompt))
                if self.prefill_budget is not None and \
                        self._round_prefill_tokens > 0 and \
                        self._round_prefill_tokens + bucket > \
                        self.prefill_budget:
                    break        # FIFO: defer, don't reorder past it
                self._round_prefill_tokens += bucket
                groups.setdefault(bucket, []).append(request)
            taken += 1
        del self._pending[:taken]
        for request in chunked:
            slot = free.pop(0)
            request.slot = slot
            request.prefilling = True
            request.prefill_pos = 0
            self._slots[slot] = request
            self.stats["chunk_admits"] += 1
        if not groups:
            return
        # grow-only here (admits scatter [:bucket]); the round planner
        # owns shrinking, with full knowledge of every active context
        self._fit_caches(max(max(groups), self._cache_t))
        start = time.perf_counter()
        for bucket, requests in groups.items():
            while requests:
                width = min(self.max_slots,
                            self._next_pow2(len(requests)))
                chunk, requests = requests[:width], requests[width:]
                self._admit_group(bucket, width, chunk, free)
        self.stats["prefill_s"] += time.perf_counter() - start

    def _admit_group(self, bucket: int, width: int,
                     chunk: list, free: list) -> None:
        n = len(chunk)
        slots = [free.pop(0) for _ in range(n)]
        # pad rows need DISTINCT slot ids (scatter order is unspecified
        # on collision): remaining free slots first, then occupied ones
        # — either way the pad row rewrites that slot's own content
        used = set(slots)
        spare = [s for s in range(self.max_slots) if s not in used]
        pad_slots = spare[:width - n]
        prompts = np.zeros((width, bucket), np.int32)
        true_lens = np.zeros((width,), np.int32)
        valid = np.zeros((width,), bool)
        for j, request in enumerate(chunk):
            prompts[j, :len(request.prompt)] = request.prompt
            true_lens[j] = len(request.prompt)
            valid[j] = True
        firsts, self._k, self._v, self._tokens, self._lengths = \
            self._admit_fn(bucket, width)(
                self.params, self._k, self._v, self._tokens,
                self._lengths, jnp.asarray(prompts),
                jnp.asarray(true_lens),
                jnp.asarray(slots + pad_slots, jnp.int32),
                jnp.asarray(valid))
        # NO host sync here: the dispatch is async and the first token
        # already lives in the device tokens buffer, which the next
        # decode round returns as `tokens_in` — fetching `firsts` now
        # would cost a full tunnel round-trip per admit group.  The
        # request is live (slot assigned) with its first token OWED;
        # pump() resolves it from the round sync (generated[0]).
        for j, request in enumerate(chunk):
            request.slot = slots[j]
            request.generated = []            # first token pending
            self._slots[slots[j]] = request
            self.stats["prefills"] += 1

    def _finished(self, request: DecodeRequest, token: int) -> bool:
        return (self.eos_token is not None and token == self.eos_token) \
            or len(request.generated) >= request.max_new_tokens \
            or len(request.prompt) + len(request.generated) >= \
            self.max_seq - 1

    def _retire(self, slot: int) -> None:
        request = self._slots[slot]
        self._slots[slot] = None
        self.stats["completed"] += 1
        count = len(request.generated)
        if count >= 2 and request.last_time > request.first_time:
            self.itl_samples.append(
                (request.last_time - request.first_time) / (count - 1))
        if request.max_gap > 0:
            self.gap_samples.append(request.max_gap)
        generated = request.generated
        if self.eos_token is not None and generated and \
                generated[-1] == self.eos_token:
            generated = generated[:-1]
        try:
            request.callback(request.request_id, generated)
        except Exception:
            self.logger.exception("callback failed for %s",
                                  request.request_id)

    def _round_plan(self, occupied) -> tuple:
        """(num_steps, required_t, budgets): how long to run before the
        next host sync, the cache time-axis extent this round needs,
        and how many tokens each slot may still emit.

        num_steps is retire-aligned: with requests waiting, the round
        ends near the earliest slot retirement so the freed slot
        refills immediately instead of burning MXU lanes on a finished
        request.  With an empty queue it runs to the longest remaining
        budget — early exit would free lanes nothing is waiting for.
        The value is pow2-CEILed (jit cache stays at log2 variants;
        the in-scan budget mask absorbs the overshoot) — flooring
        would instead fragment a cycle's tail into extra host syncs,
        and a sync round-trip costs ~100 ms through a tunneled
        device."""
        budgets = np.zeros((self.max_slots,), np.int32)
        max_len = 0
        for slot in occupied:
            request = self._slots[slot]
            # a just-admitted slot still OWES its first token (resolved
            # at the next round sync): account for it now or the device
            # generates one extra token per request that the host
            # discards — phantom "useful" work in the stats
            owed = 0 if request.generated else 1
            generated = len(request.generated) + owed
            current = len(request.prompt) + generated
            # budget 0 is legal: a deferred admit whose OWED first token
            # already satisfies the request (max_new_tokens=1, or prompt
            # at the seq cap) only needs this round's tokens_in sync —
            # pump() masks it out of the scan so its extra device
            # emissions are never counted as useful work
            budgets[slot] = max(0, min(
                request.max_new_tokens - generated,
                self.max_seq - 1 - current))
            max_len = max(max_len, current)
        remaining = budgets[list(occupied)]
        cap = int(remaining.min()) if self._pending \
            else int(remaining.max())
        num_steps = min(self.steps_per_sync, self._next_pow2(max(1, cap)))
        return num_steps, max_len + num_steps + 1, budgets

    def pump(self) -> None:
        """One scheduling round: admit, advance prefill chunks, decode
        K steps, retire."""
        self._round_prefill_tokens = 0
        self._admit_pending()
        self._advance_prefills()
        self.stats["round_prefill_tokens_max"] = max(
            self.stats["round_prefill_tokens_max"],
            self._round_prefill_tokens)
        # mid-prefill slots hold a slot but don't decode yet
        active = np.array([r is not None and not r.prefilling
                           for r in self._slots])
        if not active.any():
            # admits can retire instantly (EOS as first token, 1-token
            # budget, prompt at the seq cap) — the idle hook must still
            # fire on this exit path or teardown callbacks never run
            if self.idle and self.on_idle is not None:
                self.on_idle()
            return
        occupied = [s for s in range(self.max_slots) if active[s]]
        num_steps, required_t, budgets = self._round_plan(occupied)
        # never shrink the cache below a mid-prefill slot's written
        # extent — the decode slots alone may need less
        for request in self._slots:
            if request is not None and request.prefilling:
                required_t = max(required_t, request.prefill_pos)
        self._fit_caches(required_t)
        self.stats["rounds"] += 1
        self.stats["occupancy_sum"] += float(active.mean())
        decode_start = time.perf_counter()
        # a slot with budget 0 (request satisfied by its owed first
        # token) stays in `occupied` for the tokens_in resolution below
        # but must not decode: masking it out of the scan keeps its
        # discarded emissions out of useful_steps
        scan_active = active & (budgets > 0)
        (emitted, emitted_active, tokens_in, self._tokens,
         self._lengths, self._k, self._v) = self._step(
            self.params, self._tokens, self._lengths,
            jnp.asarray(scan_active), jnp.asarray(budgets),
            self._k, self._v, num_steps=num_steps,
            eos=-1 if self.eos_token is None else int(self.eos_token))
        self.stats["steps"] += num_steps
        # ONE host transfer for all three sync arrays: separate
        # np.asarray calls pay one tunnel round trip each (~115 ms on
        # a tunneled bench chip, 3x per round)
        emitted, emitted_active, tokens_in = jax.device_get(
            (emitted, emitted_active, tokens_in))
        self.stats["decode_s"] += time.perf_counter() - decode_start
        useful = int(emitted_active[:, occupied].sum())
        self.stats["useful_steps"] += useful
        self.stats["wasted_steps"] += num_steps * len(occupied) - useful
        self.stats["bytes_moved"] += num_steps * (
            self._param_bytes + self._kv_bytes_per_t * self._cache_t)
        # resolve deferred admits: a freshly-admitted slot's first token
        # (prefill argmax) arrives as this round's tokens_in — no
        # per-admit sync was paid for it
        now = time.monotonic()
        for slot in occupied:
            request = self._slots[slot]
            if request is not None and not request.generated:
                self._deliver(slot, int(tokens_in[slot]), now)
        for k in range(emitted.shape[0]):
            for slot in occupied:
                request = self._slots[slot]
                if request is None or not emitted_active[k, slot]:
                    continue
                self._deliver(slot, int(emitted[k, slot]), now)
        if self.idle and self.on_idle is not None:
            self.on_idle()

    def _deliver(self, slot: int, token: int, now: float) -> None:
        """Append one resolved token, stamping SLO timestamps: tokens
        land in per-sync bursts, so TTFT is submit→first burst and the
        stall metric is the worst gap BETWEEN bursts (same-burst tokens
        contribute no gap)."""
        request = self._slots[slot]
        if not request.generated:
            request.first_time = now
            self.ttft_samples.append(now - request.submit_time)
        elif now > request.last_time:
            request.max_gap = max(request.max_gap,
                                  now - request.last_time)
        request.generated.append(token)
        request.last_time = now
        if self._finished(request, token):
            self._retire(slot)

    def slo_stats(self) -> dict:
        """Measured per-request latency SLOs (milliseconds): TTFT
        (submit → first token burst), per-request mean inter-token
        latency, and the p95 of each request's worst inter-burst stall
        (what chunked prefill bounds)."""
        def pct(samples, q):
            return float(np.percentile(np.fromiter(samples, float),
                                       q)) * 1000.0 if samples else None
        return {
            "ttft_p50_ms": pct(self.ttft_samples, 50),
            "ttft_p95_ms": pct(self.ttft_samples, 95),
            "itl_p50_ms": pct(self.itl_samples, 50),
            "itl_p95_ms": pct(self.itl_samples, 95),
            "stall_p95_ms": pct(self.gap_samples, 95),
            "ttft_count": len(self.ttft_samples),
            "itl_count": len(self.itl_samples),
        }

    def wasted_fraction(self) -> float:
        total = self.stats["useful_steps"] + self.stats["wasted_steps"]
        return self.stats["wasted_steps"] / total if total else 0.0

    def mean_occupancy(self) -> float:
        rounds = max(self.stats["rounds"], 1)
        return self.stats["occupancy_sum"] / rounds
