# Registrar: the service-discovery service, with primary failover.
#
# Capability parity with the reference registrar
# (reference: aiko_services/registrar.py:129-357):
#   * FSM start → primary_search → (secondary | primary) with a 2 s
#     promotion timeout;
#   * on promotion: clear the retained boot topic, arm a last-will
#     "(primary absent)", publish retained "(primary found topic version
#     time)";
#   * service table protocol on topic_in: (add record), (remove topic),
#     (share response_topic lease_time filter), (history response count);
#     live add/remove events republished on topic_out;
#   * watches {namespace}/+/+/+/state for "(absent)" last-wills and purges
#     every service of a dead process (service-id 0 = whole process);
#   * history ring buffer of departed services.

from __future__ import annotations

from collections import deque

from .service import (
    Service, ServiceFields, ServiceFilter, ServiceProtocol, Services,
    ServiceTopicPath,
)
from .state import StateMachine
from .utils import generate, generate_sexpr, get_logger, parse, parse_int

__all__ = ["Registrar", "PROTOCOL_REGISTRAR"]

PROTOCOL_REGISTRAR = ServiceProtocol("registrar")
_PRIMARY_SEARCH_TIMEOUT = 2.0      # seconds (reference: registrar.py:130)
_HISTORY_LIMIT = 4096              # entries (reference: registrar.py:129)
_VERSION = "0"

_STATES = ["start", "primary_search", "secondary", "primary"]
_TRANSITIONS = [
    {"trigger": "initialize", "source": "start", "dest": "primary_search"},
    {"trigger": "primary_found", "source": "primary_search",
     "dest": "secondary"},
    {"trigger": "primary_promotion", "source": "primary_search",
     "dest": "primary"},
    {"trigger": "primary_absent", "source": "secondary",
     "dest": "primary_search"},
    {"trigger": "primary_yield", "source": "primary", "dest": "secondary"},
]


class Registrar(Service):
    def __init__(self, runtime):
        super().__init__(runtime, "registrar", PROTOCOL_REGISTRAR)
        self.logger = get_logger("registrar")
        self.services = Services()
        self.history: deque[ServiceFields] = deque(maxlen=_HISTORY_LIMIT)
        self._search_timer = None
        self._primary_topic_path: str | None = None   # whom we stand by for
        self.state_machine = StateMachine(
            self, _STATES, _TRANSITIONS, initial="start")

        runtime.add_message_handler(self._boot_handler,
                                    runtime.topic_registrar_boot)
        runtime.add_message_handler(self._in_handler, self.topic_in)
        runtime.add_message_handler(
            self._state_handler, f"{runtime.namespace}/+/+/+/state")
        self.state_machine.transition("initialize")

    @property
    def is_primary(self) -> bool:
        return self.state_machine.state == "primary"

    # -- election ----------------------------------------------------------
    def on_enter_primary_search(self) -> None:
        self._search_timer = self.runtime.event.add_oneshot_handler(
            self._search_timeout, _PRIMARY_SEARCH_TIMEOUT)

    def _search_timeout(self) -> None:
        self._search_timer = None
        if self.state_machine.state == "primary_search":
            self.state_machine.transition("primary_promotion")

    def _cancel_search(self) -> None:
        if self._search_timer is not None:
            self.runtime.event.remove_timer_handler(self._search_timer)
            self._search_timer = None

    def on_enter_secondary(self) -> None:
        self._cancel_search()
        self.logger.info("registrar %s: secondary (standby)",
                         self.topic_path)

    def on_enter_primary(self) -> None:
        self._cancel_search()
        runtime = self.runtime
        boot_topic = runtime.topic_registrar_boot
        # clear any stale retained boot record, arm failover will, announce
        runtime.publish(boot_topic, "", retain=True)
        add_will = getattr(runtime.message, "add_last_will_and_testament",
                           None)
        if add_will:
            add_will(boot_topic, generate("primary", ["absent"]), True)
        self._announce_primary()
        self.logger.info("registrar %s: primary", self.topic_path)

    def _announce_primary(self) -> None:
        timestamp = f"{self.runtime.event.clock.now():.3f}"
        self.runtime.publish(
            self.runtime.topic_registrar_boot,
            generate("primary",
                     ["found", self.topic_path, _VERSION, timestamp]),
            retain=True)

    def _boot_handler(self, _topic, payload) -> None:
        if payload in ("", b"", None):
            return
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command != "primary" or not params:
            return
        if params[0] == "found":
            primary_topic = params[1] if len(params) > 1 else None
            if primary_topic == self.topic_path:
                return      # our own announcement
            if primary_topic:
                self._primary_topic_path = primary_topic
            if self.state_machine.state == "primary_search":
                self.state_machine.transition("primary_found")
            elif self.state_machine.state == "primary":
                # Split-brain (simultaneous promotion — the reference's
                # known defect, registrar.py:54-55): resolve by
                # deterministic order.  Lower topic_path wins; the loser
                # yields and disarms its failover will, the winner
                # re-asserts so the retained boot record converges on it.
                if primary_topic and primary_topic < self.topic_path:
                    self.logger.warning(
                        "registrar %s: yielding primary to %s",
                        self.topic_path, primary_topic)
                    remove_will = getattr(
                        self.runtime.message,
                        "remove_last_will_and_testament", None)
                    if remove_will:
                        remove_will(self.runtime.topic_registrar_boot)
                    self.state_machine.transition("primary_yield")
                else:
                    self._announce_primary()
        elif params[0] == "absent":
            if self.state_machine.state == "secondary":
                self.state_machine.transition("primary_absent")

    # -- service table protocol -------------------------------------------
    def _in_handler(self, _topic, payload) -> None:
        if not self.is_primary:
            return
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "add" and len(params) >= 5:
            try:
                fields = ServiceFields.from_record(params)
            except Exception:
                return
            existing = self.services.get(fields.topic_path)
            self.services.add(fields)
            if existing == fields:
                # idempotent re-registration (reconnect replay, periodic
                # re-announce): the table is already right — do not storm
                # every cache in the fleet with a no-op event.  A CHANGED
                # record (e.g. a peer data-plane endpoint advertised
                # after the fact, ISSUE 6) still propagates.
                return
            self.runtime.publish(
                self.topic_out,
                generate("add", [fields.to_record()]))
        elif command == "remove" and params:
            fields = self.services.remove(params[0])
            if fields is not None:
                # audited: deque(maxlen=_HISTORY_LIMIT)  # graft: disable=lint-unbounded-queue
                self.history.appendleft(fields)
                self.runtime.publish(self.topic_out,
                                     generate("remove", [params[0]]))
        elif command == "share" and len(params) >= 2:
            self._share(params[0], params[2] if len(params) > 2 else "*")
        elif command == "history" and params:
            self._share_history(params[0],
                                parse_int(params[1], 16)
                                if len(params) > 1 else 16)

    def _share(self, response_topic: str, protocol_filter) -> None:
        service_filter = ServiceFilter(
            protocol=protocol_filter if isinstance(protocol_filter, str)
            else "*")
        records = [f for f in self.services if service_filter.matches(f)]
        self.runtime.publish(response_topic,
                             generate("item_count", [str(len(records))]))
        for fields in records:
            self.runtime.publish(
                response_topic, generate("add", [fields.to_record()]))

    def _share_history(self, response_topic: str, count: int) -> None:
        records = list(self.history)[:count]
        self.runtime.publish(response_topic,
                             generate("item_count", [str(len(records))]))
        for fields in records:
            self.runtime.publish(
                response_topic, generate("history", [fields.to_record()]))

    # -- process liveness --------------------------------------------------
    def _state_handler(self, topic, payload) -> None:
        try:
            command, _ = parse(payload) if payload else ("", [])
        except Exception:
            return
        if command != "absent":
            return
        if self.state_machine.state == "secondary":
            # Failover hardening (ISSUE 4): the boot-topic "(primary
            # absent)" LWT is ONE message on a lossy transport — if it is
            # dropped, a secondary that only listened there stands by
            # forever.  The primary's process-state LWT ("(absent)",
            # RETAINED on its state topic) is an independent death
            # signal carried by the same wildcard subscription, so a
            # secondary promotes on either.
            primary = self._primary_topic_path
            parsed = ServiceTopicPath.parse(primary) if primary else None
            if parsed is not None and \
                    topic == f"{parsed.process_path}/0/state":
                self.logger.warning(
                    "registrar %s: primary %s process died (state LWT); "
                    "starting promotion", self.topic_path, primary)
                self.state_machine.transition("primary_absent")
            return
        if not self.is_primary:
            return
        topic_path = ServiceTopicPath.parse(topic.rsplit("/", 1)[0])
        if topic_path is None:
            return
        if topic_path.service_id == "0":
            removed = self.services.remove_process(topic_path.process_path)
            for fields in removed:
                # audited: deque(maxlen=_HISTORY_LIMIT)  # graft: disable=lint-unbounded-queue
                self.history.appendleft(fields)
                self.runtime.publish(self.topic_out,
                                     generate("remove", [fields.topic_path]))

    # -- shutdown ----------------------------------------------------------
    def stop(self) -> None:
        was_primary = self.is_primary
        if was_primary:
            boot_topic = self.runtime.topic_registrar_boot
            self.runtime.publish(boot_topic, "", retain=True)
            self.runtime.publish(boot_topic,
                                 generate("primary", ["absent"]))
            remove_will = getattr(self.runtime.message,
                                  "remove_last_will_and_testament", None)
            if remove_will:
                remove_will(boot_topic)
        # full teardown: a stopped registrar must neither keep serving its
        # protocol nor re-assert primacy when a successor announces itself
        self._cancel_search()
        runtime = self.runtime
        runtime.remove_message_handler(self._boot_handler,
                                       runtime.topic_registrar_boot)
        runtime.remove_message_handler(self._in_handler, self.topic_in)
        runtime.remove_message_handler(
            self._state_handler, f"{runtime.namespace}/+/+/+/state")
        if was_primary:
            self.state_machine.transition("primary_yield")
        super().stop()
