# Eventual-consistency shared state over the control plane.
#
# Capability parity with the reference share layer
# (reference: aiko_services/share.py:70-656):
#   * ECProducer — owns a (≤2-level) dict, serves "(share response_topic
#     lease_time filter)" snapshot requests with "(item_count N)" +
#     "(add k v)"…, then streams "(add/update/remove)" deltas to every
#     leaseholder whose filter matches; accepts remote add/update/remove
#     commands (dashboard mutation path); local get/update/remove API with
#     change-handler fan-out.
#   * ECConsumer — mirrors a producer's filtered share into a local cache,
#     auto-extends its lease at 0.8x by re-requesting the share.
#   * ServicesCache — client-side replica of the registrar's service table
#     with add/remove handler fan-out per ServiceFilter.
#
# Simplification vs the reference: a lease re-request doubles as both
# extension and resync, so there is a single code path for join/extend.

from __future__ import annotations

import itertools

from .connection import ConnectionState
from .lease import Lease
from .service import ServiceFields, ServiceFilter, Services, ServiceTopicPath
from .utils import generate, generate_sexpr, parse, parse_int, parse_sexpr

__all__ = ["ECProducer", "ECConsumer", "ServicesCache",
           "EC_LEASE_TIME", "filter_matches_item"]

EC_LEASE_TIME = 300.0     # seconds (reference: share.py:86)
_consumer_counter = itertools.count()


def filter_matches_item(item_filter, name: str) -> bool:
    """Share filters select top-level item names; "*" selects all.
    "a.b" items match a filter entry "a" (whole-branch selection)."""
    if item_filter in ("*", None) or item_filter == ["*"]:
        return True
    if isinstance(item_filter, str):
        item_filter = [item_filter]
    top = name.split(".")[0]
    return any(f == name or f == top for f in item_filter)


def _flatten(share: dict) -> dict:
    """{"a": 1, "b": {"c": 2}} → {"a": 1, "b.c": 2}"""
    flat = {}
    for key, value in share.items():
        if isinstance(value, dict):
            for sub, leaf in value.items():
                flat[f"{key}.{sub}"] = leaf
        else:
            flat[key] = value
    return flat


def _set_path(share: dict, name: str, value) -> None:
    if "." in name:
        top, sub = name.split(".", 1)
        share.setdefault(top, {})[sub] = value
    else:
        share[name] = value


def _del_path(share: dict, name: str) -> None:
    if "." in name:
        top, sub = name.split(".", 1)
        branch = share.get(top)
        if isinstance(branch, dict):
            branch.pop(sub, None)
            if not branch:
                share.pop(top, None)
    else:
        share.pop(name, None)


class ECProducer:
    def __init__(self, service, share: dict | None = None):
        self.service = service
        self.runtime = service.runtime
        self.share = share if share is not None else {}
        # Maintained flattened view (ISSUE 10 satellite): the producer
        # used to call _flatten(self.share) — a full dict rebuild — on
        # EVERY get/update existence check and again per consumer sync,
        # an O(n)-per-operation pattern that collapses at session
        # cardinality (1e5 keys × a sync storm = 1e10 key visits).
        # The view is updated incrementally on update/remove (O(1) per
        # leaf; O(branch) only when a whole top-level branch is
        # replaced or removed), so a sync is O(items shipped) and a
        # get/update is O(1).  Invariant: all mutations go through
        # update()/remove() (the remote command path already does) —
        # writing producer.share[...] directly was never part of the
        # API and now additionally bypasses delta publication.
        self._flat = _flatten(self.share)
        self._handlers = []       # handler(command, name, value)
        # response_topic → {"lease": Lease, "filter": ...}
        self._consumers: dict[str, dict] = {}
        self.runtime.add_message_handler(
            self._control_handler, service.topic_control)

    # -- local API ---------------------------------------------------------
    def get(self, name: str, default=None):
        if name in self._flat:
            return self._flat[name]
        return self.share.get(name, default)

    def update(self, name: str, value) -> None:
        exists = name in self._flat or name in self.share
        self._flat_forget(name)
        _set_path(self.share, name, value)
        if "." not in name and isinstance(value, dict):
            for sub, leaf in value.items():
                self._flat[f"{name}.{sub}"] = leaf
        else:
            self._flat[name] = value
        command = "update" if exists else "add"
        self._notify(command, name, value)

    def remove(self, name: str) -> None:
        self._flat_forget(name)
        _del_path(self.share, name)
        self._notify("remove", name, None)

    def _flat_forget(self, name: str) -> None:
        """Drop `name`'s current leaves from the flat view, BEFORE the
        backing dict changes (a replaced top-level branch enumerates
        its old keys from the share, not by scanning the view)."""
        if "." in name:
            self._flat.pop(name, None)
            return
        old = self.share.get(name)
        if isinstance(old, dict):
            for sub in old:
                self._flat.pop(f"{name}.{sub}", None)
        self._flat.pop(name, None)

    def keys(self):
        return list(self._flat.keys())

    def add_handler(self, handler) -> None:
        self._handlers.append(handler)

    def remove_handler(self, handler) -> None:
        if handler in self._handlers:
            self._handlers.remove(handler)

    def terminate(self) -> None:
        """Detach from the control topic and drop all consumer leases."""
        self.runtime.remove_message_handler(self._control_handler,
                                            self.service.topic_control)
        for consumer in self._consumers.values():
            consumer["lease"].terminate()
        self._consumers.clear()
        self._handlers.clear()

    # -- wire protocol -----------------------------------------------------
    def _control_handler(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "share" and len(params) >= 2:
            response_topic = params[0]
            lease_time = parse_int(params[1], int(EC_LEASE_TIME))
            item_filter = params[2] if len(params) > 2 else "*"
            if len(params) > 3:
                item_filter = params[2:]
            self._handle_share(response_topic, lease_time, item_filter)
        elif command in ("add", "update") and len(params) >= 2:
            value = _decode_value(params[1])
            self.update(params[0], value)
        elif command == "remove" and params:
            self.remove(params[0])

    def _handle_share(self, response_topic, lease_time, item_filter) -> None:
        existing = self._consumers.get(response_topic)
        if existing:
            existing["lease"].extend(lease_time)
            existing["filter"] = item_filter
        else:
            lease = Lease(self.runtime.event, lease_time, response_topic,
                          lease_expired_handler=self._lease_expired)
            self._consumers[response_topic] = {
                "lease": lease, "filter": item_filter}
        self._synchronize(response_topic, item_filter)

    def _lease_expired(self, response_topic) -> None:
        self._consumers.pop(response_topic, None)

    def _synchronize(self, response_topic, item_filter) -> None:
        items = [(k, v) for k, v in self._flat.items()
                 if filter_matches_item(item_filter, k)]
        publish = self.runtime.publish
        publish(response_topic, generate("item_count", [str(len(items))]))
        for name, value in items:
            publish(response_topic,
                    generate("add", [name, generate_sexpr(value)]))
        # end-of-snapshot marker on the response topic: per-publisher FIFO
        # ordering makes this arrive after every snapshot item, so the
        # consumer synchronizes on it rather than counting adds (counting
        # mis-fires when live deltas interleave with the snapshot);
        # topic_out carries it too for observers (reference: share.py:322-333)
        publish(response_topic, generate("sync", [response_topic]))
        publish(self.service.topic_out,
                generate("sync", [response_topic]))

    def _notify(self, command, name, value) -> None:
        for handler in list(self._handlers):
            handler(command, name, value)
        for response_topic, consumer in list(self._consumers.items()):
            if filter_matches_item(consumer["filter"], name):
                params = [name] if command == "remove" else \
                    [name, generate_sexpr(value)]
                self.runtime.publish(response_topic,
                                     generate(command, params))


def _decode_value(value):
    """Invert the producer's generate_sexpr encoding, then fold scalar
    strings back to bool/int/float (the wire is typeless).

    Without the parse_sexpr step, any string containing spaces/parens
    came back wearing its canonical length prefix ("34:devices=..."),
    and lists/dicts came back as their unparsed source text."""
    if isinstance(value, str):
        try:
            value = parse_sexpr(value)
        except Exception:
            pass
    return _fold_scalars(value)


def _fold_scalars(value):
    if isinstance(value, str):
        if value == "true":
            return True
        if value == "false":
            return False
        for cast in (int, float):
            try:
                return cast(value)
            except ValueError:
                continue
        return value
    if isinstance(value, list):
        return [_fold_scalars(item) for item in value]
    if isinstance(value, dict):
        return {key: _fold_scalars(item) for key, item in value.items()}
    return value


class ECConsumer:
    def __init__(self, runtime, cache: dict, producer_topic_control: str,
                 item_filter="*", lease_time: float = EC_LEASE_TIME):
        self.runtime = runtime
        self.cache = cache
        self.producer_topic_control = producer_topic_control
        self.item_filter = item_filter
        self.lease_time = lease_time
        self.synchronized = False
        self._handlers = []       # handler(command, item_name, value)
        self._expected = None
        self._lease = None
        # share-request dedup (ISSUE 10 satellite): a reconnect flap
        # storm — N connection transitions inside one lease window —
        # must hold ONE outstanding share request, not N.  Each request
        # makes the producer replay the full filtered snapshot; N
        # requests at session cardinality is an N×n item storm.  The
        # outstanding flag clears on the sync marker (the snapshot
        # completed) or on a timeout (the producer died mid-snapshot;
        # the next lease extension re-requests).
        self.stats = {"share_requests": 0, "share_requests_deduped": 0}
        self._request_outstanding = False
        self._request_timer = None
        self._was_connected = False
        self.response_topic = (f"{runtime.topic_path}/0/ec/"
                               f"{next(_consumer_counter)}")
        runtime.add_message_handler(self._consumer_handler,
                                    self.response_topic)
        runtime.connection.add_handler(self._connection_handler)

    def _connection_handler(self, _connection, state) -> None:
        if state < ConnectionState.TRANSPORT:
            # transport lost: the NEXT recovery resynchronizes (once)
            self._was_connected = False
            return
        if self._lease is None:
            self._lease = Lease(
                self.runtime.event, self.lease_time, self.response_topic,
                lease_extend_handler=lambda *_: self._share_request(),
                automatic_extend=True)
            self._share_request()
        elif not self._was_connected:
            # reconnect: the producer may have expired our lease while
            # we were gone — resync, deduped across flap storms
            self._share_request()
        self._was_connected = True

    def _share_request(self) -> None:
        if self._request_outstanding:
            self.stats["share_requests_deduped"] += 1
            return
        self._request_outstanding = True
        timeout = max(1.0, min(self.lease_time * 0.4, 30.0))
        self._request_timer = self.runtime.event.add_oneshot_handler(
            self._request_expired, timeout)
        self.stats["share_requests"] += 1
        item_filter = self.item_filter
        params = [self.response_topic, str(int(self.lease_time))]
        if isinstance(item_filter, (list, tuple)):
            params.extend(item_filter)
        else:
            params.append(item_filter)
        self.runtime.publish(self.producer_topic_control,
                             generate("share", params))

    def _request_expired(self) -> None:
        # no sync marker arrived inside the window: stop holding the
        # dedup gate shut so the next extend/reconnect can re-request
        self._request_timer = None
        self._request_outstanding = False

    def _request_settled(self) -> None:
        self._request_outstanding = False
        if self._request_timer is not None:
            self.runtime.event.remove_timer_handler(self._request_timer)
            self._request_timer = None

    def _consumer_handler(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "item_count" and params:
            self._expected = parse_int(params[0])    # diagnostic only
        elif command in ("add", "update") and len(params) >= 2:
            self.cache[params[0]] = _decode_value(params[1])
            self._fire(command, params[0], self.cache[params[0]])
        elif command == "remove" and params:
            self.cache.pop(params[0], None)
            self._fire("remove", params[0], None)
        elif command == "sync":
            # end-of-snapshot marker: ordered after every snapshot item
            # by per-publisher FIFO, immune to interleaved live deltas
            # (counting adds is not — they decrement the count early)
            self._expected = None
            self._request_settled()
            if not self.synchronized:
                self.synchronized = True
                self._fire("sync", None, None)

    def _fire(self, command, name, value) -> None:
        for handler in list(self._handlers):
            handler(command, name, value)

    def add_handler(self, handler) -> None:
        self._handlers.append(handler)

    def terminate(self) -> None:
        if self._lease:
            self._lease.terminate()
        self._request_settled()
        self.runtime.connection.remove_handler(self._connection_handler)
        self.runtime.remove_message_handler(self._consumer_handler,
                                            self.response_topic)


class ServicesCache:
    """Local replica of the registrar's service table."""

    def __init__(self, runtime, history_limit: int = 64):
        self.runtime = runtime
        self.services = Services()
        self.history: list[ServiceFields] = []
        self.history_limit = history_limit
        self.synchronized = False
        self._handlers = []       # (handler, ServiceFilter)
        self._expected = None
        self._registrar_out = None
        self.response_topic = (f"{runtime.topic_path}/0/cache/"
                               f"{next(_consumer_counter)}")
        runtime.add_message_handler(self._response_handler,
                                    self.response_topic)
        runtime.add_registrar_handler(self._registrar_handler)

    def _registrar_handler(self, registrar) -> None:
        if registrar is None:
            self.synchronized = False
            return
        registrar_out = f"{registrar['topic_path']}/out"
        if self._registrar_out != registrar_out:
            if self._registrar_out:
                self.runtime.remove_message_handler(self._event_handler,
                                                    self._registrar_out)
            self._registrar_out = registrar_out
            self.runtime.add_message_handler(self._event_handler,
                                             registrar_out)
        self.runtime.publish(
            f"{registrar['topic_path']}/in",
            generate("share", [self.response_topic, str(int(EC_LEASE_TIME)),
                               "*"]))

    def _response_handler(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "item_count" and params:
            self._expected = parse_int(params[0])
            if self._expected == 0:
                self._expected = None
                self.synchronized = True
        elif command == "add" and params:
            self._add_record(params[0])
            if self._expected is not None:
                self._expected -= 1
                if self._expected <= 0:
                    self._expected = None
                    self.synchronized = True

    def _event_handler(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "add" and params:
            self._add_record(params[0])
        elif command == "remove" and params:
            fields = self.services.remove(params[0])
            if fields is not None:
                self._remember(fields)
                self._fire("remove", fields)

    def _add_record(self, record) -> None:
        if isinstance(record, str):
            record = parse_sexpr(record)
        try:
            fields = ServiceFields.from_record(record)
        except Exception:
            return
        self.services.add(fields)
        self._fire("add", fields)

    def _remember(self, fields) -> None:
        self.history.insert(0, fields)
        del self.history[self.history_limit:]

    def _fire(self, command, fields) -> None:
        for handler, service_filter in list(self._handlers):
            if service_filter.matches(fields):
                handler(command, fields)

    def add_handler(self, handler, service_filter: ServiceFilter) -> None:
        """handler(command, ServiceFields); replays current matches."""
        self._handlers.append((handler, service_filter))
        for fields in self.services.filter(service_filter):
            handler("add", fields)

    def remove_handler(self, handler) -> None:
        self._handlers = [(h, f) for h, f in self._handlers if h != handler]

    def get_services(self) -> Services:
        return self.services

    def terminate(self) -> None:
        """Detach all transport subscriptions and handlers."""
        self.runtime.remove_message_handler(self._response_handler,
                                            self.response_topic)
        if self._registrar_out:
            self.runtime.remove_message_handler(self._event_handler,
                                                self._registrar_out)
            self._registrar_out = None
        self._handlers.clear()
