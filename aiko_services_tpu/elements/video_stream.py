# Streaming video I/O: network ingest and egress.
#
# Capability parity with the reference's GStreamer stream path —
# VideoStreamReader (RTSP/RTP H.264 ingest,
# reference: gstreamer/video_stream_reader.py:22-98) and
# VideoStreamWriter (RTP/RTMP egress,
# reference: gstreamer/video_stream_writer.py:27-80).
#
# Design for this framework (no GStreamer in the serving image; OpenCV is
# built with FFMPEG):
#   * PE_VideoStreamRead — URL ingest (rtsp:// udp:// http:// ...)
#     through OpenCV's FFMPEG backend, with reconnect + exponential
#     backoff and drop-to-latest real-time semantics (the reference
#     bounds its queue at 30 frames; a live pipeline wants the newest
#     frame, not a backlog).
#   * MJPEGStreamServer / PE_VideoStreamServe — HTTP multipart-MJPEG
#     egress (stdlib http.server): any browser, OpenCV, or ffmpeg client
#     can consume it; also the loopback peer the integration tests use.
#   * PE_VideoUDPSend / PE_VideoUDPReceive — low-latency JPEG-over-UDP
#     with a tiny chunking header (frame, part, count), the functional
#     stand-in for the reference's RTP/UDP leg; datagram loss drops that
#     frame only (live semantics again).

from __future__ import annotations

import socket
import struct
import threading
import time

from ..pipeline import Frame, FrameOutput, PipelineElement
from ..utils import Lock, get_logger

__all__ = ["PE_VideoStreamRead", "PE_VideoStreamServe", "MJPEGStreamServer",
           "PE_VideoStreamWrite",
           "PE_VideoUDPSend", "PE_VideoUDPReceive", "encode_jpeg",
           "decode_jpeg"]

_BOUNDARY = "aikoframe"


def encode_jpeg(image_rgb, quality: int = 80) -> bytes:
    import cv2
    import numpy as np

    bgr = np.asarray(image_rgb).astype("uint8")[:, :, ::-1]
    ok, data = cv2.imencode(".jpg", bgr,
                            [cv2.IMWRITE_JPEG_QUALITY, int(quality)])
    if not ok:
        raise ValueError("jpeg encode failed")
    return data.tobytes()


def decode_jpeg(data: bytes):
    import cv2
    import numpy as np

    bgr = cv2.imdecode(np.frombuffer(data, "uint8"), cv2.IMREAD_COLOR)
    if bgr is None:
        raise ValueError("jpeg decode failed")
    return bgr[:, :, ::-1]


class PE_VideoStreamRead(PipelineElement):
    """Network stream source: `url` parameter (rtsp://, udp://, http://
    MJPEG, ...) decoded by OpenCV/FFMPEG on a capture thread.

    Real-time semantics: the capture thread always overwrites the latest
    frame; a timer emits it at `rate` — a slow pipeline sees fresh frames,
    never a stale backlog.  Lost connections reconnect with exponential
    backoff (`backoff` initial seconds, doubling to `backoff_limit`)."""

    def start_stream(self, stream) -> None:
        url, found = self.get_parameter("url", stream=stream)
        if not found:
            raise ValueError(f"{self.name}: no url parameter")
        rate, _ = self.get_parameter("rate", 20.0, stream)
        backoff, _ = self.get_parameter("backoff", 0.5, stream)
        backoff_limit, _ = self.get_parameter("backoff_limit", 8.0, stream)
        logger = get_logger(f"videostream.{self.name}")
        state = {"latest": None, "stop": False, "connected": False,
                 "reconnects": -1,       # first connect isn't a reconnect
                 "lock": Lock(f"videostream.{self.name}")}
        stream.variables[f"{self.definition.name}.state"] = state

        def capture_loop():
            import cv2

            delay = float(backoff)
            while not state["stop"]:
                capture = cv2.VideoCapture(str(url))
                if not capture.isOpened():
                    capture.release()
                    state["connected"] = False
                    logger.warning("%s: cannot open %s; retry in %.1fs",
                                   self.name, url, delay)
                    time.sleep(delay)
                    delay = min(delay * 2, float(backoff_limit))
                    continue
                state["connected"] = True
                state["reconnects"] += 1
                delay = float(backoff)           # healthy: reset backoff
                while not state["stop"]:
                    ok, bgr = capture.read()
                    if not ok:
                        break                    # EOF / connection lost
                    with state["lock"]:
                        state["latest"] = bgr[:, :, ::-1]
                capture.release()
                state["connected"] = False

        state["thread"] = threading.Thread(
            target=capture_loop, name=f"{self.name}.capture", daemon=True)
        state["thread"].start()

        def tick():
            # locked read-and-clear: a frame stored between an unlocked
            # read and the clear would be silently dropped
            with state["lock"]:
                latest = state["latest"]
                state["latest"] = None           # emit each frame once
            if latest is not None:
                self.create_frame(stream, {"image": latest})

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, 1.0 / float(rate))

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            state["stop"] = True
            self.runtime.event.remove_timer_handler(state["timer"])

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})


class MJPEGStreamServer:
    """Minimal multipart-MJPEG HTTP server (stdlib only).

    publish(jpeg_bytes) hands every connected client the newest frame;
    slow clients skip frames rather than queueing them."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import http.server

        server_self = self
        self._condition = threading.Condition()
        self._frame: bytes | None = None
        self._sequence = 0
        self.clients_served = 0

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):                    # noqa: N802 (stdlib API)
                server_self.clients_served += 1
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    f"multipart/x-mixed-replace; boundary={_BOUNDARY}")
                self.end_headers()
                last_sequence = -1
                try:
                    while True:
                        with server_self._condition:
                            server_self._condition.wait_for(
                                lambda: server_self._sequence !=
                                last_sequence or server_self._closing,
                                timeout=5.0)
                            if server_self._closing:
                                return
                            frame = server_self._frame
                            last_sequence = server_self._sequence
                        if frame is None:
                            continue
                        self.wfile.write(
                            f"--{_BOUNDARY}\r\nContent-Type: image/jpeg"
                            f"\r\nContent-Length: {len(frame)}"
                            f"\r\n\r\n".encode())
                        self.wfile.write(frame)
                        self.wfile.write(b"\r\n")
                except (BrokenPipeError, ConnectionResetError):
                    return

            def log_message(self, *args):        # quiet
                pass

        import http.server as hs
        self._closing = False
        self.server = hs.ThreadingHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://{host}:{self.port}/stream.mjpg"
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="mjpeg.server", daemon=True)
        self._thread.start()

    def publish(self, jpeg: bytes) -> None:
        with self._condition:
            self._frame = jpeg
            self._sequence += 1
            self._condition.notify_all()

    def close(self) -> None:
        with self._condition:
            self._closing = True
            self._condition.notify_all()
        self.server.shutdown()
        self.server.server_close()


class PE_VideoStreamServe(PipelineElement):
    """Egress sink: serves the pipeline's frames as HTTP multipart-MJPEG
    (parameter `port`, 0 = ephemeral; the bound URL lands in the EC share
    as `stream_url`)."""

    def start_stream(self, stream) -> None:
        port, _ = self.get_parameter("port", 0, stream)
        quality, _ = self.get_parameter("quality", 80, stream)
        server = MJPEGStreamServer(port=int(port))
        stream.variables[f"{self.definition.name}.server"] = server
        stream.variables[f"{self.definition.name}.quality"] = int(quality)
        self.ec_producer.update("stream_url", server.url)

    def stop_stream(self, stream) -> None:
        server = stream.variables.get(f"{self.definition.name}.server")
        if server is not None:
            server.close()

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        server = frame.stream.variables[f"{self.definition.name}.server"]
        quality = frame.stream.variables[f"{self.definition.name}.quality"]
        server.publish(encode_jpeg(image, quality))
        return FrameOutput(True, {})


class PE_VideoStreamWrite(PipelineElement):
    """H.264 egress sink (reference parity:
    gstreamer/video_stream_writer.py:27-80, the x264 RTP/RTMP leg, with
    the reference's zerolatency tuning from gstreamer/utilities.py:34-36).

    Parameter `url` decides the transport:
      * file targets (*.mp4, *.mkv, *.avi) → cv2.VideoWriter via the
        FFMPEG backend, fourcc parameter (default "avc1" = H.264,
        falling back per `fourcc_fallback`, default "mp4v");
      * rtsp:// rtmp:// udp:// → an ffmpeg subprocess fed raw RGB on
        stdin encoding libx264 `-preset ultrafast -tune zerolatency`
        (OpenCV's writer cannot push network streams).
    The first frame fixes the stream geometry; fps via parameter `fps`.
    The EC share reports `write_url` and `write_backend`."""

    def start_stream(self, stream) -> None:
        stream.variables[f"{self.definition.name}.state"] = {
            "writer": None, "proc": None, "size": None,
            "frames_written": 0}

    def _open(self, stream, width: int, height: int) -> dict:
        state = stream.variables[f"{self.definition.name}.state"]
        url, found = self.get_parameter("url", stream=stream)
        if not found:
            raise ValueError(f"{self.name}: no url parameter")
        url = str(url)
        fps, _ = self.get_parameter("fps", 20.0, stream)
        fps = float(fps)
        logger = get_logger(f"videowrite.{self.name}")
        if url.split("://", 1)[0] in ("rtsp", "rtmp", "udp", "tcp"):
            import subprocess
            sink = {"rtsp": ["-f", "rtsp", "-rtsp_transport", "tcp"],
                    "rtmp": ["-f", "flv"],
                    "udp": ["-f", "mpegts"],
                    "tcp": ["-f", "mpegts"]}[url.split("://", 1)[0]]
            command = [
                "ffmpeg", "-loglevel", "error", "-f", "rawvideo",
                "-pix_fmt", "rgb24", "-s", f"{width}x{height}",
                "-r", f"{fps}", "-i", "-",
                "-c:v", "libx264", "-preset", "ultrafast",
                "-tune", "zerolatency", "-pix_fmt", "yuv420p",
                *sink, url]
            state["proc"] = subprocess.Popen(
                command, stdin=subprocess.PIPE,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            backend = "ffmpeg-libx264"
        else:
            import cv2
            fourcc, _ = self.get_parameter("fourcc", "avc1", stream)
            fallback, _ = self.get_parameter("fourcc_fallback", "mp4v",
                                             stream)
            writer = cv2.VideoWriter(
                url, cv2.VideoWriter_fourcc(*str(fourcc)), fps,
                (width, height))
            backend = f"cv2-{fourcc}"
            if not writer.isOpened():
                writer.release()
                writer = cv2.VideoWriter(
                    url, cv2.VideoWriter_fourcc(*str(fallback)), fps,
                    (width, height))
                backend = f"cv2-{fallback}"
                logger.warning("%s: fourcc %s unavailable, using %s",
                               self.name, fourcc, fallback)
            if not writer.isOpened():
                raise RuntimeError(f"{self.name}: cannot open {url}")
            state["writer"] = writer
        # size set LAST: a failed open must leave the state un-poisoned
        # so the next frame reports the real error (and can retry)
        state["size"] = (width, height)
        self.ec_producer.update("write_url", url)
        self.ec_producer.update("write_backend", backend)
        return state

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np

        rgb = np.ascontiguousarray(np.asarray(image).astype("uint8"))
        state = frame.stream.variables[f"{self.definition.name}.state"]
        if state["size"] is None:
            try:
                # first-frame egress open: the encoder spawn is the
                # sanctioned lazy-init seam (size is only known here)
                state = self._open(frame.stream, rgb.shape[1],  # graft: disable=lint-blocking-call
                                   rgb.shape[0])
            except Exception as exc:
                return FrameOutput(False,
                                   diagnostic=f"egress open: {exc!r}")
        if (rgb.shape[1], rgb.shape[0]) != state["size"]:
            return FrameOutput(False, diagnostic=(
                f"frame {rgb.shape[1]}x{rgb.shape[0]} != stream "
                f"{state['size'][0]}x{state['size'][1]}"))
        if state["proc"] is not None:
            if state["proc"].poll() is not None:
                return FrameOutput(False,
                                   diagnostic="ffmpeg egress died")
            try:
                state["proc"].stdin.write(rgb.tobytes())
            except BrokenPipeError:
                return FrameOutput(False,
                                   diagnostic="ffmpeg egress pipe broke")
        else:
            state["writer"].write(rgb[:, :, ::-1])       # RGB → BGR
        state["frames_written"] += 1
        return FrameOutput(True, {})

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if not state:
            return
        if state.get("writer") is not None:
            state["writer"].release()
        proc = state.get("proc")
        if proc is not None:
            # close stdin separately: a broken pipe here must not stop
            # a healthy ffmpeg from finalizing the container mux
            try:
                proc.stdin.close()
            except Exception:
                pass
            try:
                proc.wait(timeout=10.0)
            except Exception:
                proc.kill()
                try:
                    proc.wait(timeout=5.0)   # reap; never leave a zombie
                except Exception:
                    pass


# -- JPEG over UDP -----------------------------------------------------------
# datagram = header(frame_id u32, part u16, part_count u16) + jpeg chunk
_UDP_HEADER = struct.Struct("!IHH")
_UDP_CHUNK = 60000                  # stay under the 64 KiB datagram cap
# assembly-state bounds for the open UDP port: a flood of datagrams
# with distinct frame ids (each claiming a large part count) must not
# grow per-frame state without limit.  128 parts × 60 KB ≈ 7.7 MB caps
# a single frame far above any sane JPEG; 64 concurrent frames bounds
# the jitter window's working set (oldest assembly evicted first).
_UDP_MAX_PARTS = 128
_UDP_MAX_PENDING = 64


class PE_VideoUDPSend(PipelineElement):
    """Low-latency egress: JPEG frames chunked over UDP to host:port
    (the functional stand-in for the reference's RTP/UDP writer leg)."""

    def start_stream(self, stream) -> None:
        state = {
            "socket": socket.socket(socket.AF_INET, socket.SOCK_DGRAM),
            "frame_id": 0,
        }
        stream.variables[f"{self.definition.name}.state"] = state

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            state["socket"].close()

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        host, _ = self.get_parameter("host", "127.0.0.1", frame.stream)
        port, found = self.get_parameter("port", stream=frame.stream)
        if not found:
            return FrameOutput(False, diagnostic="no port parameter")
        quality, _ = self.get_parameter("quality", 80, frame.stream)
        state = frame.stream.variables[f"{self.definition.name}.state"]
        payload = encode_jpeg(image, int(quality))
        chunks = [payload[i:i + _UDP_CHUNK]
                  for i in range(0, len(payload), _UDP_CHUNK)] or [b""]
        frame_id = state["frame_id"] = (state["frame_id"] + 1) & 0xFFFFFFFF
        address = (str(host), int(port))
        for part, chunk in enumerate(chunks):
            header = _UDP_HEADER.pack(frame_id, part, len(chunks))
            state["socket"].sendto(header + chunk, address)
        return FrameOutput(True, {})


def _frame_id_newer(a: int, b: int) -> bool:
    """True when frame id `a` is newer than `b` under u32 wraparound."""
    return ((a - b) & 0xFFFFFFFF) < 0x80000000


class PE_VideoUDPReceive(PipelineElement):
    """Source: reassembles JPEG-over-UDP frames from PE_VideoUDPSend
    through a JITTER BUFFER — datagrams may arrive reordered, delayed,
    interleaved across frames, or not at all (the reference's GStreamer
    chain runs rtpjitterbuffer with explicit latency for the same
    reason: gstreamer/video_stream_reader.py:22-98).

    Per-frame assembly buffers tolerate cross-frame interleaving and
    out-of-order parts; a frame older than `latency_ms` that never
    completed is purged (counted `udp_incomplete`), and a frame that
    completes AFTER a newer frame was already delivered is dropped
    (`udp_late`) — live semantics never step backwards.  Parameter
    `port` (0 = ephemeral; bound port lands in the EC share as
    `udp_port`)."""

    def start_stream(self, stream) -> None:
        port, _ = self.get_parameter("port", 0, stream)
        latency_ms, _ = self.get_parameter("latency_ms", 50.0, stream)
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("0.0.0.0", int(port)))
        sock.settimeout(0.25)
        state = {"socket": sock, "stop": False, "latest": None,
                 "stats": {"complete": 0, "incomplete": 0, "late": 0}}
        stream.variables[f"{self.definition.name}.state"] = state
        self.ec_producer.update("udp_port", sock.getsockname()[1])
        window = float(latency_ms) / 1000.0

        def receive_loop():
            import time as _time
            pending: dict = {}       # frame_id -> {parts, count, t0}
            delivered = None         # newest frame_id handed over
            stale_run = 0            # consecutive not-newer FRAMES
            last_stale = None
            while not state["stop"]:
                try:
                    datagram = sock.recv(65535)
                except socket.timeout:
                    datagram = None
                except OSError:
                    return
                now = _time.monotonic()
                if datagram is not None and \
                        len(datagram) >= _UDP_HEADER.size:
                    frame_id, part, count = _UDP_HEADER.unpack(
                        datagram[:_UDP_HEADER.size])
                    if count == 0 or part >= count or \
                            count > _UDP_MAX_PARTS:
                        # corrupt/hostile header: an out-of-range part
                        # would satisfy the length==count completion
                        # check while leaving a hole for the join, and
                        # an absurd part count would reserve unbounded
                        # assembly state
                        state["stats"]["incomplete"] += 1
                        continue
                    stale = delivered is not None and (
                        frame_id == delivered or
                        not _frame_id_newer(frame_id, delivered))
                    if stale:
                        state["stats"]["late"] += 1
                        # count stale FRAMES, not datagrams: one late
                        # multi-part frame must not masquerade as a
                        # sender restart
                        if frame_id != last_stale:
                            stale_run += 1
                            last_stale = frame_id
                        # a RESTARTED sender counts from 1 again — a
                        # large backwards jump, or a sustained run of
                        # "late" traffic, is a new stream, not jitter;
                        # resync instead of freezing until the new ids
                        # catch up (the pre-jitter-buffer code resynced
                        # on any id change)
                        backwards = (delivered - frame_id) & 0xFFFFFFFF
                        if backwards > 4096 or stale_run > 32:
                            delivered = None
                            pending.clear()
                            stale_run = 0
                            last_stale = None
                    else:
                        stale_run = 0
                        last_stale = None
                        if frame_id not in pending and \
                                len(pending) >= _UDP_MAX_PENDING:
                            # cap concurrent assemblies: evict the
                            # oldest — under a frame-id flood the
                            # newest ids are the live stream
                            oldest = min(pending,
                                         key=lambda f: pending[f]["t0"])
                            del pending[oldest]
                            state["stats"]["incomplete"] += 1
                        entry = pending.setdefault(
                            frame_id, {"parts": {}, "count": count,
                                       "t0": now})
                        if part >= entry["count"]:
                            # headers disagree across datagrams of one
                            # frame id — drop rather than corrupt
                            continue
                        entry["parts"][part] = \
                            datagram[_UDP_HEADER.size:]
                        if len(entry["parts"]) == entry["count"]:
                            data = b"".join(
                                entry["parts"][i]
                                for i in range(entry["count"]))
                            del pending[frame_id]
                            try:
                                state["latest"] = decode_jpeg(data)
                                state["stats"]["complete"] += 1
                                delivered = frame_id
                                # frames older than the delivered one
                                # can never be shown — purge them
                                for stale in [f for f in pending
                                              if not _frame_id_newer(
                                                  f, frame_id)]:
                                    del pending[stale]
                                    state["stats"]["incomplete"] += 1
                            except ValueError:
                                state["stats"]["incomplete"] += 1
                # age out frames whose missing parts exceeded the
                # jitter window — they are loss, not jitter
                for stale in [f for f, e in pending.items()
                              if now - e["t0"] > window]:
                    del pending[stale]
                    state["stats"]["incomplete"] += 1

        state["thread"] = threading.Thread(
            target=receive_loop, name=f"{self.name}.udp", daemon=True)
        state["thread"].start()

        rate, _ = self.get_parameter("rate", 20.0, stream)

        def tick():
            latest = state["latest"]
            if latest is not None:
                state["latest"] = None
                self.create_frame(stream, {"image": latest})
            for key, value in state["stats"].items():
                share_key = f"udp_{key}"
                if self.ec_producer.get(share_key) != value:
                    self.ec_producer.update(share_key, value)

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, 1.0 / float(rate))

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            state["stop"] = True
            self.runtime.event.remove_timer_handler(state["timer"])
            state["socket"].close()

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})
