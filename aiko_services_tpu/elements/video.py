# Video elements: file/camera/stream I/O and tracking.
#
# Capability parity with the reference video path
# (reference: aiko_services/elements/video_io.py:28-126 OpenCV
# StreamElements + the gstreamer/ wrappers, gstreamer/__init__.py:7-22):
# file read/write and camera capture ride OpenCV (which itself fronts
# ffmpeg/gstreamer); PE_VideoShow is display-gated.  PE_Tracker is the
# multi-object IoU tracker stage of the BASELINE "video → detect →
# tracker" pipeline (config 4).

from __future__ import annotations

from ..pipeline import Frame, FrameOutput, PipelineElement

__all__ = ["PE_VideoReadFile", "PE_VideoWriteFile", "PE_VideoCameraRead",
           "PE_VideoShow", "PE_Tracker"]


class PE_VideoReadFile(PipelineElement):
    """Source: decodes a video file, one frame per timer tick at the
    requested rate (reference: video_io.py VideoReadFile)."""

    contracts = {"out:image": "u8[*,*,3]"}

    def start_stream(self, stream) -> None:
        import cv2

        pathname, found = self.get_parameter("pathname", stream=stream)
        if not found:
            raise ValueError(f"{self.name}: no pathname parameter")
        rate, _ = self.get_parameter("rate", 20.0, stream)
        capture = cv2.VideoCapture(str(pathname))
        if not capture.isOpened():
            raise ValueError(f"{self.name}: cannot open {pathname}")
        state = {"capture": capture}
        stream.variables[f"{self.definition.name}.state"] = state

        def tick():
            ok, bgr = capture.read()
            if not ok:
                self.runtime.event.remove_timer_handler(state["timer"])
                if self.pipeline is not None:
                    self.pipeline.post("destroy_stream", stream.stream_id)
                return
            self.create_frame(stream, {"image": bgr[:, :, ::-1]})  # RGB

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, 1.0 / float(rate), immediate=True)

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            self.runtime.event.remove_timer_handler(state["timer"])
            state["capture"].release()

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})


class PE_VideoWriteFile(PipelineElement):
    """Sink: encodes frames to a video file (reference: VideoWriteFile)."""

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import cv2
        import numpy as np

        key = f"{self.definition.name}.writer"
        writer = frame.stream.variables.get(key)
        image = np.asarray(image).astype("uint8")
        if writer is None:
            pathname, found = self.get_parameter("pathname",
                                                 stream=frame.stream)
            if not found:
                return FrameOutput(False, diagnostic="no pathname")
            rate, _ = self.get_parameter("rate", 20.0, frame.stream)
            pathname = str(pathname).format(stream_id=frame.stream_id)
            fourcc = cv2.VideoWriter_fourcc(*"mp4v")
            writer = cv2.VideoWriter(
                pathname, fourcc, float(rate),
                (image.shape[1], image.shape[0]))
            frame.stream.variables[key] = writer
        writer.write(image[:, :, ::-1])            # RGB → BGR
        return FrameOutput(True, {})

    def stop_stream(self, stream) -> None:
        writer = stream.variables.get(f"{self.definition.name}.writer")
        if writer is not None:
            writer.release()


class PE_VideoCameraRead(PipelineElement):
    """Camera source (v4l2 via OpenCV) — hardware-gated
    (reference: gstreamer/video_camera_reader.py)."""

    def start_stream(self, stream) -> None:
        import cv2

        device, _ = self.get_parameter("device", 0, stream)
        rate, _ = self.get_parameter("rate", 20.0, stream)
        capture = cv2.VideoCapture(int(device))
        if not capture.isOpened():
            raise RuntimeError(f"{self.name}: no camera at {device}; use "
                               f"PE_VideoReadFile for file input")
        state = {"capture": capture}
        stream.variables[f"{self.definition.name}.state"] = state

        def tick():
            ok, bgr = capture.read()
            if ok:
                self.create_frame(stream, {"image": bgr[:, :, ::-1]})

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, 1.0 / float(rate))

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            self.runtime.event.remove_timer_handler(state["timer"])
            state["capture"].release()

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})


class PE_VideoShow(PipelineElement):
    """Display sink — gated on a GUI being present
    (reference: video_io.py VideoShow)."""

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np

        try:
            import cv2
            cv2.imshow(self.name, np.asarray(image)[:, :, ::-1])
            cv2.waitKey(1)
        except Exception:
            # headless: count frames instead of displaying
            shown = frame.stream.variables.get("video_show.count", 0)
            frame.stream.variables["video_show.count"] = shown + 1
        return FrameOutput(True, {})


class PE_Tracker(PipelineElement):
    """Greedy IoU multi-object tracker: assigns stable track ids to
    per-frame detection boxes [x1, y1, x2, y2] (the tracker stage of
    BASELINE config 4).  Tracks expire after `max_age` frames unmatched."""

    def start_stream(self, stream) -> None:
        stream.variables[f"{self.definition.name}.tracks"] = {}
        stream.variables[f"{self.definition.name}.next_id"] = 0

    @staticmethod
    def _iou(a, b) -> float:
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        iw, ih = max(0.0, ix2 - ix1), max(0.0, iy2 - iy1)
        inter = iw * ih
        area_a = (a[2] - a[0]) * (a[3] - a[1])
        area_b = (b[2] - b[0]) * (b[3] - b[1])
        union = area_a + area_b - inter
        return inter / union if union > 0 else 0.0

    def process_frame(self, frame: Frame, boxes=None, **_) -> FrameOutput:
        iou_threshold, _ = self.get_parameter("iou_threshold", 0.3,
                                              frame.stream)
        max_age, _ = self.get_parameter("max_age", 5, frame.stream)
        prefix = self.definition.name
        tracks = frame.stream.variables[f"{prefix}.tracks"]
        boxes = [list(map(float, box)) for box in (boxes or [])]

        # greedy match: highest IoU first
        candidates = []
        for track_id, track in tracks.items():
            for index, box in enumerate(boxes):
                iou = self._iou(track["box"], box)
                if iou >= float(iou_threshold):
                    candidates.append((iou, track_id, index))
        candidates.sort(reverse=True)
        matched_tracks, matched_boxes = set(), set()
        assignments = {}
        for iou, track_id, index in candidates:
            if track_id in matched_tracks or index in matched_boxes:
                continue
            matched_tracks.add(track_id)
            matched_boxes.add(index)
            assignments[index] = track_id
            tracks[track_id] = {"box": boxes[index], "age": 0}

        for index, box in enumerate(boxes):         # births
            if index not in matched_boxes:
                track_id = frame.stream.variables[f"{prefix}.next_id"]
                frame.stream.variables[f"{prefix}.next_id"] = track_id + 1
                tracks[track_id] = {"box": box, "age": 0}
                assignments[index] = track_id

        for track_id in list(tracks):               # deaths
            if track_id not in matched_tracks and \
                    track_id not in assignments.values():
                tracks[track_id]["age"] += 1
                if tracks[track_id]["age"] > int(max_age):
                    del tracks[track_id]

        tracked = [{"track_id": assignments[i], "box": boxes[i]}
                   for i in range(len(boxes))]
        return FrameOutput(True, {"tracks": tracked})
