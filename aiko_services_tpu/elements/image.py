# Image elements: file I/O, transforms, annotation, and batched
# classification on the ComputeRuntime.
#
# Capability parity with the reference image elements
# (reference: aiko_services/elements/image_io.py:17-86 — PIL
# StreamElements) rebuilt on the modern pipeline API, plus the ResNet
# classify element (BASELINE.md config 2: "ResNet-18 image-classify
# PipelineElement") the reference names but never ships.

from __future__ import annotations

from ..pipeline import DEFERRED, Frame, FrameOutput, PipelineElement

__all__ = [
    "PE_ImageReadFile", "PE_ImageWriteFile", "PE_ImageResize",
    "PE_ImageAnnotate", "PE_ImageOverlay", "PE_ImageClassify",
]


class PE_ImageReadFile(PipelineElement):
    """pathname (parameter or swag) → image [H, W, 3] uint8."""

    contracts = {"out:image": "u8[*,*,3]"}

    def process_frame(self, frame: Frame, pathname=None, **_) -> FrameOutput:
        import numpy as np
        from PIL import Image

        if pathname is None:
            pathname, found = self.get_parameter("pathname",
                                                 stream=frame.stream)
            if not found:
                return FrameOutput(False, diagnostic="no pathname")
        image = Image.open(str(pathname)).convert("RGB")
        return FrameOutput(True, {"image": np.asarray(image)})


class PE_ImageWriteFile(PipelineElement):
    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np
        from PIL import Image

        pathname, found = self.get_parameter("pathname",
                                             stream=frame.stream)
        if not found:
            return FrameOutput(False, diagnostic="no pathname")
        pathname = str(pathname).format(stream_id=frame.stream_id,
                                        frame_id=frame.frame_id)
        Image.fromarray(np.asarray(image).astype("uint8")).save(pathname)
        return FrameOutput(True, {})


class PE_ImageResize(PipelineElement):

    contracts = {"in:image": "u8[*,*,3] | f32[*,*,3]",
                 "out:image": "u8[*,*,3]"}

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np
        from PIL import Image

        width, _ = self.get_parameter("width", 224, frame.stream)
        height, _ = self.get_parameter("height", 224, frame.stream)
        resized = Image.fromarray(np.asarray(image).astype("uint8")) \
            .resize((int(width), int(height)))
        return FrameOutput(True, {"image": np.asarray(resized)})


class PE_ImageAnnotate(PipelineElement):
    """Draws text + optional boxes onto the image
    (reference: image_io.py ImageAnnotate*)."""

    def process_frame(self, frame: Frame, image=None, text="",
                      boxes=None, **_) -> FrameOutput:
        import numpy as np
        from PIL import Image, ImageDraw

        pil = Image.fromarray(np.asarray(image).astype("uint8"))
        draw = ImageDraw.Draw(pil)
        if text:
            draw.text((8, 8), str(text), fill=(255, 32, 32))
        for box in boxes or []:
            draw.rectangle([tuple(box[:2]), tuple(box[2:4])],
                           outline=(32, 255, 32), width=2)
        return FrameOutput(True, {"image": np.asarray(pil)})


class PE_ImageOverlay(PipelineElement):
    """Alpha-blend `overlay` onto `image` (reference: ImageOverlay)."""

    def process_frame(self, frame: Frame, image=None, overlay=None,
                      **_) -> FrameOutput:
        import numpy as np

        alpha, _ = self.get_parameter("alpha", 0.5, frame.stream)
        image = np.asarray(image, dtype="float32")
        overlay = np.asarray(overlay, dtype="float32")
        if overlay.shape != image.shape:
            from PIL import Image
            overlay = np.asarray(Image.fromarray(
                overlay.astype("uint8")).resize(
                    (image.shape[1], image.shape[0])), dtype="float32")
        blended = (1 - float(alpha)) * image + float(alpha) * overlay
        return FrameOutput(True,
                           {"image": blended.clip(0, 255).astype("uint8")})


class PE_ImageClassify(PipelineElement):
    """Batched ResNet classification through the ComputeRuntime
    (BASELINE.md config 2).  Emits {"class_id", "confidence"}.

    Parameters: preset (resnet18/resnet34), image_size, mode
    ("batched"|"sync"), max_batch, max_wait, compute (service name)."""

    # any-size RGB frame (resized host-side); outputs are python
    # scalars (int class id, float confidence) — explicit opt-out
    contracts = {
        "in:image": "u8[*,*,3] | f32[*,*,3]",
        "out:class_id": "any", "out:confidence": "any",
    }

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._program = f"classify.{self.definition.name}"
        self._setup_done = False

    def _setup(self) -> None:
        if self._setup_done:
            return
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models.resnet import (
            RESNET_PRESETS, resnet_axes, resnet_forward, resnet_init)

        preset, _ = self.get_parameter("preset", "resnet18")
        image_size, _ = self.get_parameter("image_size", 224)
        max_batch, _ = self.get_parameter("max_batch", 32)
        max_wait, _ = self.get_parameter("max_wait", 0.05)
        self.mode, _ = self.get_parameter("mode", "batched")
        self.image_size = int(image_size)

        compute_name, _ = self.get_parameter("compute", "compute")
        self.compute = self.runtime.service_by_name(compute_name)
        if self.compute is None:
            raise RuntimeError(
                f"classify element {self.name}: no ComputeRuntime "
                f"service named {compute_name!r}")
        config = RESNET_PRESETS[str(preset)]
        params = resnet_init(jax.random.PRNGKey(0), config)
        self.params = self.compute.place_params(params,
                                                resnet_axes(params))

        forward = jax.jit(
            lambda images: resnet_forward(self.params, config, images))

        def run_bucket(_bucket, images):
            logits = forward(images)
            probs = jax.nn.softmax(logits, axis=-1)
            return (jnp.argmax(probs, axis=-1),
                    jnp.max(probs, axis=-1))

        def collate(_bucket, payloads):
            images = np.stack([np.asarray(p, dtype="float32") / 255.0
                               for p in payloads])
            return jnp.asarray(images)

        def split(results, count):
            class_ids, confidences = (np.asarray(r) for r in results)
            return [(int(class_ids[i]), float(confidences[i]))
                    for i in range(count)]

        self.compute.register_batched(
            self._program, run_bucket, [self.image_size], collate, split,
            max_batch=int(max_batch), max_wait=float(max_wait))
        self._setup_done = True

    def start_stream(self, stream) -> None:
        self._setup()

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np

        self._setup()
        image = np.asarray(image)
        if image.shape[0] != self.image_size or \
                image.shape[1] != self.image_size:
            from PIL import Image
            image = np.asarray(Image.fromarray(
                image.astype("uint8")).resize(
                    (self.image_size, self.image_size)))

        if self.mode == "sync":
            box = {}
            self.compute.submit(self._program, frame.stream_id, image,
                                self.image_size,
                                lambda _sid, r: box.setdefault("r", r))
            self.compute.programs[self._program].scheduler.drain(
                force=True)
            result = box["r"]
            if isinstance(result, Exception):
                return FrameOutput(False, diagnostic=repr(result))
            class_id, confidence = result
            return FrameOutput(True, {"class_id": class_id,
                                      "confidence": confidence})

        def callback(_sid, result):
            outputs = result if isinstance(result, Exception) else \
                {"class_id": result[0], "confidence": result[1]}
            self.pipeline.post("resume_frame", frame,
                               self.definition.name, outputs)

        self.compute.submit(self._program, frame.stream_id, image,
                            self.image_size, callback)
        return FrameOutput(True, DEFERRED)
