# Audio I/O and DSP elements.
#
# Capability parity with the reference audio elements
# (reference: aiko_services/elements/audio_io.py:58-487): microphone
# sources, FFT, amplitude/frequency filtering, band resampling, speaker
# sink, and the binary remote send/receive tensor path.
#
# TPU-native changes: FFT/filtering run as jitted jax (device-side when a
# TPU is present); the remote tensor path rides the framework transport's
# binary topics instead of raw MQTT; hardware capture/playback devices are
# gated (PE_MicrophoneSim is the deterministic stand-in used by tests,
# demos and benchmarks).

from __future__ import annotations

import zlib

from ..pipeline import Frame, FrameOutput, PipelineElement
from ..utils import get_logger

__all__ = [
    "PE_MicrophoneSim", "PE_Microphone", "PE_Speaker", "PE_FFT",
    "PE_GraphXY", "PE_AudioFilter", "PE_AudioResampler",
    "PE_RemoteSend", "PE_RemoteReceive", "encode_tensor",
    "decode_tensor",
]

SAMPLE_RATE = 16000


# -- binary tensor marshalling (reference: audio_io.py:392-439) -------------

def encode_tensor(array) -> bytes:
    """ndarray → zlib(npy) bytes for binary transport topics."""
    import io

    import numpy as np

    buffer = io.BytesIO()
    np.save(buffer, np.asarray(array), allow_pickle=False)
    return zlib.compress(buffer.getvalue())


def decode_tensor(payload: bytes):
    import io

    import numpy as np

    return np.load(io.BytesIO(zlib.decompress(payload)),
                   allow_pickle=False)


class PE_MicrophoneSim(PipelineElement):
    """Deterministic microphone: emits `chunk_seconds` of synthesized
    audio (tone + noise) per timer tick — the hardware-free source for
    tests, demos and load benchmarks."""

    contracts = {"out:audio": "f32[*]"}

    def start_stream(self, stream) -> None:
        import numpy as np

        chunk_seconds, _ = self.get_parameter("chunk_seconds", 1.0, stream)
        rate, _ = self.get_parameter("rate", SAMPLE_RATE, stream)
        frequency, _ = self.get_parameter("frequency", 440.0, stream)
        limit, _ = self.get_parameter("limit", 0, stream)
        state = {"count": 0, "limit": int(limit)}
        samples = int(float(chunk_seconds) * int(rate))
        rng = np.random.default_rng(0)

        def tick():
            if stream.state != "run" or (state["limit"] and
                                         state["count"] >= state["limit"]):
                self.runtime.event.remove_timer_handler(state["timer"])
                return
            t = (np.arange(samples) +
                 state["count"] * samples) / float(rate)
            audio = (0.5 * np.sin(2 * np.pi * float(frequency) * t) +
                     0.01 * rng.standard_normal(samples)).astype("float32")
            state["count"] += 1
            self.create_frame(stream, {"audio": audio})

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, float(chunk_seconds), immediate=True)
        stream.variables[f"{self.definition.name}.state"] = state

    def stop_stream(self, stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state:
            self.runtime.event.remove_timer_handler(state["timer"])

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})


class PE_Microphone(PipelineElement):
    """Hardware microphone via sounddevice — gated: raises a clear error
    when no capture stack is present (reference: PE_MicrophoneSD,
    audio_io.py:268-360).  Capture thread marshals chunks onto the event
    loop via create_frame."""

    def start_stream(self, stream) -> None:
        try:
            import sounddevice  # noqa: F401
        except ImportError as exc:
            raise RuntimeError(
                "PE_Microphone needs the sounddevice capture stack; use "
                "PE_MicrophoneSim for hardware-free runs") from exc
        import numpy as np
        import sounddevice

        rate, _ = self.get_parameter("rate", SAMPLE_RATE, stream)
        chunk_seconds, _ = self.get_parameter("chunk_seconds", 1.0, stream)
        chunks: list = []
        samples = int(float(chunk_seconds) * int(rate))

        def on_audio(indata, _frames, _time, _status):
            chunks.append(indata[:, 0].copy())
            total = sum(c.size for c in chunks)
            if total >= samples:
                audio = np.concatenate(chunks)[:samples].astype("float32")
                chunks.clear()
                self.create_frame(stream, {"audio": audio})

        sd_stream = sounddevice.InputStream(
            samplerate=int(rate), channels=1, callback=on_audio)
        sd_stream.start()
        stream.variables[f"{self.definition.name}.sd"] = sd_stream

    def stop_stream(self, stream) -> None:
        sd_stream = stream.variables.get(f"{self.definition.name}.sd")
        if sd_stream:
            sd_stream.stop()
            sd_stream.close()

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})


class PE_Speaker(PipelineElement):
    """Playback sink — sounddevice when present, else collects into
    stream.variables["speaker.audio"] (testable sink, reference:
    audio_io.py PE_Speaker)."""

    contracts = {"in:audio": "f32[*]"}

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        import numpy as np

        rate, _ = self.get_parameter("rate", SAMPLE_RATE, frame.stream)
        try:
            import sounddevice
        except ImportError:
            sounddevice = None
        if sounddevice is not None:
            # a failure INSIDE the audio stack is a real fault and must
            # surface — only a missing library selects the test sink
            try:
                sounddevice.play(np.asarray(audio), int(rate))
            except Exception as exc:
                return FrameOutput(
                    False, diagnostic=f"audio playback failed: {exc!r}")
            return FrameOutput(True, {})
        key = "speaker.audio"
        existing = frame.stream.variables.get(key)
        audio = np.asarray(audio)
        frame.stream.variables[key] = audio if existing is None else \
            np.concatenate([existing, audio])
        return FrameOutput(True, {})


class PE_FFT(PipelineElement):
    """audio → (frequencies, magnitudes) (reference: audio_io.py PE_FFT;
    jitted jax so it fuses with downstream device work)."""

    contracts = {"in:audio": "f32[*]", "out:frequencies": "f64[*]",
                 "out:magnitudes": "f32[*]"}

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        import jax
        import jax.numpy as jnp

        def fft(audio):
            spectrum = jnp.fft.rfft(audio)
            return jnp.abs(spectrum)

        self._fft = jax.jit(fft)

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        import numpy as np

        rate, _ = self.get_parameter("rate", SAMPLE_RATE, frame.stream)
        audio = np.asarray(audio, dtype="float32")
        magnitudes = self._fft(audio)
        frequencies = np.fft.rfftfreq(audio.size, 1.0 / int(rate))
        return FrameOutput(True, {"frequencies": frequencies,
                                  "magnitudes": magnitudes})


class PE_GraphXY(PipelineElement):
    """Spectrum plot: (frequencies, magnitudes) → image [H, W, 3] uint8
    (reference: audio_io.py PE_GraphXY renders a pygal chart into an
    OpenCV window).  Headless-first: the raster lands in the swag so it
    composes with PE_VideoShow / PE_VideoStreamServe / recorders;
    parameter `display=true` additionally opens an OpenCV window when
    cv2 is importable."""

    def process_frame(self, frame: Frame, frequencies=None,
                      magnitudes=None, **_) -> FrameOutput:
        import numpy as np

        width, _ = self.get_parameter("width", 320, frame.stream)
        height, _ = self.get_parameter("height", 160, frame.stream)
        display, _ = self.get_parameter("display", False, frame.stream)
        width, height = int(width), int(height)
        magnitudes = np.asarray(magnitudes, dtype="float32")
        frequencies = np.asarray(frequencies, dtype="float32") \
            if frequencies is not None else \
            np.arange(magnitudes.size, dtype="float32")

        image = np.zeros((height, width, 3), np.uint8)
        if magnitudes.size:
            # bin magnitudes into `width` columns by FREQUENCY (the
            # x-axis stays honest for any upstream sample rate),
            # log-compress, normalize to the frame max
            top = float(frequencies[-1]) or 1.0
            cut_hz = np.linspace(0.0, top, width + 1)[1:-1]
            # reduceat demands starts < size (degenerate 1-bin input
            # would otherwise hand it index 1 of a length-1 array)
            cuts = np.minimum(np.searchsorted(frequencies, cut_hz),
                              magnitudes.size - 1)
            starts = np.concatenate(([0], cuts))
            stops = np.concatenate((cuts, [magnitudes.size]))
            sums = np.add.reduceat(magnitudes, starts)
            counts = stops - starts
            columns = np.where(counts > 0,
                               sums / np.maximum(counts, 1), 0.0)
            columns = np.log1p(columns)
            peak = columns.max()
            if peak > 0:
                bars = (columns / peak * (height - 1)).astype(int)
                for x, bar in enumerate(bars):
                    if bar > 0:
                        image[height - bar:, x] = (64, 200, 64)
        # wire parameters arrive as strings: "false" must stay false
        if str(display).lower() == "true":       # pragma: no cover - UI
            try:
                # broad except: headless cv2 builds raise cv2.error from
                # imshow — degrade to the swag raster, never fail the
                # frame (same policy as PE_VideoShow)
                import cv2
                cv2.imshow(self.name, image[..., ::-1])
                cv2.waitKey(1)
            except Exception:
                pass
        return FrameOutput(True, {"image": image})


class PE_AudioFilter(PipelineElement):
    """Band + amplitude filter over FFT output (reference: audio_io.py
    PE_AudioFilter): zeroes magnitudes outside [low_hz, high_hz] and
    below amplitude_floor."""

    def process_frame(self, frame: Frame, frequencies=None,
                      magnitudes=None, **_) -> FrameOutput:
        import numpy as np

        low, _ = self.get_parameter("low_hz", 0.0, frame.stream)
        high, _ = self.get_parameter("high_hz", 8000.0, frame.stream)
        floor, _ = self.get_parameter("amplitude_floor", 0.0, frame.stream)
        frequencies = np.asarray(frequencies)
        magnitudes = np.asarray(magnitudes).copy()
        keep = (frequencies >= float(low)) & (frequencies <= float(high))
        magnitudes[~keep] = 0.0
        magnitudes[magnitudes < float(floor)] = 0.0
        return FrameOutput(True, {"frequencies": frequencies,
                                  "magnitudes": magnitudes})


class PE_AudioResampler(PipelineElement):
    """Bin FFT magnitudes into `band_count` bands (reference:
    audio_io.py PE_AudioResampler's 8-band LED output)."""

    def process_frame(self, frame: Frame, frequencies=None,
                      magnitudes=None, **_) -> FrameOutput:
        import numpy as np

        band_count, _ = self.get_parameter("band_count", 8, frame.stream)
        magnitudes = np.asarray(magnitudes)
        bands = np.array_split(magnitudes, int(band_count))
        levels = np.array([float(np.mean(band)) for band in bands])
        return FrameOutput(True, {"bands": levels})


class PE_RemoteSend(PipelineElement):
    """Tensor egress over a binary transport topic (reference:
    audio_io.py PE_RemoteSend0-2: zlib+np.save over raw MQTT)."""

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        topic, found = self.get_parameter("topic", stream=frame.stream)
        if not found:
            return FrameOutput(False, diagnostic="no topic")
        self.runtime.publish(str(topic), encode_tensor(audio))
        return FrameOutput(True, {})


class PE_RemoteReceive(PipelineElement):
    """Tensor ingress: subscribes a binary topic at stream start; each
    arriving tensor becomes a new frame (source element)."""

    def start_stream(self, stream) -> None:
        topic, found = self.get_parameter("topic", stream=stream)
        if not found:
            raise ValueError(f"{self.name}: no topic parameter")
        logger = get_logger(f"remote_receive.{self.name}")

        def on_message(_topic, payload):
            try:
                tensor = decode_tensor(payload)
            except Exception:
                logger.warning("undecodable tensor on %s", topic)
                return
            self.create_frame(stream, {"audio": tensor})

        stream.variables[f"{self.definition.name}.handler"] = \
            (on_message, str(topic))
        self.runtime.add_message_handler(on_message, str(topic),
                                         binary=True)

    def stop_stream(self, stream) -> None:
        entry = stream.variables.get(f"{self.definition.name}.handler")
        if entry:
            self.runtime.remove_message_handler(entry[0], entry[1])

    def process_frame(self, frame: Frame, **_) -> FrameOutput:
        return FrameOutput(True, {})
