# Detection + agent elements: the BASELINE config 4 detect stage and the
# config 5 LLM agent stage.

from __future__ import annotations

from ..pipeline import DEFERRED, Frame, FrameOutput, PipelineElement

__all__ = ["PE_Detect", "PE_LlamaAgent"]


def _session_key(raw: str) -> str:
    """SessionTable keys may not contain '.', '/', or spaces; stream /
    frame ids may (stream ids embed topic-ish paths).  Deterministic
    sanitization keeps the same stream mapping to the same session."""
    return raw.replace(".", "-").replace("/", "-").replace(" ", "-")


class PE_Detect(PipelineElement):
    """Batched object detection through the ComputeRuntime (the detect
    stage of video → detect → tracker).  Emits {"boxes": [[x1,y1,x2,y2]..],
    "scores", "classes"} with zero-score detections stripped host-side.

    Parameters: preset (detector_r18/detector_test), image_size, mode,
    score_threshold, max_batch, max_wait, compute, wire (raw|dct8),
    dct_keep."""

    # any-size RGB frame (resized host-side to image_size); uint8 is
    # the wire-native form, floats keep the historical 0-255 contract.
    # Detections are host-side python lists — explicit opt-out.
    contracts = {
        "in:image": "u8[*,*,3] | dct8-u8[*,*,3] | f32[*,*,3]",
        "out:boxes": "any", "out:scores": "any", "out:classes": "any",
    }

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._program = f"detect.{self.definition.name}"
        self._setup_done = False

    def _setup(self) -> None:
        if self._setup_done:
            return

        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..compute import resolve_pipelined
        from ..models.detector import (
            DETECTOR_PRESETS, detect, detector_axes, detector_init)

        preset, _ = self.get_parameter("preset", "detector_r18")
        image_size, _ = self.get_parameter("image_size", 256)
        threshold, _ = self.get_parameter("score_threshold", 0.3)
        max_batch, _ = self.get_parameter("max_batch", 16)
        max_wait, _ = self.get_parameter("max_wait", 0.05)
        self.mode, _ = self.get_parameter("mode", "batched")
        self.image_size = int(image_size)

        compute_name, _ = self.get_parameter("compute", "compute")
        self.compute = self.runtime.service_by_name(compute_name)
        if self.compute is None:
            raise RuntimeError(f"detect element {self.name}: no "
                               f"ComputeRuntime named {compute_name!r}")
        config = DETECTOR_PRESETS[str(preset)]
        # dtype is opt-in bf16: measured on the bench chip, bf16 convs
        # run 2.4x SLOWER than f32 for this backbone (67.8 vs 27.8
        # fps/chip at batch 32/256px) — the conv path, unlike matmuls,
        # does not win from bf16 here.  detect()'s score/box
        # post-processing is f32 regardless.
        dtype_name, _ = self.get_parameter("dtype", "float32")
        if str(dtype_name) == "bfloat16":
            import dataclasses
            config = dataclasses.replace(
                config, dtype=jnp.bfloat16,
                backbone=dataclasses.replace(config.backbone,
                                             dtype=jnp.bfloat16))
        params = detector_init(jax.random.PRNGKey(0), config)
        self.params = self.compute.place_params(params,
                                                detector_axes(params))
        threshold = float(threshold)

        # wire format: "raw" ships uint8 (normalize on device — already
        # 4x under f32); "dct8" ships quantized int8 DCT coefficients
        # (another 4x under raw at keep=16, JPEG-grade fidelity) and the
        # device program fuses dequant+iDCT+normalize+model.  The
        # tunnel/PCIe hop is the scarce resource for camera pipelines.
        wire, _ = self.get_parameter("wire", "raw")
        wire = str(wire)
        dct_keep, _ = self.get_parameter("dct_keep", 16)
        dct_keep = int(dct_keep)
        size_ = self.image_size
        if wire == "dct8":
            from ..ops.image_wire import dct8_decode

            forward = jax.jit(lambda params, codes: detect(
                params, config=config,
                images=dct8_decode(codes, size_, size_),
                score_threshold=threshold))
        else:
            forward = jax.jit(lambda params, raw: detect(
                params, config=config,
                images=raw.astype(jnp.float32) / 255.0,
                score_threshold=threshold))

        def run_bucket(_bucket, images):
            return forward(self.params, images)

        def to_uint8(p):
            # float frames keep the historical 0-255 contract (the old
            # collate divided floats by 255 too)
            p = np.asarray(p)
            if p.dtype == np.uint8:
                return p
            return np.clip(p, 0, 255).astype(np.uint8)

        # pad partial batches to max_batch: ONE compile per bucket
        # (same recompilation-storm guard as PE_WhisperASR); split()
        # only reads the real rows back
        from ..utils import parse_bool
        pad_batch, _ = self.get_parameter("pad_batch",
                                          self.mode == "batched")
        pad_batch = parse_bool(pad_batch, self.mode == "batched")
        size = self.image_size
        full = int(max_batch)

        def collate(_bucket, payloads):
            rows = full if pad_batch else len(payloads)
            if wire == "dct8":
                from ..ops.image_wire import dct8_encode
                batch = np.zeros((rows, size // 8, size // 8, 3,
                                  dct_keep), np.int8)
                for i, p in enumerate(payloads):
                    batch[i] = dct8_encode(to_uint8(p), keep=dct_keep)
                return jnp.asarray(batch)
            batch = np.zeros((rows, size, size, 3), np.uint8)
            for i, p in enumerate(payloads):
                batch[i] = to_uint8(p)
            return jnp.asarray(batch)

        def split(results, count):
            boxes, scores, classes = (np.asarray(r) for r in results)
            out = []
            for i in range(count):
                keep = scores[i] > 0.0
                out.append({"boxes": boxes[i][keep].tolist(),
                            "scores": scores[i][keep].tolist(),
                            "classes": classes[i][keep].tolist()})
            return out

        pipelined, _ = self.get_parameter("pipelined", False)
        max_in_flight, _ = self.get_parameter("max_in_flight", 4)
        self.compute.register_batched(
            self._program, run_bucket, [self.image_size], collate, split,
            max_batch=int(max_batch), max_wait=float(max_wait),
            pipelined=resolve_pipelined(pipelined, self.mode),
            max_in_flight=int(max_in_flight))
        self._setup_done = True

    def start_stream(self, stream) -> None:
        self._setup()

    def process_frame(self, frame: Frame, image=None, **_) -> FrameOutput:
        import numpy as np

        self._setup()
        image = np.asarray(image)
        if image.shape[:2] != (self.image_size, self.image_size):
            from PIL import Image
            image = np.asarray(Image.fromarray(image.astype("uint8"))
                               .resize((self.image_size,
                                        self.image_size)))

        if self.mode == "sync":
            box = {}
            self.compute.submit(self._program, frame.stream_id, image,
                                self.image_size,
                                lambda _sid, r: box.setdefault("r", r))
            self.compute.programs[self._program].scheduler.drain(
                force=True)
            result = box["r"]
            if isinstance(result, Exception):
                return FrameOutput(False, diagnostic=repr(result))
            return FrameOutput(True, result)

        def callback(_sid, result):
            self.pipeline.post("resume_frame", frame,
                               self.definition.name, result)

        self.compute.submit(self._program, frame.stream_id, image,
                            self.image_size, callback)
        return FrameOutput(True, DEFERRED)


class PE_LlamaAgent(PipelineElement):
    """LLM agent stage (BASELINE config 5: vision+ASR+Llama agent).

    Takes `text` (e.g. an ASR transcript + telemetry), prompts the
    decoder-only model, emits {"response", "response_tokens"}.  The model
    is TP-sharded over the ComputeRuntime's mesh via its logical axes.

    Tokenization is a pluggable hook (parameter-free byte fallback keeps
    the element self-contained; a real BPE tokenizer drops in via the
    `tokenizer`/`detokenizer` attributes)."""

    contracts = {"in:text": "str", "out:response": "str",
                 "out:response_tokens": "i32[*]"}

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._setup_done = False
        self._stats_timer = None
        self.prefix_cache = None
        self._session_table = None
        self.tokenizer = lambda text: [b % 250 for b in
                                       text.encode("utf-8")][:120]
        self.detokenizer = lambda tokens: " ".join(str(t) for t in tokens)

    def _publish_serving_stats(self) -> None:
        """Decoder occupancy/throughput into the pipeline's EC share —
        the observability the batch path gets from _publish_stats.
        Dedup'd: EC updates fan out to every leaseholder, so an idle
        decoder must not stream identical values every second."""
        producer = getattr(self.pipeline, "ec_producer", None)
        if producer is None:
            return
        name = self.definition.name
        stats = self.decoder.stats
        for key, value in (
                (f"serving.{name}.active", self.decoder.active_count),
                (f"serving.{name}.completed", stats["completed"]),
                (f"serving.{name}.steps", stats["steps"]),
                (f"serving.{name}.occupancy",
                 round(self.decoder.mean_occupancy(), 3))):
            if producer.get(key) != value:
                producer.update(key, value)

    def _setup(self) -> None:
        if self._setup_done:
            return
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models.llama import (
            LLAMA_PRESETS, llama_axes, llama_greedy_decode, llama_init)

        preset, _ = self.get_parameter("preset", "tiny")
        max_tokens, _ = self.get_parameter("max_tokens", 16)
        self.prompt_length, _ = self.get_parameter("prompt_length", 128)
        max_batch, _ = self.get_parameter("max_batch", 8)
        max_wait, _ = self.get_parameter("max_wait", 0.05)
        self.mode, _ = self.get_parameter("mode", "batched")
        self._program = f"agent.{self.definition.name}"

        compute_name, _ = self.get_parameter("compute", "compute")
        self.compute = self.runtime.service_by_name(compute_name)
        if self.compute is None:
            raise RuntimeError(f"agent element {self.name}: no "
                               f"ComputeRuntime named {compute_name!r}")
        config = LLAMA_PRESETS[str(preset)]
        tokenizer_path, _ = self.get_parameter("tokenizer", "")
        if tokenizer_path:
            from ..models.tokenizer import load_tokenizer
            # stream-start model load is the sanctioned lazy-init seam
            bpe = load_tokenizer(str(tokenizer_path))  # graft: disable=lint-blocking-call
            limit = int(self.prompt_length)
            vocab = config.vocab
            # drop ids the model's embedding can't represent — jnp.take
            # would clamp them silently (same guard greedy_decode applies
            # to whisper specials)
            self.tokenizer = lambda text: [
                t for t in bpe.encode(text) if t < vocab][:limit]
            self.detokenizer = bpe.decode
        params = llama_init(jax.random.PRNGKey(0), config)
        self.params = self.compute.place_params(params,
                                                llama_axes(config))
        tokens = int(max_tokens)
        self.max_tokens = tokens

        if self.mode == "continuous":
            # iteration-level scheduling: requests join/leave the running
            # batch between decode steps (serving.ContinuousDecoder) —
            # ragged generation lengths no longer idle the MXU
            from ..serving import ContinuousDecoder, PrefixKVCache
            from ..utils import parse_bool
            # serving role (ISSUE 14): tag the OWNING pipeline's
            # discovery record so role-aware discovery/routing
            # (serving_disagg, ops/admission.DeadlineRouter) can tell
            # prefill, decode, and colocated pools apart
            role, _ = self.get_parameter("role", "")
            if role and self.pipeline is not None:
                from ..serving_disagg import tag_role
                tag_role(self.pipeline, str(role))
            steps_per_sync, _ = self.get_parameter("steps_per_sync", 4)
            eos_token, _ = self.get_parameter("eos_token", -1)
            # prefix/KV reuse (ISSUE 13): parameter `prefix_block` > 0
            # binds a hash-addressed prefix cache to the decoder, so
            # shared system prompts and multi-turn histories skip
            # re-prefill.  Chunked prefill is forced on (default: one
            # bucket-sized chunk) because conversation histories
            # outgrow the prefill bucket, and chunking lifts the
            # prompt cap to max_seq.
            prefix_block, _ = self.get_parameter("prefix_block", 0)
            prefill_chunk, _ = self.get_parameter("prefill_chunk", 0)
            self.prefix_cache = None
            if int(prefix_block) > 0:
                cache_mb, _ = self.get_parameter("prefix_cache_mb", 64)
                tenant_mb, _ = self.get_parameter("prefix_tenant_mb", 0)
                self.prefix_cache = PrefixKVCache(
                    block_tokens=int(prefix_block),
                    max_bytes=int(float(cache_mb) * (1 << 20)),
                    tenant_max_bytes=int(float(tenant_mb) * (1 << 20))
                    or None,
                    name=self.definition.name)
                prefill_chunk = int(prefill_chunk) or \
                    int(self.prompt_length)
                # tiered KV (ISSUE 17): parameter `host_kv_mb` > 0
                # backs the prefix cache with a host-RAM block store —
                # session demotion and LRU pressure demote chain
                # blocks to host instead of forgetting them, and the
                # admission/session-touch prefetch kicks re-land them
                # asynchronously before the next turn's admit round
                host_kv_mb, _ = self.get_parameter("host_kv_mb", 0)
                if int(host_kv_mb) > 0:
                    from ..serving_tiered import HostBlockStore
                    host_tenant_mb, _ = self.get_parameter(
                        "host_kv_tenant_mb", 0)
                    self.prefix_cache.attach_host_store(HostBlockStore(
                        max_bytes=int(float(host_kv_mb) * (1 << 20)),
                        tenant_max_bytes=int(
                            float(host_tenant_mb) * (1 << 20)) or None,
                        name=self.definition.name))
            # paged KV (ISSUE 15): parameter `paged` rebuilds the slot
            # cache as a block pool + per-slot tables — prefix hits
            # alias instead of copying, and the disagg path can land
            # shipped KV by direct slot-table install even WITHOUT a
            # prefix cache bound (see below)
            paged, _ = self.get_parameter("paged", False)
            paged = parse_bool(paged, False)
            self.decoder = ContinuousDecoder(
                self.params, config, max_slots=int(max_batch),
                prefill_buckets=(int(self.prompt_length),),
                steps_per_sync=int(steps_per_sync),
                prefill_chunk=int(prefill_chunk) or None,
                eos_token=int(eos_token) if int(eos_token) >= 0 else None,
                name=self.definition.name,
                prefix_cache=self.prefix_cache,
                paged_kv=paged, kv_block=int(prefix_block) or 32)
            # session-resident conversation KV (ISSUE 13 / PR 10
            # residue c): parameter `sessions` persists per-(tenant,
            # session) history in a SessionTable; each turn re-submits
            # its whole history and the prefix cache longest-matches
            # it, so a returning session resumes decode instead of
            # re-prefilling.  Lease expiry / byte-budget demotion
            # release the pinned KV handles through the table's hooks.
            sessions, _ = self.get_parameter("sessions", False)
            self._session_table = None
            self._session_view = None
            if parse_bool(sessions, False) and \
                    self.prefix_cache is not None:
                from ..state.sessions import SessionTable, SessionView
                session_lease, _ = self.get_parameter(
                    "session_lease", 300.0)
                session_shards, _ = self.get_parameter(
                    "session_shards", 2)
                session_idle, _ = self.get_parameter(
                    "session_idle", 0.0)
                # tiered cache: expiry/demotion DEMOTE the pinned KV
                # to the host store (demote-not-forget, ISSUE 17);
                # without a host store demote_sessions degrades to
                # release_sessions exactly
                self._session_table = SessionTable(
                    self.pipeline, num_shards=int(session_shards),
                    lease_time=float(session_lease),
                    on_expired=self.prefix_cache.demote_sessions,
                    on_demoted=self.prefix_cache.demote_sessions,
                    demote_idle=float(session_idle) or None)
                # crash re-materialization source (ISSUE 19):
                # parameter `session_mirror` names ANOTHER runtime's
                # SessionTable topic root; its shard deltas replicate
                # into a SessionView here, so when that runtime dies
                # and callers fail over to this pipeline, the
                # conversation history is already local — the turn's
                # full-history re-submit re-prefills (chunked) and
                # the continuation is BIT-IDENTICAL to a never-crashed
                # decode, no KV bytes required
                mirror, _ = self.get_parameter("session_mirror", "")
                if str(mirror or ""):
                    self._session_view = SessionView(
                        self.runtime, str(mirror),
                        int(session_shards))
            # disaggregated serving (ISSUE 14): parameter `disagg`
            # routes prompts through a PrefillClient — a role=prefill
            # runtime computes the prompt KV and ships it over the
            # peer plane; this decoder only prefills the ragged
            # suffix.  The shipped chain needs somewhere to land: a
            # bound prefix cache, or (ISSUE 15) a paged decoder whose
            # pool takes the blocks by direct slot-table install —
            # so a cacheless decode pool engages too.  Falls back to
            # local prefill whenever the pool is absent — never a
            # dropped request.
            self._prefill_client = None
            disagg, _ = self.get_parameter("disagg", False)
            if parse_bool(disagg, False) and \
                    (self.prefix_cache is not None or paged):
                from ..serving_disagg import PrefillClient
                transfer_timeout, _ = self.get_parameter(
                    "disagg_timeout", 5.0)
                disagg_retries, _ = self.get_parameter(
                    "disagg_retries", 1)
                self._prefill_client = PrefillClient(
                    self.runtime, self.decoder,
                    services_cache=getattr(self.pipeline,
                                           "_services_cache", None),
                    name=self.definition.name,
                    transfer_timeout=float(transfer_timeout),
                    retries=int(disagg_retries))
            self._setup_done = True
            return

        decode = jax.jit(lambda params, prompt: llama_greedy_decode(
            params, config, prompt, max_tokens=tokens))

        def run_bucket(_bucket, prompts):
            return decode(self.params, prompts)

        def collate(_bucket, payloads):
            return jnp.asarray(np.stack(payloads), jnp.int32)

        def split(results, count):
            generated = np.asarray(results)
            return [generated[i].tolist() for i in range(count)]

        self.compute.register_batched(
            self._program, run_bucket, [int(self.prompt_length)],
            collate, split, max_batch=int(max_batch),
            max_wait=float(max_wait))
        self._setup_done = True

    def start_stream(self, stream) -> None:
        self._setup()
        if self.mode == "continuous":
            # pump timer lives while any stream is open (same teardown
            # discipline as the other timer-owning elements)
            self._open_streams = getattr(self, "_open_streams", 0) + 1
            if self._open_streams == 1:
                self.decoder.attach(self.runtime.event)
                self.decoder.on_idle = None
                if self._stats_timer is None:
                    self._stats_timer = self.runtime.event.\
                        add_timer_handler(self._publish_serving_stats,
                                          1.0)

    def stop_stream(self, stream) -> None:
        if self.mode == "continuous":
            self._open_streams = max(0,
                                     getattr(self, "_open_streams", 0) - 1)
            if self._open_streams == 0:
                # in-flight requests must still complete (their frames
                # are parked DEFERRED) — detach only once drained; the
                # stats timer lives until then so drain completions
                # still publish
                if self.decoder.idle:
                    self._teardown_continuous()
                else:
                    self.decoder.on_idle = lambda: (
                        self._teardown_continuous()
                        if getattr(self, "_open_streams", 0) == 0
                        else None)

    def _teardown_continuous(self) -> None:
        self._publish_serving_stats()       # final truth, not stale
        if self._stats_timer is not None:
            self.runtime.event.remove_timer_handler(self._stats_timer)
            self._stats_timer = None
        if getattr(self, "_prefill_client", None) is not None:
            self._prefill_client.stop()
            self._prefill_client = None
        if getattr(self, "_session_view", None) is not None:
            self._session_view.terminate()
            self._session_view = None
        if self._session_table is not None:
            self._session_table.stop()
        if self.prefix_cache is not None and \
                self.prefix_cache.promoter is not None:
            self.prefix_cache.promoter.stop()
        self.decoder.detach(self.runtime.event)

    def _pad_prompt(self, text):
        import numpy as np

        tokens = self.tokenizer(str(text)) or [1]
        length = int(self.prompt_length)
        padded = ([0] * max(0, length - len(tokens)) + tokens)[-length:]
        return np.asarray(padded, np.int32)

    def _to_outputs(self, generated):
        return {"response_tokens": generated,
                "response": self.detokenizer(generated)}

    def process_frame(self, frame: Frame, text="", **_) -> FrameOutput:
        self._setup()

        if self.mode == "continuous":
            turn = self.tokenizer(str(text)) or [1]
            # conversation state (ISSUE 13): with sessions on, the turn
            # prompt is the session's WHOLE history plus the new text —
            # re-submitted every turn, which is exactly what the prefix
            # cache longest-matches, so only the new tokens prefill
            tenant_param, _ = self.get_parameter("tenant", "",
                                                 frame.stream)
            # ONE normalized tenant key for decoder, cache, and table:
            # harvested blocks, session pins, and table keys must
            # share a root or session_store would match nothing — and
            # SessionTable keys may not contain '.', '/', or spaces,
            # so the key is sanitized up front
            tenant = _session_key(str(tenant_param or "default"))
            table = self._session_table
            session_id = ""
            history: list = []
            cap = self.decoder.max_seq - self.max_tokens - 2
            if table is not None:
                session_param, _ = self.get_parameter("session", "",
                                                      frame.stream)
                session_id = _session_key(
                    str(session_param or frame.stream_id))
                payload = table.get(tenant, session_id)
                if isinstance(payload, dict):
                    history = [int(t) for t in
                               payload.get("history", ())]
                elif getattr(self, "_session_view", None) is not None:
                    # failover turn (ISSUE 19): the local table has
                    # never seen this session but the mirrored state
                    # plane has — adopt its history; on_done below
                    # re-creates the session locally, so ONE turn
                    # re-materializes it completely
                    mirrored = self._session_view.get(tenant,
                                                      session_id)
                    if isinstance(mirrored, dict):
                        history = [int(t) for t in
                                   mirrored.get("history", ())]
            tokens = (history + turn)[-cap:] if history else turn[-cap:]
            if history and self.prefix_cache is not None and \
                    self.prefix_cache.tiered:
                # session touch = the earliest possible promotion kick
                # (ISSUE 17): a revived conversation's demoted chain
                # starts re-landing from host RAM NOW, while the turn
                # is still threading through submit/admission
                self.prefix_cache.prefetch(tenant, tokens)

            def on_done(_rid, generated):
                if table is not None:
                    # the finished turn IS the next turn's prefix:
                    # pin its chain under the session handle and
                    # persist the history in the state plane (lease
                    # expiry / demotion release the pin via the
                    # table's hooks).  A shed create (tenant at its
                    # session-count budget) must release the pin it
                    # just took — no table entry means no expiry hook
                    # would ever drop it.
                    new_history = (tokens + [int(t) for t in
                                             generated])[-cap:]
                    leaf, kv_tokens = self.prefix_cache.session_store(
                        tenant, session_id, new_history)
                    if not table.create(tenant, session_id,
                                        {"history": new_history,
                                         "kv": leaf or "",
                                         "kv_tokens": kv_tokens}):
                        self.prefix_cache.session_release(tenant,
                                                          session_id)
                self.pipeline.post("resume_frame", frame,
                                   self.definition.name,
                                   self._to_outputs(generated))

            # the frame's end-to-end deadline rides the ambient
            # TraceContext in ENGINE-clock seconds; the decoder's
            # admission runs on time.monotonic — carry only the
            # REMAINING budget across the domain boundary (ISSUE 12:
            # the journey then reports the margin at completion)
            import time as _time
            from ..observe.tracing import current_trace
            context = current_trace()
            deadline = None
            if context is not None and context.deadline is not None:
                remaining = context.remaining(
                    self.runtime.event.clock.now())
                if remaining is not None:
                    deadline = _time.monotonic() + max(0.0, remaining)
            request_id = f"{frame.stream_id}.{frame.frame_id}"
            client = getattr(self, "_prefill_client", None)
            if client is not None:
                # disaggregated path (ISSUE 14): the transfer is
                # async, so a decoder refusal AFTER the KV lands must
                # fail the parked frame through resume_frame
                def on_refused(_rid):
                    self.pipeline.post(
                        "resume_frame", frame, self.definition.name,
                        RuntimeError(
                            "decoder admission shed after prefill "
                            "transfer: estimated admit wait outruns "
                            "the remaining deadline budget"))
                accepted = client.submit(
                    request_id, tokens, self.max_tokens, on_done,
                    deadline=deadline, tenant=tenant,
                    on_refused=on_refused)
            else:
                accepted = self.decoder.submit(
                    request_id, tokens, self.max_tokens, on_done,
                    deadline=deadline,
                    tenant=tenant if self.prefix_cache is not None
                    else None)
            if not accepted:
                return FrameOutput(False, diagnostic=(
                    "decoder admission shed: estimated admit wait "
                    "outruns the remaining deadline budget"))
            return FrameOutput(True, DEFERRED)

        prompt = self._pad_prompt(text)
        length = int(self.prompt_length)

        if self.mode == "sync":
            box = {}
            self.compute.submit(self._program, frame.stream_id, prompt,
                                length,
                                lambda _sid, r: box.setdefault("r", r))
            self.compute.programs[self._program].scheduler.drain(
                force=True)
            result = box["r"]
            if isinstance(result, Exception):
                return FrameOutput(False, diagnostic=repr(result))
            return FrameOutput(True, self._to_outputs(result))

        def callback(_sid, result):
            outputs = result if isinstance(result, Exception) else \
                self._to_outputs(result)
            self.pipeline.post("resume_frame", frame,
                               self.definition.name, outputs)

        self.compute.submit(self._program, frame.stream_id, prompt,
                            length, callback)
        return FrameOutput(True, DEFERRED)
