# Built-in pipeline elements.
#
# Capability parity with the reference built-ins
# (reference: aiko_services/pipeline_elements.py:37-175): PE_GenerateNumbers
# (source), PE_Metrics (per-element timing sink), PE_0..PE_4 arithmetic test
# elements, PE_DataEncode/PE_DataDecode tensor marshalling.
#
# TPU-native change: DataEncode/Decode marshal tensors only at the
# host↔control-plane boundary; co-located elements pass jax.Arrays through
# the swag untouched (SURVEY.md §5.8: the encode/decode seam becomes tensor
# egress/ingress at the device edge only).

from .common import (                                       # noqa: F401
    PE_GenerateNumbers, PE_Metrics, PE_Identity,
    PE_0, PE_1, PE_2, PE_3, PE_4,
    PE_DataEncode, PE_DataDecode,
)
from .speech import (                                       # noqa: F401
    PE_AudioFraming, PE_AudioReadFile, PE_AudioWriteFile, PE_LogMel,
    PE_Synthesize, PE_WhisperASR,
)
from .audio import (                                        # noqa: F401
    PE_AudioFilter, PE_AudioResampler, PE_FFT, PE_GraphXY,
    PE_Microphone,
    PE_MicrophoneSim, PE_RemoteReceive, PE_RemoteSend, PE_Speaker,
)
from .image import (                                        # noqa: F401
    PE_ImageAnnotate, PE_ImageClassify, PE_ImageOverlay, PE_ImageReadFile,
    PE_ImageResize, PE_ImageWriteFile,
)
from .video import (                                        # noqa: F401
    PE_Tracker, PE_VideoCameraRead, PE_VideoReadFile, PE_VideoShow,
    PE_VideoWriteFile,
)
from .video_stream import (                                 # noqa: F401
    MJPEGStreamServer, PE_VideoStreamRead, PE_VideoStreamServe,
    PE_VideoStreamWrite, PE_VideoUDPReceive, PE_VideoUDPSend,
)
from .detect import PE_Detect, PE_LlamaAgent                # noqa: F401
from .tts import PE_NeuralTTS                               # noqa: F401

__all__ = [
    "PE_GenerateNumbers", "PE_Metrics", "PE_Identity",
    "PE_0", "PE_1", "PE_2", "PE_3", "PE_4",
    "PE_DataEncode", "PE_DataDecode",
    "PE_AudioFraming", "PE_AudioReadFile", "PE_AudioWriteFile",
    "PE_LogMel", "PE_Synthesize", "PE_WhisperASR",
    "PE_AudioFilter", "PE_AudioResampler", "PE_FFT", "PE_GraphXY",
    "PE_Microphone",
    "PE_MicrophoneSim", "PE_RemoteReceive", "PE_RemoteSend", "PE_Speaker",
    "PE_ImageAnnotate", "PE_ImageClassify", "PE_ImageOverlay",
    "PE_ImageReadFile", "PE_ImageResize", "PE_ImageWriteFile",
    "MJPEGStreamServer", "PE_VideoStreamRead", "PE_VideoStreamServe",
    "PE_VideoStreamWrite", "PE_VideoUDPReceive", "PE_VideoUDPSend",
    "PE_Tracker", "PE_VideoCameraRead", "PE_VideoReadFile", "PE_VideoShow",
    "PE_VideoWriteFile",
    "PE_Detect", "PE_LlamaAgent", "PE_NeuralTTS",
]
