# Arithmetic / plumbing elements used by tests, examples and benchmarks.
# (reference: aiko_services/pipeline_elements.py:37-175)

from __future__ import annotations

import base64
import io

from ..pipeline import Frame, FrameOutput, PipelineElement, Stream

__all__ = [
    "PE_GenerateNumbers", "PE_Metrics", "PE_Identity",
    "PE_0", "PE_1", "PE_2", "PE_3", "PE_4",
    "PE_DataEncode", "PE_DataDecode",
]


class PE_GenerateNumbers(PipelineElement):
    """Source: emits `number` frames on a timer while the stream runs
    (reference: pipeline_elements.py:37-61 — a thread there; a timer on the
    event engine here, so it is deterministic under a VirtualClock)."""

    def start_stream(self, stream: Stream) -> None:
        rate, _ = self.get_parameter("rate", 10.0, stream)
        limit, _ = self.get_parameter("limit", 0, stream)
        state = {"count": 0, "limit": int(limit)}
        stream.variables[f"{self.definition.name}.state"] = state

        def tick():
            if stream.state != "run":
                self.runtime.event.remove_timer_handler(state["timer"])
                return
            if state["limit"] and state["count"] >= state["limit"]:
                self.runtime.event.remove_timer_handler(state["timer"])
                return
            self.create_frame(stream, {"number": state["count"]})
            state["count"] += 1

        state["timer"] = self.runtime.event.add_timer_handler(
            tick, 1.0 / float(rate), immediate=True)

    def stop_stream(self, stream: Stream) -> None:
        state = stream.variables.get(f"{self.definition.name}.state")
        if state and "timer" in state:
            self.runtime.event.remove_timer_handler(state["timer"])

    def process_frame(self, frame: Frame, **inputs) -> FrameOutput:
        # source: the frame already carries `number` (posted by create_frame)
        return FrameOutput(True, {})


class PE_Metrics(PipelineElement):
    """Sink: publishes per-element frame timings into its EC share
    (reference logs them, pipeline_elements.py:63-79; sharing makes them
    dashboard-visible and machine-readable)."""

    def process_frame(self, frame: Frame, **inputs) -> FrameOutput:
        for name, seconds in frame.metrics.items():
            if name.startswith("time_"):
                self.ec_producer.update(
                    f"metrics.{name}", round(seconds * 1000.0, 3))
        self.ec_producer.update("metrics.frame_id", frame.frame_id)
        return FrameOutput(True, {})


class PE_Identity(PipelineElement):
    """Pass-through: returns declared inputs unchanged (aloha_honua-style
    single-element benchmark pipeline)."""

    def process_frame(self, frame: Frame, **inputs) -> FrameOutput:
        return FrameOutput(True, dict(inputs))


class PE_0(PipelineElement):
    """number → a = number + constant (reference: pipeline_elements.py:82)"""

    def process_frame(self, frame: Frame, number=0, **_) -> FrameOutput:
        constant, _found = self.get_parameter("constant", 1, frame.stream)
        return FrameOutput(True, {"a": number + int(constant)})


class PE_1(PipelineElement):
    def process_frame(self, frame: Frame, number=0, **_) -> FrameOutput:
        return FrameOutput(True, {"a": number + 1})


class PE_2(PipelineElement):
    def process_frame(self, frame: Frame, a=0, **_) -> FrameOutput:
        return FrameOutput(True, {"b": a * 2})


class PE_3(PipelineElement):
    def process_frame(self, frame: Frame, a=0, **_) -> FrameOutput:
        return FrameOutput(True, {"c": a + 10})


class PE_4(PipelineElement):
    """Fan-in: b + c → d"""

    def process_frame(self, frame: Frame, b=0, c=0, **_) -> FrameOutput:
        return FrameOutput(True, {"d": b + c})


class PE_DataEncode(PipelineElement):
    """Tensor egress: ndarray/jax.Array → base64(npy) string for transport
    over the control plane (reference: pipeline_elements.py:147-160).
    Only needed when a frame leaves the device/host boundary."""

    def process_frame(self, frame: Frame, data=None, **_) -> FrameOutput:
        import numpy as np
        array = np.asarray(data)
        buffer = io.BytesIO()
        np.save(buffer, array, allow_pickle=False)
        encoded = base64.b64encode(buffer.getvalue()).decode("ascii")
        return FrameOutput(True, {"data": encoded})


class PE_DataDecode(PipelineElement):
    """Tensor ingress: base64(npy) string → ndarray
    (reference: pipeline_elements.py:162-175)."""

    def process_frame(self, frame: Frame, data=None, **_) -> FrameOutput:
        import numpy as np
        if isinstance(data, str):
            buffer = io.BytesIO(base64.b64decode(data.encode("ascii")))
            data = np.load(buffer, allow_pickle=False)
        return FrameOutput(True, {"data": data})
