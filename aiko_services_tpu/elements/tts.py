# Neural TTS pipeline element: batched text→speech on the ComputeRuntime.
#
# Replaces the sine-stack placeholder (PE_Synthesize stays as the
# dependency-free fallback) with the jax acoustic model + Griffin-Lim
# vocoder from models/tts.py — the same batched serving pattern as
# PE_WhisperASR: frames from many streams coalesce into one device
# program (reference wraps Coqui VITS inline on the event loop:
# examples/speech/speech_elements.py:96-131).

from __future__ import annotations

from ..pipeline import DEFERRED, Frame, FrameOutput, PipelineElement
from ..utils import get_logger

__all__ = ["PE_NeuralTTS"]


class PE_NeuralTTS(PipelineElement):
    """text → audio.  Parameters: preset (test/base), weights (flat npz),
    tokenizer (vocab dir or builtin:byte), mode ("batched"|"sync"),
    max_tokens, max_batch, max_wait, gl_iters.
    Emits {"audio": float32[samples], "sample_rate"}."""

    contracts = {"in:text": "str", "out:audio": "f32[*]"}

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.logger = get_logger(f"tts.{self.name}")
        self._program = f"neural_tts.{self.definition.name}"
        self._setup_done = False
        self.tokenizer = None

    def _setup(self) -> None:
        if self._setup_done:
            return
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models.tokenizer import ByteTokenizer, load_tokenizer
        from ..models.tts import TTS_PRESETS, tts_axes, tts_init, synthesize
        from ..ops.audio import WHISPER_HOP

        preset, _ = self.get_parameter("preset", "test")
        weights, _ = self.get_parameter("weights", "")
        tokenizer_path, _ = self.get_parameter("tokenizer", "builtin:byte")
        max_batch, _ = self.get_parameter("max_batch", 16)
        max_wait, _ = self.get_parameter("max_wait", 0.05)
        gl_iters, _ = self.get_parameter("gl_iters", 32)
        self.mode, _ = self.get_parameter("mode", "batched")

        compute_name, _ = self.get_parameter("compute", "compute")
        self.compute = self.runtime.service_by_name(compute_name)
        if self.compute is None:
            raise RuntimeError(f"TTS element {self.name}: no "
                               f"ComputeRuntime named {compute_name!r}")

        self.config = TTS_PRESETS[str(preset)]
        max_tokens, _ = self.get_parameter("max_tokens",
                                           self.config.max_tokens)
        self.max_tokens = min(int(max_tokens), self.config.max_tokens)
        # stream-start model load is the sanctioned lazy-init seam
        self.tokenizer = ByteTokenizer() if tokenizer_path == \
            "builtin:byte" else load_tokenizer(str(tokenizer_path))  # graft: disable=lint-blocking-call
        params = tts_init(jax.random.PRNGKey(0), self.config)
        if weights:
            from .speech import load_flat_npz
            params = load_flat_npz(params, str(weights))
        self.params = self.compute.place_params(params,
                                                tts_axes(self.config))
        config = self.config
        gl_iters = int(gl_iters)

        # mel→waveform leg: a trained neural vocoder checkpoint
        # (parameter `vocoder_weights`) replaces Griffin-Lim; absent,
        # the weight-free fallback keeps working
        vocoder_weights, _ = self.get_parameter("vocoder_weights", "")
        vocoder_preset, _ = self.get_parameter("vocoder_preset", "test")
        self.vocoder = None
        vocoder_config = None
        if vocoder_weights:
            from ..models.vocoder import (VOCODER_PRESETS, vocoder_axes,
                                          vocoder_init)
            from .speech import load_flat_npz
            vocoder_config = VOCODER_PRESETS[str(vocoder_preset)]
            vparams = vocoder_init(jax.random.PRNGKey(0), vocoder_config)
            vparams = load_flat_npz(vparams, str(vocoder_weights))
            self.vocoder = self.compute.place_params(
                vparams, vocoder_axes(vocoder_config))

        fn = jax.jit(lambda params, vocoder, tokens: synthesize(
            params, config, tokens, n_iter=gl_iters,
            vocoder=vocoder, vocoder_config=vocoder_config))

        def run_bucket(bucket, token_batch):
            return fn(self.params, self.vocoder, token_batch)

        def collate(bucket, payloads):
            batch = np.zeros((len(payloads), bucket), dtype="int32")
            for i, ids in enumerate(payloads):
                t = min(len(ids), bucket)
                batch[i, :t] = np.asarray(ids[:t], dtype="int32")
            return jnp.asarray(batch)

        def split(results, count):
            # trim each row to its predicted duration: the static tail
            # past the regulator's total synthesizes silence-garbage
            audio_batch, samples = results
            audio_batch = np.asarray(audio_batch, dtype=np.float32)
            samples = np.asarray(samples)
            return [audio_batch[i, :max(int(samples[i]), WHISPER_HOP)]
                    for i in range(count)]

        from ..compute import resolve_pipelined
        pipelined, _ = self.get_parameter("pipelined", False)
        pipelined = resolve_pipelined(pipelined, self.mode)
        self.compute.register_batched(
            self._program, run_bucket, [self.max_tokens],
            collate, split, max_batch=int(max_batch),
            max_wait=float(max_wait), pipelined=pipelined)
        self._setup_done = True

    def start_stream(self, stream) -> None:
        self._setup()

    def process_frame(self, frame: Frame, text="", **_) -> FrameOutput:
        self._setup()
        from ..ops.audio import WHISPER_SAMPLE_RATE

        ids = self.tokenizer.encode(str(text))[:self.max_tokens]
        if not ids:
            ids = [32]                                   # space: silence
        if self.mode == "sync":
            box = {}
            self.compute.submit(self._program, frame.stream_id, ids,
                                len(ids),
                                lambda _sid, r: box.setdefault("r", r))
            self.compute.programs[self._program].scheduler.drain(
                force=True)
            result = box["r"]
            if isinstance(result, Exception):
                return FrameOutput(False, diagnostic=repr(result))
            return FrameOutput(True, {
                "audio": result,
                "sample_rate": WHISPER_SAMPLE_RATE})

        def callback(_sid, result):
            outputs = result if isinstance(result, Exception) else \
                {"audio": result,
                 "sample_rate": WHISPER_SAMPLE_RATE}
            self.pipeline.post("resume_frame", frame,
                               self.definition.name, outputs)

        self.compute.submit(self._program, frame.stream_id, ids, len(ids),
                            callback)
        return FrameOutput(True, DEFERRED)
