# Speech pipeline elements: framing, log-mel frontend, batched Whisper ASR,
# placeholder TTS, wav file I/O.
#
# Capability parity with the reference speech elements
# (reference: examples/speech/speech_elements.py:44-250): PE_AudioFraming
# (sliding-window concat over an LRU), PE_AudioWriteFile, the WhisperX ASR
# element, speech framing, and the Coqui TTS element.
#
# TPU-native redesign:
#   * PE_LogMel runs the whisper mel frontend in jax (ops/audio.py) — the
#     mic→features→encoder path stays on device;
#   * PE_WhisperASR submits to a ComputeRuntime batched program and defers
#     the frame (pipeline.DEFERRED): frames from hundreds of streams
#     coalesce into MXU-sized batches (the ≥200-stream north star), or
#     runs synchronously with mode="sync";
#   * PE_Synthesize is an explicit placeholder voice (formant-ish sine
#     stack) keeping the TTS seam real until a neural vocoder lands.

from __future__ import annotations

import math
import wave

from ..pipeline import DEFERRED, Frame, FrameOutput, PipelineElement
from ..utils import LRUCache, get_logger

__all__ = [
    "PE_AudioFraming", "PE_LogMel", "PE_WhisperASR", "PE_Synthesize",
    "PE_AudioReadFile", "PE_AudioWriteFile", "load_wav", "save_wav",
    "load_flat_npz", "save_flat_npz",
]

SAMPLE_RATE = 16000         # voice rate (reference: audio_io.py:224-228)


def compression_ratio(text: str) -> float:
    """len(utf8)/len(zlib(utf8)) — degenerate repetition (the classic
    whisper hallucination mode) compresses far better than speech;
    ratios above ~2.4 flag it (reference gate:
    speech_elements.py:174-250)."""
    import zlib

    data = text.encode("utf-8")
    if not data:
        return 0.0
    return len(data) / len(zlib.compress(data))


def load_wav(pathname: str):
    """wav → float32 [-1, 1] mono numpy array (stdlib only)."""
    import numpy as np

    with wave.open(pathname, "rb") as reader:
        frames = reader.readframes(reader.getnframes())
        width = reader.getsampwidth()
        channels = reader.getnchannels()
        rate = reader.getframerate()
    dtype = {1: np.int8, 2: np.int16, 4: np.int32}[width]
    audio = np.frombuffer(frames, dtype=dtype).astype(np.float32)
    audio /= float(np.iinfo(dtype).max)
    if channels > 1:
        audio = audio.reshape(-1, channels).mean(axis=1)
    return audio, rate


def save_wav(pathname: str, audio, sample_rate: int = SAMPLE_RATE) -> None:
    import numpy as np

    clipped = np.clip(np.asarray(audio), -1.0, 1.0)
    pcm = (clipped * 32767.0).astype(np.int16)
    with wave.open(pathname, "wb") as writer:
        writer.setnchannels(1)
        writer.setsampwidth(2)
        writer.setframerate(sample_rate)
        writer.writeframes(pcm.tobytes())


class PE_AudioFraming(PipelineElement):
    """Sliding-window concat: keeps the last `window_count` audio chunks
    per stream and emits their concatenation — more ASR context per frame
    (reference: speech_elements.py:44-73)."""

    contracts = {"audio": "f32[*]"}

    def start_stream(self, stream) -> None:
        count, _ = self.get_parameter("window_count", 3, stream)
        stream.variables[f"{self.definition.name}.window"] = \
            LRUCache(int(count))

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        import numpy as np

        window: LRUCache = frame.stream.variables[
            f"{self.definition.name}.window"]
        window.put(frame.frame_id, np.asarray(audio))
        chunks = [window.get(key) for key in sorted(window.keys())]
        return FrameOutput(True, {"audio": np.concatenate(chunks)})


class PE_LogMel(PipelineElement):
    """audio [T_samples] → log-mel [T_frames, 80] (jax).

    Parameter `device`: "default" runs on the accelerator (co-located
    serving: mel stays on device for the encoder); "cpu" pins the
    frontend to the host CPU backend — right when the accelerator is
    behind a thin link and the batched ASR program uploads mel itself
    (mel is 4× smaller than raw f32 audio over the wire)."""

    contracts = {"in:audio": "f32[*]", "out:mel": "f32[*,80]"}

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        import jax
        from ..ops.audio import log_mel_spectrogram
        self._fn = jax.jit(log_mel_spectrogram)
        self._cpu = None

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        import numpy as np

        device, _ = self.get_parameter("device", "default", frame.stream)
        batch = np.asarray(audio, dtype="float32")[None]
        if device == "cpu":
            import jax
            if self._cpu is None:
                self._cpu = jax.devices("cpu")[0]
            with jax.default_device(self._cpu):
                mel = self._fn(batch)
        else:
            mel = self._fn(batch)
        return FrameOutput(True, {"mel": mel[0]})


class PE_WhisperASR(PipelineElement):
    """Batched Whisper ASR through a ComputeRuntime.

    Parameters: preset (tiny/base/small/...), mode ("batched"|"sync"),
    max_tokens, buckets (mel-frame bucket ladder), frontend ("mel" takes
    a host-computed mel input; "audio" takes raw samples and fuses the
    log-mel frontend INTO the batched device program — one jit from
    samples to tokens, no per-frame host feature dispatch).  The compute
    runtime is found by service name via parameter `compute` (default
    "compute").  Emits {"tokens": int32[T], "text": str}."""

    contracts = {
        # float mel, or pre-packed i8mel rows ([T, 80+4]: int8 codes +
        # per-row f32 scale bytes — the ASR wire codec, ops/audio.py)
        "in:mel": "f32[*,80] | bf16[*,80] | i8mel-i8[*,84]",
        # raw float samples, 16-bit PCM, or pre-encoded µ-law codes
        "in:audio": "f32[*] | i16[*] | mulaw-u8[*]",
        "out:tokens": "i32[*]",
        "out:text": "str",
    }

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.logger = get_logger(f"asr.{self.name}")
        self._program = f"whisper_asr.{self.definition.name}"
        self._setup_done = False
        # pluggable id→text hook (parameter `tokenizer` loads a real BPE
        # vocab in _setup; the default mirrors PE_LlamaAgent's seam)
        self.detokenizer = lambda ids: " ".join(str(t) for t in ids)

    # -- model + program setup (lazy: first stream) -------------------------
    def _setup(self) -> None:
        if self._setup_done:
            return
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..models.whisper import (
            WHISPER_PRESETS, WhisperConfig, greedy_decode_scored,
            sot_sequence_for, whisper_init)

        preset, _ = self.get_parameter("preset", "tiny")
        max_tokens, _ = self.get_parameter("max_tokens", 24)
        buckets, _ = self.get_parameter("buckets", [100, 500, 1000, 3000])
        weights, _ = self.get_parameter("weights", "")
        # long-audio buckets round up to flash-kernel geometry: the
        # pallas path needs ctx % 128 == 0 and only wins at ctx >= 1024
        # (ops/attention.py crossover measurements) — e.g. 3000 mel
        # frames (ctx 1500, unfused) pad ~2% to 3072 (ctx 1536, flash).
        # Defaults OFF when a pretrained checkpoint is loaded: its
        # trained audio ctx (whisper: exactly 1500) must not be
        # stretched to positions it never saw.  Parameter
        # `flash_buckets` overrides either way.
        from ..ops.attention import FLASH_MIN_SEQ
        from ..utils import parse_bool
        flash_buckets, _ = self.get_parameter("flash_buckets",
                                              not weights)
        if parse_bool(flash_buckets, not weights):
            buckets = sorted({
                b if b // 2 < FLASH_MIN_SEQ else -(-b // 256) * 256
                for b in buckets})
        max_batch, _ = self.get_parameter("max_batch", 32)
        max_wait, _ = self.get_parameter("max_wait", 0.05)
        self.mode, _ = self.get_parameter("mode", "batched")
        self.frontend, _ = self.get_parameter("frontend", "mel")
        max_tokens = int(max_tokens)
        # per-frame completion budget: frames submit with an absolute
        # deadline and the batch former dispatches a partial batch
        # early when the earliest deadline is at risk (measured-service
        # EWMA) — latency becomes a scheduling input, not a hope
        deadline_ms, _ = self.get_parameter("deadline_ms", 0)
        self.deadline_s = float(deadline_ms) / 1000.0

        # decode conditioning + quality gates (reference behavior:
        # speech_elements.py:174-250 — language pinning and the
        # explicit hallucination-suppression block around
        # faster-whisper)
        language, _ = self.get_parameter("language", "")
        task, _ = self.get_parameter("task", "transcribe")
        timestamps, _ = self.get_parameter("timestamps", False)
        self.timestamps = parse_bool(timestamps, False)
        logprob_threshold, _ = self.get_parameter(
            "logprob_threshold", -1.0)
        self.logprob_threshold = float(logprob_threshold)
        compression_threshold, _ = self.get_parameter(
            "compression_ratio_threshold", 2.4)
        self.compression_threshold = float(compression_threshold)
        # int8 cross-attention KV (opt-in).  Two modes
        # (layers.quantize_kv): true/"position" halves the cross-KV's
        # HBM FOOTPRINT only (the per-position dequant multiply
        # re-materializes per decode step — measured ~24% SLOWER at
        # batch 256); "tensor" uses one scale per BATCH ELEMENT so the
        # dequant is a bare convert fused into the attention dot —
        # halves the decode tail's dominant READ as well (measured
        # −14% round; see the bench's chip kv-quant A/B).
        kv_quant, _ = self.get_parameter("kv_quant", False)
        if isinstance(kv_quant, str):
            # wire-delivered parameters arrive as (possibly padded)
            # strings; an unrecognized mode must fail loudly, not
            # silently coerce to bf16 (ADVICE r5)
            kv_mode = kv_quant.strip().lower()
            if kv_mode in ("tensor", "position"):
                self.kv_quant = kv_mode
            elif kv_mode in ("true", "t", "yes", "on", "1"):
                self.kv_quant = True
            elif kv_mode in ("false", "f", "no", "off", "0", ""):
                self.kv_quant = False
            else:
                raise ValueError(
                    f"ASR element {self.name}: unrecognized kv_quant "
                    f"mode {kv_quant!r} (expected tensor | position | "
                    f"a boolean)")
        else:
            self.kv_quant = parse_bool(kv_quant, False)

        compute_name, _ = self.get_parameter("compute", "compute")
        self.compute = self.runtime.service_by_name(compute_name)
        if self.compute is None:
            raise RuntimeError(
                f"ASR element {self.name}: no ComputeRuntime service "
                f"named {compute_name!r} in this process")

        base = WHISPER_PRESETS[str(preset)]
        # context sized to the largest bucket (mel frames → ctx = frames/2)
        self.config = WhisperConfig(
            n_mels=base.n_mels, n_audio_ctx=max(buckets) // 2,
            n_text_ctx=max_tokens + 8, n_vocab=base.n_vocab,
            dim=base.dim, num_heads=base.num_heads,
            enc_layers=base.enc_layers, dec_layers=base.dec_layers,
            dtype=jnp.bfloat16, sot=base.sot, eot=base.eot)
        tokenizer_path, _ = self.get_parameter("tokenizer", "")
        if tokenizer_path:
            from ..models.tokenizer import load_tokenizer
            # stream-start model load is the sanctioned lazy-init seam
            self.detokenizer = load_tokenizer(str(tokenizer_path)).decode  # graft: disable=lint-blocking-call
        params = whisper_init(jax.random.PRNGKey(0), self.config)
        if weights:
            params = load_flat_npz(params, str(weights))
        self.params = self.compute.place_params(
            params, _whisper_axes(self.config))

        per_bucket_config = {}

        audio_frontend = self.frontend == "audio"
        # audio wire format: "int16" (default) ships lossless PCM;
        # "mulaw" ships uint8 μ-law codes (half the bytes — worth it
        # when the host→device wire is the bottleneck, at ~38 dB SNR)
        # and expands them on device.  Lossy encoding is opt-in so
        # existing pipelines keep full input fidelity.
        wire, _ = self.get_parameter("wire", "int16")
        wire = str(wire)

        # the conditioning prompt: <|sot|> [lang task] [notimestamps];
        # timestamps off additionally masks timestamp ids out of the
        # argmax (sot_sequence_for validates vocab coverage)
        sot_sequence = sot_sequence_for(
            self.config, language=str(language) or None,
            task=str(task), timestamps=self.timestamps)
        # the prompt occupies decoder positions too; n_text_ctx was
        # sized max_tokens+8 above and the longest prompt is 4 tokens
        if len(sot_sequence) + max_tokens > self.config.n_text_ctx:
            raise ValueError(
                f"ASR element {self.name}: conditioning prompt "
                f"({len(sot_sequence)} tokens) + max_tokens "
                f"({max_tokens}) exceeds decoder context "
                f"{self.config.n_text_ctx}")

        # pp_stages >= 2: TRUE pipeline parallelism over device groups —
        # the mel+encoder stage runs on one group, the autoregressive
        # decode stage on another (StagedExecutor), with batch k+1
        # encoding while batch k decodes.  The compute program's
        # in_flight peak (EC share) is the measured overlap.  Each
        # stage carries only ITS OWN param subtree (encoder weights on
        # stage 0, decoder on stage 1), built once and shared by every
        # bucket's executor — not a full-model copy per bucket/stage.
        pp_stages, _ = self.get_parameter("pp_stages", 0)
        pp_stages = int(pp_stages)
        if pp_stages >= 2:
            self._stage_params = (
                {k: self.params[k]
                 for k in ("conv1", "conv2", "enc_blocks", "ln_enc")},
                {k: self.params[k]
                 for k in ("tok_embed", "pos_embed", "dec_blocks",
                           "ln_dec")},
            )

        def make_fn(bucket):
            import dataclasses
            config = dataclasses.replace(
                self.config, n_audio_ctx=bucket // 2)
            decode_kwargs = dict(max_tokens=max_tokens,
                                 sot_sequence=sot_sequence,
                                 suppress_timestamps=not self.timestamps,
                                 kv_quant=self.kv_quant)

            def to_mel(payload):
                if not audio_frontend:
                    return payload
                from ..ops.audio import (log_mel_spectrogram,
                                         mulaw_decode)
                if wire == "mulaw":
                    audio = mulaw_decode(payload)
                else:
                    audio = payload.astype(jnp.float32) / 32768.0
                return log_mel_spectrogram(
                    audio, num_mels=config.n_mels).astype(config.dtype)

            if pp_stages >= 2:
                from ..models.whisper import (encode,
                                              greedy_decode_from_audio)
                from ..parallel.pipeline_parallel import StagedExecutor

                def stage_encode(params, payload):
                    return encode(params, config,
                                  to_mel(payload).astype(config.dtype))

                def stage_decode(params, audio):
                    return greedy_decode_from_audio(params, config,
                                                    audio,
                                                    **decode_kwargs)

                executor = StagedExecutor(
                    [(stage_encode, self._stage_params[0]),
                     (stage_decode, self._stage_params[1])])

                def run_staged(_params, batch):
                    y = executor.submit(batch)
                    # occupancy here is tracked by the compute
                    # program's in_flight (split() retires there, not
                    # through executor.collect) — undo submit's count
                    # so the executor's gauge can't drift upward
                    executor.in_flight -= 1
                    return y
                return run_staged

            def fused(params, payload):
                # wire codes expand to float on device: the host does
                # no per-frame feature work at all
                return greedy_decode_scored(
                    params, config, to_mel(payload).astype(config.dtype),
                    **decode_kwargs)
            return jax.jit(fused)

        def run_bucket(bucket, batch):
            if bucket not in per_bucket_config:
                per_bucket_config[bucket] = make_fn(bucket)
            return per_bucket_config[bucket](self.params, batch)

        # batched mode pads the batch dim to max_batch so each bucket
        # compiles exactly ONE program (a partial batch otherwise means a
        # fresh XLA compile per distinct size — a recompilation storm in
        # serving); split() slices the real rows back out.
        pad_batch, _ = self.get_parameter("pad_batch",
                                          self.mode == "batched")
        pad_batch = parse_bool(pad_batch, self.mode == "batched")

        def rows(count):
            return int(max_batch) if pad_batch else count

        def collate(bucket, payloads):
            if audio_frontend:
                from ..ops.audio import WHISPER_HOP, mulaw_encode
                if wire == "mulaw":
                    # silence encodes to code 128 (μ-law zero), not 0
                    batch = np.full((rows(len(payloads)),
                                     bucket * WHISPER_HOP), 128,
                                    dtype="uint8")
                    for i, audio in enumerate(payloads):
                        audio = np.asarray(audio)
                        t = min(audio.shape[0], batch.shape[1])
                        if audio.dtype == np.uint8:
                            # already µ-law codes (an ingest element or
                            # the binary wire path encoded once): pure
                            # copy, no per-frame transcode
                            batch[i, :t] = audio[:t]
                        else:
                            batch[i, :t] = mulaw_encode(audio[:t])
                    return jnp.asarray(batch)
                batch = np.zeros((rows(len(payloads)),
                                  bucket * WHISPER_HOP), dtype="int16")
                for i, audio in enumerate(payloads):
                    audio = np.asarray(audio)
                    t = min(audio.shape[0], batch.shape[1])
                    if audio.dtype == np.int16:
                        batch[i, :t] = audio[:t]
                    else:      # float [-1, 1] → 16-bit PCM quantization
                        batch[i, :t] = np.clip(
                            audio[:t] * 32767.0, -32768, 32767
                        ).astype(np.int16)
                return jnp.asarray(batch)
            batch = np.zeros((rows(len(payloads)), bucket,
                              self.config.n_mels), dtype="float32")
            for i, mel in enumerate(payloads):
                mel = np.asarray(mel)
                if mel.dtype == np.int8 and \
                        mel.shape[-1] == self.config.n_mels + 4:
                    # pre-encoded i8mel codes (an ingest element packed
                    # once, or a pipeline shipped packed rows end to
                    # end): per-row scales ride the trailing 4 bytes —
                    # expand on the host, no per-frame transcode upstream
                    from ..ops.audio import mel_i8_unpack
                    mel = mel_i8_unpack(mel)
                t = min(mel.shape[0], bucket)
                batch[i, :t] = mel[:t]
            return jnp.asarray(batch, jnp.bfloat16)

        def split(results, count):
            tokens, lengths, avg_logprob = results
            tokens = np.asarray(tokens)
            lengths = np.asarray(lengths)
            avg_logprob = np.asarray(avg_logprob)
            return [(tokens[i, :lengths[i]], int(lengths[i]),
                     float(avg_logprob[i])) for i in range(count)]

        from ..compute import resolve_pipelined
        pipelined, _ = self.get_parameter("pipelined", False)
        pipelined = resolve_pipelined(pipelined, self.mode)
        max_in_flight, _ = self.get_parameter("max_in_flight", 4)
        self.compute.register_batched(
            self._program, run_bucket, buckets, collate, split,
            max_batch=int(max_batch), max_wait=float(max_wait),
            pipelined=pipelined, max_in_flight=int(max_in_flight))
        self._setup_done = True

    def start_stream(self, stream) -> None:
        self._setup()

    def process_frame(self, frame: Frame, mel=None, audio=None,
                      **_) -> FrameOutput:
        self._setup()
        if self.frontend == "audio":
            from ..ops.audio import WHISPER_HOP
            mel = audio                    # payload is raw samples
            length = int(audio.shape[0]) // WHISPER_HOP
        else:
            length = int(mel.shape[0])
        if self.mode == "sync":
            box = {}
            self.compute.submit(self._program, frame.stream_id, mel,
                                length,
                                lambda _sid, r: box.setdefault("r", r))
            self.compute.programs[self._program].scheduler.drain(
                force=True)
            result = box["r"]
            if isinstance(result, Exception):
                return FrameOutput(False, diagnostic=repr(result))
            return FrameOutput(True, self._to_outputs(result))

        def callback(_sid, result):
            # scheduler drains on the event loop; resume via the mailbox so
            # ordering with other pipeline work is preserved
            self.pipeline.post("resume_frame", frame,
                               self.definition.name,
                               result if isinstance(result, Exception)
                               else self._to_outputs(result))

        deadline = (self.runtime.event.clock.now() + self.deadline_s) \
            if self.deadline_s > 0 else None
        self.compute.submit(self._program, frame.stream_id, mel, length,
                            callback, deadline=deadline)
        return FrameOutput(True, DEFERRED)

    def _to_outputs(self, result):
        tokens, length, avg_logprob = result
        outputs = {"tokens": tokens, "avg_logprob": avg_logprob}
        if self.timestamps:
            from ..models.whisper import parse_timestamp_segments
            segments, text_tokens = parse_timestamp_segments(tokens,
                                                             length)
            text = self.detokenizer([int(t) for t in text_tokens])
            outputs["segments"] = [
                seg | {"text": self.detokenizer(
                    [int(t) for t in seg["tokens"]])}
                for seg in segments]
        else:
            text = self.detokenizer([int(t) for t in tokens[:length]])
        # hallucination gates, the reference ASR element's filtering
        # behavior (speech_elements.py:174-250): improbable decodes
        # (low mean logprob) or degenerate repetition (text that zlib
        # squashes too well) are suppressed rather than emitted
        reason = ""
        if avg_logprob < self.logprob_threshold:
            reason = f"avg_logprob {avg_logprob:.2f} < " \
                     f"{self.logprob_threshold}"
        else:
            ratio = compression_ratio(text)
            if ratio > self.compression_threshold:
                reason = (f"compression_ratio {ratio:.2f} > "
                          f"{self.compression_threshold}")
        if reason:
            # a suppressed decode must not leak its hallucinated
            # transcript through ANY output — text, segments, or the
            # raw token ids a downstream detokenizer/agent would read
            import numpy as np
            outputs |= {"text": "", "suppressed": reason,
                        "tokens": np.zeros((0,), np.int32)}
            if "segments" in outputs:
                outputs["segments"] = []
        else:
            outputs["text"] = text
        return outputs


def _whisper_axes(config):
    from ..models.whisper import whisper_axes
    return whisper_axes(config)


def load_flat_npz(params, pathname: str):
    """Overlay weights from an npz whose keys are '/'-joined tree paths
    (e.g. "dec_blocks/3/attn/q/w").  Leaves absent from the file keep
    their initialized values; shape mismatches raise."""
    import numpy as np
    import jax

    flat = dict(np.load(pathname))

    def overlay(path, leaf):
        key = _tree_path_str(path)
        if key not in flat:
            return leaf
        loaded = flat[key]
        shape = tuple(leaf.shape)
        if loaded.shape != shape:
            # position tables may be longer in the checkpoint than the
            # serving context (e.g. 448-token pos_embed, 24-token server):
            # a leading-dim prefix is the correct slice for them
            if (loaded.ndim == leaf.ndim and
                    loaded.shape[1:] == shape[1:] and
                    loaded.shape[0] > shape[0] and
                    key.rsplit("/", 1)[-1].startswith("pos_embed")):
                loaded = loaded[:shape[0]]
            else:
                raise ValueError(f"weights[{key}]: shape {loaded.shape} "
                                 f"!= model {shape}")
        return loaded.astype(leaf.dtype)

    return jax.tree_util.tree_map_with_path(overlay, params)


def _tree_path_str(path) -> str:
    """jax tree path → the '/'-joined key scheme of the flat-npz format."""
    parts = []
    for entry in path:
        key = getattr(entry, "key", getattr(entry, "idx", None))
        parts.append(str(key))
    return "/".join(parts)


def save_flat_npz(params, pathname: str) -> None:
    """Inverse of load_flat_npz: write a param tree as an npz of
    '/'-joined tree paths (the checkpoint interchange scheme the weight
    converter in tools/convert_whisper.py also produces)."""
    import numpy as np
    import jax

    def to_numpy(leaf):
        arr = np.asarray(leaf)
        if arr.dtype.kind == "V":
            # ml_dtypes (bfloat16 etc.) round-trip through npz as raw
            # void bytes that np.load can't cast back — store the
            # interchange checkpoint as f32 (lossless upcast; the
            # loader casts to the model dtype anyway)
            arr = arr.astype(np.float32)
        return arr

    flat = {}
    jax.tree_util.tree_map_with_path(
        lambda path, leaf: flat.__setitem__(_tree_path_str(path),
                                            to_numpy(leaf)), params)
    np.savez(pathname, **flat)


class PE_Synthesize(PipelineElement):
    """Placeholder TTS: deterministic formant-ish sine stack per token —
    keeps the text→audio seam exercised end-to-end until a neural TTS
    model lands (reference uses Coqui VITS, speech_elements.py:96-131)."""

    contracts = {"in:text": "str", "out:audio": "f32[*]"}

    def process_frame(self, frame: Frame, text="", **_) -> FrameOutput:
        import numpy as np

        words = str(text).split() or ["_"]
        duration = 0.08
        t = np.arange(int(SAMPLE_RATE * duration)) / SAMPLE_RATE
        chunks = []
        for word in words:
            f0 = 110.0 + (hash(word) % 800)
            tone = (0.5 * np.sin(2 * np.pi * f0 * t) +
                    0.25 * np.sin(2 * np.pi * 2 * f0 * t))
            envelope = np.minimum(1.0, 10 * (1 - np.abs(2 * t /
                                                        duration - 1)))
            chunks.append((tone * envelope).astype(np.float32))
        return FrameOutput(True, {"audio": np.concatenate(chunks)})


class PE_AudioReadFile(PipelineElement):
    """Source: reads a wav file per frame from parameter/swag `pathname`,
    emits float32 audio (chunked via parameter chunk_seconds, 0 = whole
    file)."""

    def process_frame(self, frame: Frame, pathname=None, **_) -> FrameOutput:
        if pathname is None:
            pathname, found = self.get_parameter("pathname",
                                                 stream=frame.stream)
            if not found:
                return FrameOutput(False, diagnostic="no pathname")
        audio, rate = load_wav(str(pathname))
        return FrameOutput(True, {"audio": audio, "sample_rate": rate})


class PE_AudioWriteFile(PipelineElement):
    """Sink: appends audio chunks to a wav file per stream
    (reference: speech_elements.py PE_AudioWriteFile)."""

    def process_frame(self, frame: Frame, audio=None, **_) -> FrameOutput:
        import numpy as np

        pathname, found = self.get_parameter("pathname",
                                             stream=frame.stream)
        if not found:
            return FrameOutput(False, diagnostic="no pathname")
        pathname = str(pathname).format(stream_id=frame.stream_id)
        key = f"{self.definition.name}.audio"
        existing = frame.stream.variables.get(key)
        combined = np.asarray(audio) if existing is None else \
            np.concatenate([existing, np.asarray(audio)])
        frame.stream.variables[key] = combined
        save_wav(pathname, combined)
        return FrameOutput(True, {})
