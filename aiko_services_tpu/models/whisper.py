# Whisper: encoder-decoder speech recognition, TPU-native.
#
# Capability parity target: the reference's ASR element wraps faster-whisper
# on CUDA ("small" default — reference: examples/speech/speech_elements.py:
# 174-250); here the architecture is implemented directly in jax so it jits
# onto the MXU, batches across streams, and shards over a mesh (heads/ffn on
# the model axis via layers.py logical axes).
#
# Architecture (Radford et al., "Robust Speech Recognition via Large-Scale
# Weak Supervision"): log-mel [B, T, 80] → 2×conv(gelu, stride 1/2) →
# sinusoidal positions → pre-norm transformer encoder; decoder = learned
# positions + causal self-attention + cross-attention, weight-tied logits.
# Greedy decode runs as a single lax.scan with static-shape KV caches: one
# compiled program per (batch, max_len) bucket — no per-token Python.

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["WhisperConfig", "whisper_init", "whisper_axes", "encode",
           "decode_step", "greedy_decode", "greedy_decode_scored",
           "greedy_decode_from_audio", "forward", "WHISPER_PRESETS",
           "sot_sequence_for", "parse_timestamp_segments", "LANGUAGES"]


@dataclass(frozen=True)
class WhisperConfig:
    n_mels: int = 80
    n_audio_ctx: int = 1500        # frames after stride-2 conv (30 s)
    n_text_ctx: int = 448
    n_vocab: int = 51865
    dim: int = 768
    num_heads: int = 12
    enc_layers: int = 12
    dec_layers: int = 12
    dtype: object = jnp.float32
    # special tokens (multilingual tokenizer defaults, as in openai
    # whisper); presets with small vocabularies override them so the ids
    # stay in-range — greedy_decode asserts this
    sot: int = 50258
    eot: int = 50257

    @property
    def head_dim(self):
        return self.dim // self.num_heads


# parameter table mirrors the reference's model-size table
# (speech_elements.py:175-180: tiny 39M … large 1550M)
WHISPER_PRESETS = {
    # not a real whisper size: CI/smoke geometry (real 80-mel frontend,
    # toy transformer) so end-to-end speech tests run in seconds on CPU
    "test":   WhisperConfig(dim=64,   num_heads=4,  enc_layers=2,
                            dec_layers=2, n_vocab=256, sot=254, eot=255),
    "tiny":   WhisperConfig(dim=384,  num_heads=6,  enc_layers=4,
                            dec_layers=4),
    "base":   WhisperConfig(dim=512,  num_heads=8,  enc_layers=6,
                            dec_layers=6),
    "small":  WhisperConfig(dim=768,  num_heads=12, enc_layers=12,
                            dec_layers=12),
    "medium": WhisperConfig(dim=1024, num_heads=16, enc_layers=24,
                            dec_layers=24),
    "large":  WhisperConfig(dim=1280, num_heads=20, enc_layers=32,
                            dec_layers=32),
}

# Special tokens (multilingual tokenizer ids, as in openai/whisper)
SOT = 50258
EOT = 50257
TOKEN_TRANSLATE = 50358
TOKEN_TRANSCRIBE = 50359
TOKEN_NO_TIMESTAMPS = 50363
TOKEN_TIMESTAMP_BEGIN = 50364       # <|0.00|>; each id adds 0.02 s
TIMESTAMP_STEP_S = 0.02

# Language order of the multilingual tokenizer: token id for language i
# is SOT + 1 + i (reference capability: speech_elements.py:174-250 pins
# language="en" through faster-whisper; here it's a prompt token)
LANGUAGES = (
    "en", "zh", "de", "es", "ru", "ko", "fr", "ja", "pt", "tr", "pl",
    "ca", "nl", "ar", "sv", "it", "id", "hi", "fi", "vi", "he", "uk",
    "el", "ms", "cs", "ro", "da", "hu", "ta", "no", "th", "ur", "hr",
    "bg", "lt", "la", "mi", "ml", "cy", "sk", "te", "fa", "lv", "bn",
    "sr", "az", "sl", "kn", "et", "mk", "br", "eu", "is", "hy", "ne",
    "mn", "bs", "kk", "sq", "sw", "gl", "mr", "pa", "si", "km", "sn",
    "yo", "so", "af", "oc", "ka", "be", "tg", "sd", "gu", "am", "yi",
    "lo", "uz", "fo", "ht", "ps", "tk", "nn", "mt", "sa", "lb", "my",
    "bo", "tl", "mg", "as", "tt", "haw", "ln", "ha", "ba", "jw", "su")


def sot_sequence_for(config: WhisperConfig, language: str | None = None,
                     task: str = "transcribe",
                     timestamps: bool = False) -> tuple:
    """The start-of-transcript prompt that conditions decoding, as in
    openai/whisper: <|sot|> [<|lang|> <|task|>] [<|notimestamps|>].

    Language/task tokens only exist in the real multilingual vocab —
    asking for them on a small-vocab preset is an error, not a silent
    degradation."""
    if task not in ("transcribe", "translate"):
        raise ValueError(f"unknown task {task!r}")
    if task == "translate" and language is None:
        # the task token only exists alongside a language token —
        # silently transcribing instead would be exactly the quiet
        # degradation this function promises not to do
        raise ValueError("task='translate' requires a language")
    sequence = [config.sot]
    if language is not None:
        if language not in LANGUAGES:
            raise ValueError(f"unknown language {language!r}")
        lang_token = SOT + 1 + LANGUAGES.index(language)
        task_token = {"transcribe": TOKEN_TRANSCRIBE,
                      "translate": TOKEN_TRANSLATE}[task]
        if max(lang_token, task_token) >= config.n_vocab:
            raise ValueError(
                f"language/task conditioning needs the multilingual "
                f"vocab (n_vocab {config.n_vocab} too small)")
        sequence += [lang_token, task_token]
    if not timestamps and TOKEN_NO_TIMESTAMPS < config.n_vocab:
        sequence.append(TOKEN_NO_TIMESTAMPS)
    return tuple(sequence)


def parse_timestamp_segments(tokens, length: int,
                             timestamp_begin: int = TOKEN_TIMESTAMP_BEGIN):
    """Split a decoded token sequence on timestamp tokens.

    Returns (segments, text_tokens): segments are
    {"start": s, "end": s, "tokens": [...]} with seconds decoded from
    the 0.02 s grid; text_tokens is everything with the timestamp
    markers stripped (what the detokenizer should see)."""
    segments, text_tokens = [], []
    current, start = [], None
    for token in list(tokens)[:length]:
        token = int(token)
        if token >= timestamp_begin:
            seconds = (token - timestamp_begin) * TIMESTAMP_STEP_S
            if start is None:
                start = seconds
            else:
                segments.append({"start": start, "end": seconds,
                                 "tokens": current})
                current, start = [], None
        else:
            current.append(token)
            text_tokens.append(token)
    if current:
        segments.append({"start": start or 0.0, "end": None,
                         "tokens": current})
    return segments, text_tokens


def _block_init(key, config: WhisperConfig, cross: bool):
    keys = jax.random.split(key, 5)
    dim, dtype = config.dim, config.dtype
    params = {
        "ln_attn": L.layer_norm_init(dim, dtype),
        "attn": L.mha_init(keys[0], dim, config.num_heads, dtype=dtype),
        "ln_mlp": L.layer_norm_init(dim, dtype),
        "mlp_in": L.linear_init(keys[1], dim, dim * 4, dtype=dtype),
        "mlp_out": L.linear_init(keys[2], dim * 4, dim, dtype=dtype),
    }
    if cross:
        params["ln_cross"] = L.layer_norm_init(dim, dtype)
        params["cross"] = L.mha_init(keys[3], dim, config.num_heads,
                                     dtype=dtype)
    return params


def _block_axes(cross: bool):
    axes = {
        "ln_attn": L.layer_norm_axes(),
        "attn": L.mha_axes(),
        "ln_mlp": L.layer_norm_axes(),
        "mlp_in": L.linear_axes("embed", "ffn"),
        "mlp_out": L.linear_axes("ffn", "embed"),
    }
    if cross:
        axes["ln_cross"] = L.layer_norm_axes()
        axes["cross"] = L.mha_axes()
    return axes


def whisper_init(key, config: WhisperConfig):
    keys = jax.random.split(key, config.enc_layers + config.dec_layers + 4)
    k_iter = iter(keys)
    dtype = config.dtype
    return {
        "conv1": L.conv1d_init(next(k_iter), config.n_mels, config.dim, 3,
                               dtype),
        "conv2": L.conv1d_init(next(k_iter), config.dim, config.dim, 3,
                               dtype),
        "enc_blocks": [_block_init(next(k_iter), config, cross=False)
                       for _ in range(config.enc_layers)],
        "ln_enc": L.layer_norm_init(config.dim, dtype),
        "tok_embed": L.embedding_init(next(k_iter), config.n_vocab,
                                      config.dim, dtype),
        "pos_embed": (jax.random.normal(
            next(k_iter), (config.n_text_ctx, config.dim)) * 0.01
            ).astype(dtype),
        "dec_blocks": [_block_init(next(k_iter), config, cross=True)
                       for _ in range(config.dec_layers)],
        "ln_dec": L.layer_norm_init(config.dim, dtype),
    }


def whisper_axes(config: WhisperConfig):
    return {
        "conv1": L.conv1d_axes(),
        "conv2": L.conv1d_axes(),
        "enc_blocks": [_block_axes(False)] * config.enc_layers,
        "ln_enc": L.layer_norm_axes(),
        "tok_embed": L.embedding_axes(),
        "pos_embed": (None, "embed"),
        "dec_blocks": [_block_axes(True)] * config.dec_layers,
        "ln_dec": L.layer_norm_axes(),
    }


def _mlp(block, x):
    return L.linear(block["mlp_out"],
                    L.gelu(L.linear(block["mlp_in"], x)))


def _encoder_block(block, x, num_heads):
    attn_out, _ = L.mha(block["attn"], L.layer_norm(block["ln_attn"], x),
                        num_heads=num_heads)
    x = x + attn_out
    return x + _mlp(block, L.layer_norm(block["ln_mlp"], x))


def encode(params, config: WhisperConfig, mel):
    """mel: [B, T_frames, n_mels] (T_frames = 2 * n_audio_ctx for 30 s)
    → audio features [B, n_audio_ctx, dim]."""
    x = L.gelu(L.conv1d(params["conv1"], mel.astype(config.dtype)))
    x = L.gelu(L.conv1d(params["conv2"], x, stride=2))
    positions = L.sinusoid_position_encoding(x.shape[1], config.dim)
    x = x + positions.astype(x.dtype)
    for block in params["enc_blocks"]:
        x = _encoder_block(block, x, config.num_heads)
    return L.layer_norm(params["ln_enc"], x)


def _decoder_block(block, x, cross_kv, num_heads, self_cache, mask):
    attn_out, self_cache = L.mha(
        block["attn"], L.layer_norm(block["ln_attn"], x),
        cache=self_cache, mask=mask, num_heads=num_heads)
    x = x + attn_out
    cross_out, _ = L.mha(block["cross"],
                         L.layer_norm(block["ln_cross"], x),
                         precomputed_kv=cross_kv, num_heads=num_heads)
    x = x + cross_out
    return x + _mlp(block, L.layer_norm(block["ln_mlp"], x)), self_cache


def precompute_cross_kv(params, config: WhisperConfig, audio,
                        quantize=False):
    """Project every decoder block's cross-attention K/V over the audio
    features ONCE per utterance — the decode loop then only projects Q
    (recomputing these per token was pure wasted MXU work).

    quantize: False (bf16), True/"position" (int8, per-position
    scales — memory lever only: the dequant multiply re-materializes
    per decode step, measured −24%), or "tensor" (int8, one scale per
    BATCH ELEMENT — the dequant is a bare convert that fuses into the
    attention dot; mha folds the per-batch scale into the softmax
    scale.  Half the decode tail's dominant read, measured −14%
    round).  See layers.quantize_kv for the measured numbers."""
    kv = [L.precompute_kv(block["cross"], audio, config.num_heads)
          for block in params["dec_blocks"]]
    if quantize:
        mode = quantize if isinstance(quantize, str) else "position"
        kv = [(L.quantize_kv(k, mode), L.quantize_kv(v, mode))
              for k, v in kv]
    return kv


def init_caches(config: WhisperConfig, batch: int,
                max_len: int | None = None):
    max_len = max_len or config.n_text_ctx
    return [L.init_kv_cache(batch, max_len, config.num_heads,
                            config.head_dim, config.dtype)
            for _ in range(config.dec_layers)]


def decode_step(params, config: WhisperConfig, tokens, cross_kv, caches,
                position_offset=0):
    """tokens: [B, T_step] (T_step=1 for incremental decode); cross_kv is
    precompute_cross_kv(...)'s output (a raw audio-features array is also
    accepted and projected on the fly).  Returns
    (logits [B, T_step, vocab], new_caches)."""
    if not isinstance(cross_kv, (list, tuple)):
        cross_kv = precompute_cross_kv(params, config, cross_kv)
    x = L.embedding(params["tok_embed"], tokens)
    t = tokens.shape[1]
    positions = position_offset + jnp.arange(t)
    x = x + jnp.take(params["pos_embed"], positions, axis=0)[None]
    x = x.astype(config.dtype)

    mask = None
    if t > 1:       # prompt prefill needs a causal mask within the step
        q_pos = position_offset + jnp.arange(t)[:, None]
        k_pos = jnp.arange(caches[0]["k"].shape[2])[None, :]
        mask = (k_pos <= q_pos)[None, None]

    new_caches = []
    for block, block_kv, cache in zip(params["dec_blocks"], cross_kv,
                                      caches):
        x, cache = _decoder_block(block, x, block_kv, config.num_heads,
                                  cache, mask)
        new_caches.append(cache)
    x = L.layer_norm(params["ln_dec"], x)
    logits = jnp.einsum("btd,vd->btv", x.astype(jnp.float32),
                        params["tok_embed"]["table"].astype(jnp.float32))
    return logits, new_caches


def greedy_decode(params, config: WhisperConfig, mel, max_tokens: int = 64,
                  sot_sequence=None, suppress_timestamps: bool = False,
                  kv_quant=False):
    """Batched greedy decoding as one compiled program.

    mel: [B, T_frames, n_mels] → (tokens [B, max_tokens], lengths [B]).
    See greedy_decode_scored for the scored variant."""
    tokens, lengths, _ = greedy_decode_scored(
        params, config, mel, max_tokens, sot_sequence,
        suppress_timestamps, kv_quant)
    return tokens, lengths


def greedy_decode_scored(params, config: WhisperConfig, mel,
                         max_tokens: int = 64, sot_sequence=None,
                         suppress_timestamps: bool = False,
                         kv_quant=False):
    """Batched greedy decoding with per-sequence quality scores.

    mel: [B, T_frames, n_mels] →
    (tokens [B, max_tokens], lengths [B], avg_logprob [B]).

    The token loop is a lax.scan over static-shape KV caches; finished
    sequences (EOT emitted) keep writing EOT — no dynamic shapes, so one
    compilation serves every utterance in the bucket.  avg_logprob is
    the mean log-probability of the emitted tokens (EOT included, as in
    openai/whisper) — the hallucination gate's first input.
    suppress_timestamps masks ids >= TOKEN_TIMESTAMP_BEGIN out of the
    argmax (the <|notimestamps|> decode mode)."""
    return greedy_decode_from_audio(
        params, config, encode(params, config, mel), max_tokens,
        sot_sequence, suppress_timestamps, kv_quant)


def greedy_decode_from_audio(params, config: WhisperConfig, audio,
                             max_tokens: int = 64, sot_sequence=None,
                             suppress_timestamps: bool = False,
                             kv_quant=False):
    """greedy_decode_scored from already-encoded audio features
    [B, n_audio_ctx, dim] — the pipeline-parallel stage boundary: an
    encoder stage on one device group hands features to a decode stage
    on another (parallel/pipeline_parallel.StagedExecutor)."""
    if sot_sequence is None:
        sot_sequence = (config.sot,)
    eot = config.eot
    if max(max(sot_sequence), eot) >= config.n_vocab:
        raise ValueError(
            f"special tokens {tuple(sot_sequence)}/eot={eot} out of range "
            f"for n_vocab={config.n_vocab}: embedding lookups would "
            f"silently clamp and early-stop could never fire")
    total = len(sot_sequence) + max_tokens
    if total > config.n_text_ctx:
        raise ValueError(
            f"sot({len(sot_sequence)}) + max_tokens({max_tokens}) exceeds "
            f"n_text_ctx({config.n_text_ctx}): positions past the table "
            f"would silently clamp")
    batch = audio.shape[0]
    cross_kv = precompute_cross_kv(params, config, audio,
                                   quantize=kv_quant)
    caches = init_caches(config, batch, max_len=total)

    if suppress_timestamps and TOKEN_TIMESTAMP_BEGIN < config.n_vocab:
        ts_mask = (jnp.arange(config.n_vocab) >=
                   TOKEN_TIMESTAMP_BEGIN)[None]
    else:
        ts_mask = None

    def pick(logits_last):
        if ts_mask is not None:
            logits_last = jnp.where(ts_mask, -jnp.inf, logits_last)
        token = jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        logprob = jnp.take_along_axis(
            jax.nn.log_softmax(logits_last, axis=-1),
            token[:, None], axis=-1)[:, 0]
        return token, logprob

    # prefill the start-of-transcript prompt
    prompt = jnp.tile(jnp.array(sot_sequence, jnp.int32)[None], (batch, 1))
    logits, caches = decode_step(params, config, prompt, cross_kv, caches)
    first, first_logprob = pick(logits[:, -1])

    def step(carry, position):
        # the carry token is EMITTED this iteration — its logprob
        # (computed when it was chosen) is scored now, so the final
        # never-emitted carry token never biases the mean
        token, token_logprob, caches, done_before, logprob_sum, \
            count = carry
        logprob_sum = logprob_sum + jnp.where(done_before, 0.0,
                                              token_logprob)
        count = count + jnp.where(done_before, 0, 1)
        done = done_before | (token == eot)
        logits, caches = decode_step(
            params, config, token[:, None], cross_kv, caches,
            position_offset=position)
        next_token, next_logprob = pick(logits[:, -1])
        next_token = jnp.where(done, eot, next_token)
        return (next_token, next_logprob, caches, done, logprob_sum,
                count), token

    positions = len(sot_sequence) + jnp.arange(max_tokens)
    (_, _, _, _, logprob_sum, count), tokens = jax.lax.scan(
        step, (first, first_logprob, caches,
               jnp.zeros((batch,), bool),
               jnp.zeros((batch,), jnp.float32),
               jnp.zeros((batch,), jnp.int32)), positions)
    tokens = jnp.moveaxis(tokens, 0, 1)            # [B, max_tokens]
    lengths = jnp.sum((tokens != eot).astype(jnp.int32), axis=1)
    return tokens, lengths, logprob_sum / jnp.maximum(count, 1)


def forward(params, config: WhisperConfig, mel, tokens):
    """Teacher-forced forward (training / scoring): full-sequence decoder.
    mel: [B, T, n_mels], tokens: [B, S] → logits [B, S, vocab]."""
    audio = encode(params, config, mel)
    batch, s = tokens.shape
    caches = init_caches(config, batch, max_len=s)
    logits, _ = decode_step(params, config, tokens, audio, caches)
    return logits
