# Llama-style decoder-only transformer, TPU-native.
#
# Parity target: BASELINE.md config 5 ("xgo_robot vision+ASR+Llama-3-8B
# agent sharded over v5e-16") — the reference only reaches an LLM through
# an HTTP hop (reference: examples/speech/speech_elements.py:155-172); here
# the model is native so the agent element shards over the mesh (TP on
# heads/ffn via logical axes, GQA KV heads, RoPE, RMSNorm, SwiGLU).

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["LlamaConfig", "llama_init", "llama_axes", "llama_forward",
           "llama_forward_sp", "llama_decode_step", "llama_greedy_decode",
           "llama_ffn", "init_llama_caches", "LLAMA_PRESETS"]


@dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 128256
    dim: int = 4096
    ffn_dim: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: object = jnp.float32
    # num_experts > 0 swaps the dense SwiGLU FFN for a top-k
    # mixture-of-experts layer (models/moe.py) — the Mixtral-style
    # geometry.  Every path (prefill, SP forward, ContinuousDecoder)
    # routes through llama_ffn, so the MoE variant serves identically.
    num_experts: int = 0
    top_k: int = 2

    @property
    def head_dim(self):
        return self.dim // self.num_heads

    def moe_config(self):
        from .moe import MoeConfig
        return MoeConfig(dim=self.dim, ffn_dim=self.ffn_dim,
                         num_experts=self.num_experts,
                         top_k=self.top_k, dtype=self.dtype)


LLAMA_PRESETS = {
    # llama-3-8b geometry (the BASELINE agent config)
    "8b": LlamaConfig(),
    # scaled-down variants for tests / CI / single-chip smoke
    "tiny": LlamaConfig(vocab=256, dim=64, ffn_dim=128, num_layers=2,
                        num_heads=4, num_kv_heads=2, max_seq_len=128),
    "1b": LlamaConfig(vocab=128256, dim=2048, ffn_dim=8192, num_layers=16,
                      num_heads=32, num_kv_heads=8),
    # MoE variants (Mixtral-style FFN): tiny for tests/dryrun, 8x1b as
    # the serving-scale geometry
    "tiny_moe": LlamaConfig(vocab=256, dim=64, ffn_dim=128, num_layers=2,
                            num_heads=4, num_kv_heads=2, max_seq_len=128,
                            num_experts=4, top_k=2),
    "8x1b": LlamaConfig(vocab=128256, dim=2048, ffn_dim=8192,
                        num_layers=16, num_heads=32, num_kv_heads=8,
                        num_experts=8, top_k=2),
}


def _layer_init(key, config: LlamaConfig):
    keys = jax.random.split(key, 4)
    dim, dtype = config.dim, config.dtype
    layer = {
        "ln_attn": L.rms_norm_init(dim, dtype),
        "attn": L.mha_init(keys[0], dim, config.num_heads,
                           config.num_kv_heads, bias=False, dtype=dtype),
        "ln_mlp": L.rms_norm_init(dim, dtype),
    }
    if config.num_experts:
        from .moe import moe_init
        layer["moe"] = moe_init(keys[1], config.moe_config())
    else:
        layer |= {
            "gate": L.linear_init(keys[1], dim, config.ffn_dim,
                                  bias=False, dtype=dtype),
            "up": L.linear_init(keys[2], dim, config.ffn_dim,
                                bias=False, dtype=dtype),
            "down": L.linear_init(keys[3], config.ffn_dim, dim,
                                  bias=False, dtype=dtype),
        }
    return layer


def _layer_axes(config: LlamaConfig | None = None):
    axes = {
        "ln_attn": L.rms_norm_axes(),
        "attn": L.mha_axes(bias=False),
        "ln_mlp": L.rms_norm_axes(),
    }
    if config is not None and config.num_experts:
        from .moe import moe_axes
        axes["moe"] = moe_axes()
    else:
        axes |= {
            "gate": L.linear_axes("embed", "ffn", bias=False),
            "up": L.linear_axes("embed", "ffn", bias=False),
            "down": L.linear_axes("ffn", "embed", bias=False),
        }
    return axes


def llama_init(key, config: LlamaConfig):
    keys = jax.random.split(key, config.num_layers + 2)
    return {
        "embed": L.embedding_init(keys[0], config.vocab, config.dim,
                                  config.dtype),
        "layers": [_layer_init(keys[i + 1], config)
                   for i in range(config.num_layers)],
        "ln_out": L.rms_norm_init(config.dim, config.dtype),
        "lm_head": L.linear_init(keys[-1], config.dim, config.vocab,
                                 bias=False, dtype=config.dtype),
    }


def llama_axes(config: LlamaConfig):
    return {
        "embed": L.embedding_axes(),
        "layers": [_layer_axes(config)] * config.num_layers,
        "ln_out": L.rms_norm_axes(),
        "lm_head": L.linear_axes("embed", "vocab", bias=False),
    }


def init_llama_caches(config: LlamaConfig, batch: int,
                      max_len: int | None = None):
    return [L.init_kv_cache(batch, max_len or config.max_seq_len,
                            config.num_kv_heads, config.head_dim,
                            config.dtype)
            for _ in range(config.num_layers)]


def _attention(layer, config: LlamaConfig, x, cos, sin, cache,
               position_offset, mask):
    """RoPE attention with GQA + KV cache: layers.mha with the rotation
    injected via qk_transform, so cached keys are stored
    already-positioned."""
    def rope(q, k):
        return (L.apply_rope(q, cos, sin, position_offset),
                L.apply_rope(k, cos, sin, position_offset))

    return L.mha(layer["attn"], x, mask=mask, cache=cache,
                 num_heads=config.num_heads,
                 num_kv_heads=config.num_kv_heads, qk_transform=rope)


def _swiglu(layer, x):
    if "gate_up" in layer:
        # serving._fuse_decode_projections form: one [dim, 2*ffn]
        # matmul, split after — halves the FFN's projection op count
        # for tiny-M decode steps
        gate_up = L.linear(layer["gate_up"], x)
        ffn = gate_up.shape[-1] // 2
        return L.linear(layer["down"],
                        jax.nn.silu(gate_up[..., :ffn]) *
                        gate_up[..., ffn:])
    return L.linear(layer["down"],
                    jax.nn.silu(L.linear(layer["gate"], x)) *
                    L.linear(layer["up"], x))


def llama_ffn(layer, config: LlamaConfig, x):
    """The per-layer FFN: dense SwiGLU, or top-k MoE when the config
    says so.  Single seam shared by prefill, SP forward, and the
    continuous-batching decode step — an MoE checkpoint serves through
    the same machinery as a dense one."""
    if config.num_experts:
        from .moe import moe_forward
        y, _ = moe_forward(layer["moe"], config.moe_config(), x)
        return y
    return _swiglu(layer, x)


def llama_hidden(params, config: LlamaConfig, tokens, caches,
                 position_offset=0):
    """tokens: [B, T] → (final hidden states [B, T, dim], new_caches).
    T=1 for incremental decode; T>1 prefills with an in-step causal
    mask.  Split from the lm_head so prefill callers can select the
    position(s) they need BEFORE the vocab projection — full-sequence
    prefill logits are [B, T, vocab] (gigabytes at serving widths)."""
    cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                  config.rope_theta)
    x = L.embedding(params["embed"], tokens).astype(config.dtype)
    t = tokens.shape[1]

    mask = None
    if t > 1:
        q_pos = position_offset + jnp.arange(t)[:, None]
        k_pos = jnp.arange(caches[0]["k"].shape[2])[None, :]
        mask = (k_pos <= q_pos)[None, None]

    new_caches = []
    for layer, cache in zip(params["layers"], caches):
        attn_out, cache = _attention(
            layer, config, L.rms_norm(layer["ln_attn"], x), cos, sin,
            cache, position_offset, mask)
        x = x + attn_out
        x = x + llama_ffn(layer, config, L.rms_norm(layer["ln_mlp"], x))
        new_caches.append(cache)
    return L.rms_norm(params["ln_out"], x), new_caches


def llama_decode_step(params, config: LlamaConfig, tokens, caches,
                      position_offset=0):
    """tokens: [B, T] → (logits [B, T, vocab], new_caches)."""
    x, new_caches = llama_hidden(params, config, tokens, caches,
                                 position_offset)
    logits = L.linear(params["lm_head"], x.astype(jnp.float32))
    return logits, new_caches


def llama_forward(params, config: LlamaConfig, tokens):
    """Teacher-forced full-sequence forward: tokens [B, S] → logits."""
    caches = init_llama_caches(config, tokens.shape[0], tokens.shape[1])
    logits, _ = llama_decode_step(params, config, tokens, caches)
    return logits


def llama_forward_sp(params, config: LlamaConfig, tokens, mesh,
                     axis_name: str = "seq", batch_axis: str = "data"):
    """Sequence-parallel long-context forward (prefill): activations
    sharded over the sequence axis; exact causal attention via ring
    attention (K/V blocks rotate over ICI, online softmax — SURVEY §5.7).

    tokens: [B, S] with S divisible by the `axis_name` mesh size.
    Returns logits [B, S, vocab] sharded the same way.  This is how a
    prompt too long for one chip's memory prefills: each device holds
    S/n of the sequence and never materializes the S×S score matrix."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.ring_attention import ring_attention_sharded

    def body(params, tokens_local):
        cos, sin = L.rope_frequencies(config.head_dim, config.max_seq_len,
                                      config.rope_theta)
        s_local = tokens_local.shape[1]
        offset = lax.axis_index(axis_name) * s_local
        x = L.embedding(params["embed"],
                        tokens_local).astype(config.dtype)
        for layer in params["layers"]:
            normed = L.rms_norm(layer["ln_attn"], x)
            q = L._split_heads(L.linear(layer["attn"]["q"], normed),
                               config.num_heads)
            k = L._split_heads(L.linear(layer["attn"]["k"], normed),
                               config.num_kv_heads)
            v = L._split_heads(L.linear(layer["attn"]["v"], normed),
                               config.num_kv_heads)
            q = L.apply_rope(q, cos, sin, offset)
            k = L.apply_rope(k, cos, sin, offset)
            # K/V stay at num_kv_heads: the ring rotates the small
            # blocks and expands per-block (GQA-aware ring attention)
            attn = ring_attention_sharded(q, k, v, axis_name=axis_name,
                                          causal=True)
            x = x + L.linear(layer["attn"]["o"], L._merge_heads(attn))
            normed = L.rms_norm(layer["ln_mlp"], x)
            x = x + llama_ffn(layer, config, normed)
        x = L.rms_norm(params["ln_out"], x)
        return L.linear(params["lm_head"], x.astype(jnp.float32))

    batch = batch_axis if batch_axis in mesh.axis_names else None
    token_spec = P(batch, axis_name)
    param_specs = jax.tree.map(lambda _: P(), params)   # replicated
    from ..parallel.collectives import shard_map
    return shard_map(
        body, mesh=mesh, in_specs=(param_specs, token_spec),
        out_specs=P(batch, axis_name, None))(params, tokens)


def llama_greedy_decode(params, config: LlamaConfig, prompt,
                        max_tokens: int = 32, eos_token: int | None = None):
    """prompt: [B, S] → generated tokens [B, max_tokens].  One lax.scan,
    static shapes, caches threaded through the carry."""
    batch, prompt_len = prompt.shape
    caches = init_llama_caches(config, batch, prompt_len + max_tokens)
    logits, caches = llama_decode_step(params, config, prompt, caches)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    eos = eos_token if eos_token is not None else -1

    def step(carry, position):
        token, caches, done = carry
        logits, caches = llama_decode_step(
            params, config, token[:, None], caches,
            position_offset=position)
        next_token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        next_token = jnp.where(done, eos, next_token)
        done = done | (next_token == eos)
        return (next_token, caches, done), token

    positions = prompt_len + jnp.arange(max_tokens)
    (_, _, _), tokens = jax.lax.scan(
        step, (first, caches, first == eos), positions)
    return jnp.moveaxis(tokens, 0, 1)
