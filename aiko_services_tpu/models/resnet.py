# ResNet: residual conv classifier, TPU-native.
#
# Parity target: BASELINE.md config 2 ("examples/pipeline: ResNet-18
# image-classify PipelineElement") — the reference has no model code of its
# own (SURVEY.md §2).  Inference-mode batchnorm (folded running stats);
# NHWC layout (TPU-native); channels on the logical "channels" axis so a
# mesh can shard large batches over data and keep convs MXU-tiled.

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

__all__ = ["ResNetConfig", "resnet_init", "resnet_axes", "resnet_forward",
           "resnet_features", "RESNET_PRESETS"]


@dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (2, 2, 2, 2)       # ResNet-18
    num_classes: int = 1000
    width: int = 64
    dtype: object = jnp.float32


RESNET_PRESETS = {
    "resnet18": ResNetConfig((2, 2, 2, 2)),
    "resnet34": ResNetConfig((3, 4, 6, 3)),
}


def _conv_init(key, kernel, in_ch, out_ch, dtype):
    fan_in = kernel * kernel * in_ch
    scale = math.sqrt(2.0 / fan_in)
    return (jax.random.normal(key, (kernel, kernel, in_ch, out_ch)) *
            scale).astype(dtype)


def _bn_init(ch, dtype):
    # inference-mode affine (scale/bias with folded running stats)
    return {"scale": jnp.ones((ch,), dtype), "bias": jnp.zeros((ch,),
                                                               dtype)}


def _conv(w, x, stride=1, padding="SAME"):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32).astype(x.dtype)


def _bn(params, x):
    return x * params["scale"] + params["bias"]


def _basic_block_init(key, in_ch, out_ch, dtype):
    keys = jax.random.split(key, 3)
    params = {
        "conv1": _conv_init(keys[0], 3, in_ch, out_ch, dtype),
        "bn1": _bn_init(out_ch, dtype),
        "conv2": _conv_init(keys[1], 3, out_ch, out_ch, dtype),
        "bn2": _bn_init(out_ch, dtype),
    }
    if in_ch != out_ch:
        params["proj"] = _conv_init(keys[2], 1, in_ch, out_ch, dtype)
        params["bn_proj"] = _bn_init(out_ch, dtype)
    return params


def _basic_block(params, x, stride):
    residual = x
    y = jax.nn.relu(_bn(params["bn1"], _conv(params["conv1"], x, stride)))
    y = _bn(params["bn2"], _conv(params["conv2"], y))
    if "proj" in params:
        residual = _bn(params["bn_proj"],
                       _conv(params["proj"], x, stride))
    return jax.nn.relu(y + residual)


def resnet_init(key, config: ResNetConfig):
    dtype = config.dtype
    keys = jax.random.split(key, 2 + sum(config.stage_sizes))
    k_iter = iter(keys)
    params = {
        "stem": _conv_init(next(k_iter), 7, 3, config.width, dtype),
        "bn_stem": _bn_init(config.width, dtype),
        "stages": [],
    }
    in_ch = config.width
    for stage, blocks in enumerate(config.stage_sizes):
        out_ch = config.width * (2 ** stage)
        stage_params = []
        for _ in range(blocks):
            stage_params.append(
                _basic_block_init(next(k_iter), in_ch, out_ch, dtype))
            in_ch = out_ch
        params["stages"].append(stage_params)
    params["head"] = {
        "w": (jax.random.normal(next(k_iter),
                                (in_ch, config.num_classes)) *
              (1.0 / math.sqrt(in_ch))).astype(dtype),
        "b": jnp.zeros((config.num_classes,), dtype),
    }
    return params


def _block_axes(params):
    axes = {"conv1": (None, None, None, "channels"),
            "bn1": {"scale": ("channels",), "bias": ("channels",)},
            "conv2": (None, None, None, "channels"),
            "bn2": {"scale": ("channels",), "bias": ("channels",)}}
    if "proj" in params:
        axes["proj"] = (None, None, None, "channels")
        axes["bn_proj"] = {"scale": ("channels",), "bias": ("channels",)}
    return axes


def resnet_axes(params):
    return {
        "stem": (None, None, None, "channels"),
        "bn_stem": {"scale": ("channels",), "bias": ("channels",)},
        "stages": [[_block_axes(b) for b in stage]
                   for stage in params["stages"]],
        "head": {"w": ("channels", "vocab"), "b": ("vocab",)},
    }


def resnet_features(params, images):
    """Backbone feature extractor: images [B, H, W, 3] → feature map at
    the final stage's stride (shared by the classifier head here and the
    detector in models/detector.py)."""
    x = images
    x = jax.nn.relu(_bn(params["bn_stem"], _conv(params["stem"], x, 2)))
    x = jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for stage, stage_params in enumerate(params["stages"]):
        for i, block in enumerate(stage_params):
            stride = 2 if (stage > 0 and i == 0) else 1
            x = _basic_block(block, x, stride)
    return x


def resnet_forward(params, config: ResNetConfig, images):
    """images: [B, H, W, 3] → logits [B, num_classes]."""
    x = resnet_features(params, images.astype(config.dtype))
    x = jnp.mean(x, axis=(1, 2))                       # global avg pool
    logits = x.astype(jnp.float32) @ params["head"]["w"].astype(
        jnp.float32) + params["head"]["b"]
    return logits
