# Mixture-of-experts FFN with expert parallelism.
#
# Fills the EP row of SURVEY.md §2's parallelism obligations (the
# reference has none).  Design: top-k token routing with a static
# capacity factor — dispatch/combine are one-hot einsums, so the whole
# layer is three big matmuls plus two scatter-free einsums (XLA-friendly:
# no dynamic shapes, no sorting loops on device).  Expert weights carry
# the "expert" logical axis, so shard_pytree places them over the expert
# mesh axis and XLA turns dispatch/combine into all_to_alls over ICI.

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["MoeConfig", "moe_init", "moe_axes", "moe_forward"]


@dataclass(frozen=True)
class MoeConfig:
    dim: int = 64
    ffn_dim: int = 128
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: object = jnp.float32


def moe_init(key, config: MoeConfig):
    keys = jax.random.split(key, 3)
    e, d, f = config.num_experts, config.dim, config.ffn_dim
    scale_in = 1.0 / math.sqrt(d)
    scale_out = 1.0 / math.sqrt(f)
    return {
        "router": L.linear_init(keys[0], d, e, bias=False,
                                dtype=config.dtype),
        "w_in": (jax.random.normal(keys[1], (e, d, f)) *
                 scale_in).astype(config.dtype),
        "w_out": (jax.random.normal(keys[2], (e, f, d)) *
                  scale_out).astype(config.dtype),
    }


def moe_axes():
    return {
        "router": L.linear_axes("embed", None, bias=False),
        "w_in": ("expert", "embed", "ffn"),
        "w_out": ("expert", "ffn", "embed"),
    }


def moe_forward(params, config: MoeConfig, x):
    """x: [B, S, D] → (y: [B, S, D], aux_loss: scalar).

    Top-k routing with capacity C per expert; overflowing tokens are
    dropped from that expert (their residual path still carries them).
    aux_loss is the standard load-balancing term (mean_prob ×
    fraction_routed per expert)."""
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    n = b * s
    e = config.num_experts
    capacity = max(1, int(config.capacity_factor * n * config.top_k / e))

    router_logits = L.linear(params["router"],
                             tokens.astype(jnp.float32))     # [N, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_probs, top_experts = jax.lax.top_k(probs, config.top_k)  # [N, K]

    # position of each token in its expert's queue (per k-slot):
    # cumulative count of earlier tokens assigned to the same expert
    one_hot = jax.nn.one_hot(top_experts, e, dtype=jnp.int32)  # [N, K, E]
    flat_assign = one_hot.reshape(n * config.top_k, e)
    position = jnp.cumsum(flat_assign, axis=0) - flat_assign   # [N*K, E]
    position = (position.reshape(n, config.top_k, e) *
                one_hot).sum(-1)                               # [N, K]
    keep = position < capacity

    # dispatch tensor: [N, K, E, C] one-hot of (expert, slot)
    slot_hot = jax.nn.one_hot(position, capacity,
                              dtype=tokens.dtype)              # [N, K, C]
    dispatch = (one_hot.astype(tokens.dtype)[..., None] *
                slot_hot[..., None, :] *
                keep[..., None, None].astype(tokens.dtype))    # [N,K,E,C]
    combine = dispatch * top_probs[..., None, None].astype(tokens.dtype)

    # route → expert batches [E, C, D]
    expert_in = jnp.einsum("nkec,nd->ecd", dispatch, tokens,
                           preferred_element_type=jnp.float32
                           ).astype(tokens.dtype)
    hidden = jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"],
                        preferred_element_type=jnp.float32)
    hidden = jax.nn.gelu(hidden).astype(tokens.dtype)
    expert_out = jnp.einsum("ecf,efd->ecd", hidden, params["w_out"],
                            preferred_element_type=jnp.float32
                            ).astype(tokens.dtype)
    y = jnp.einsum("nkec,ecd->nd", combine, expert_out,
                   preferred_element_type=jnp.float32).astype(tokens.dtype)

    # load-balancing auxiliary loss (Switch-style): fraction of tokens
    # whose top-1 choice actually landed in each expert × mean router
    # probability per expert, both [E].
    routed_fraction = jnp.mean(
        (one_hot[:, 0] * keep[:, 0:1]).astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = e * jnp.sum(routed_fraction * mean_prob)
    return y.reshape(b, s, d), aux_loss
