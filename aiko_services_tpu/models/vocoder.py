# Tiny neural vocoder: log-mel frames → waveform through a learned
# upsampling conv stack, replacing Griffin-Lim phase recovery when a
# trained head is available (Griffin-Lim stays the weight-free
# fallback in models/tts.py).
#
# Capability target: the reference's TTS leg is Coqui VITS — a NEURAL
# vocoder — on the host (reference: examples/speech/
# speech_elements.py:96-131); Griffin-Lim capped the repo's perceptual
# quality (round-4 verdict item 8).  TPU-first shape: nearest-neighbor
# upsample (jnp.repeat, a free reshape under XLA) followed by a plain
# conv1d per stage — every op is a static-shape matmul on the MXU, no
# transposed-conv checkerboard artifacts, one compile per mel
# geometry.  The stage factors multiply to exactly the analysis hop
# (WHISPER_HOP = 160), so T mel frames emit T*160 samples aligned with
# log_mel_spectrogram's framing.

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["VocoderConfig", "VOCODER_PRESETS", "vocoder_init",
           "vocoder_axes", "vocoder_forward"]


@dataclass(frozen=True)
class VocoderConfig:
    n_mels: int = 80
    hop: int = 160                    # product of upsample factors
    channels: tuple = (128, 64, 32)   # per-stage output channels
    upsample: tuple = (4, 5, 8)       # per-stage time expansion
    kernel: int = 9                   # odd: conv1d symmetric padding
    # oscillator source bank: sin/cos pairs at mel-spaced frequencies,
    # concatenated at the sample-rate stage.  A small conv stack cannot
    # synthesize periodicity from slowly-varying mel features alone
    # (measured: mel-loss plateau ~0.07 without a source); gating a
    # fixed bank is the classic source-filter escape (NSF-style) and
    # keeps the head tiny.
    basis: int = 48
    basis_fmin: float = 60.0
    basis_fmax: float = 4000.0
    sample_rate: int = 16000
    dtype: object = jnp.float32

    def __post_init__(self):
        product = math.prod(self.upsample)
        if product != self.hop:
            raise ValueError(f"upsample factors {self.upsample} "
                             f"multiply to {product}, need hop={self.hop}")
        if len(self.channels) != len(self.upsample):
            raise ValueError("need one channel width per upsample stage")


VOCODER_PRESETS = {
    # matches the test/base TTS presets' 80-mel output.  The "test"
    # geometry is the measured sweet spot on the synthetic corpus:
    # half-size channels plateaued (MCD 30.9) and double-size overfit
    # (29.3) — and the r5 data-scaling experiment
    # (tools/train_vocoder_scale.py) CONFIRMED data was the binding
    # constraint: widening the corpus 8 → 29 utterances at this same
    # geometry cut held-out MCD 23.88 → 21.10 dB, past
    # Griffin-Lim-32's 22.72, while larger geometries still overfit
    # (26.8 / 28.8).
    "test": VocoderConfig(channels=(96, 48, 24), basis=64),
    "base": VocoderConfig(),
}


def _mel_spaced_frequencies(num: int, fmin: float, fmax: float):
    """`num` frequencies equally spaced on the mel scale — dense where
    the mel filterbank is dense, so each oscillator's energy lands in
    the right analysis bin."""
    def to_mel(f):
        return 2595.0 * math.log10(1.0 + f / 700.0)

    def from_mel(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    lo, hi = to_mel(fmin), to_mel(fmax)
    return jnp.asarray([from_mel(lo + (hi - lo) * i / (num - 1))
                        for i in range(num)])


def oscillator_bank(length: int, config: VocoderConfig, freqs):
    """[length, 2*basis] sin/cos features at `freqs` (Hz) of the
    absolute sample index — a linear combination reproduces any phase,
    so frame-aligned tone onsets fit without phase tracking.  The
    frequencies are TRAINABLE (params["freqs"], init mel-spaced):
    gradient through sin(2π f t) lets the bank lock onto the corpus's
    actual partials instead of leaving a half-bin detune error."""
    t = jnp.arange(length, dtype=jnp.float32)[:, None]
    angles = 2.0 * math.pi * t * freqs[None, :] / config.sample_rate
    return jnp.concatenate([jnp.sin(angles), jnp.cos(angles)],
                           axis=-1).astype(config.dtype)


def vocoder_init(key, config: VocoderConfig):
    keys = jax.random.split(key, len(config.channels) + 4)
    widths = (config.n_mels,) + tuple(config.channels)
    return {
        "pre": L.conv1d_init(keys[0], config.n_mels, widths[1],
                             config.kernel, config.dtype),
        "stages": [L.conv1d_init(keys[i + 1], widths[i + 1],
                                 widths[i + 2] if i + 2 < len(widths)
                                 else widths[i + 1],
                                 config.kernel, config.dtype)
                   for i in range(len(config.upsample) - 1)],
        # per-sample oscillator gates (multiplicative: a purely linear
        # combination of a fixed bank could only emit one global tone)
        "gate": L.conv1d_init(keys[-3], config.channels[-1],
                              2 * config.basis, config.kernel,
                              config.dtype),
        # per-frame log-gain on the mel grid: silence must reach
        # ACTUAL zero — the log-mel analysis floor makes residual
        # conv noise in silent regions dominate MCD otherwise
        "gain": L.conv1d_init(keys[-2], config.n_mels, 1,
                              config.kernel, config.dtype),
        "post": L.conv1d_init(keys[-1],
                              config.channels[-1] + 2 * config.basis,
                              1, config.kernel, config.dtype),
        "freqs": _mel_spaced_frequencies(config.basis,
                                         config.basis_fmin,
                                         config.basis_fmax),
    }


def vocoder_axes(config: VocoderConfig):
    return {
        "pre": L.conv1d_axes(),
        "stages": [L.conv1d_axes()] * (len(config.upsample) - 1),
        "gate": L.conv1d_axes(),
        "gain": L.conv1d_axes(),
        "post": L.conv1d_axes(),
        "freqs": None,
    }


def vocoder_forward(params, config: VocoderConfig, mel):
    """log-mel [B, T, n_mels] → waveform [B, T*hop] in [-1, 1].

    Stage i: repeat time axis by upsample[i], then conv + leaky-relu;
    the first repeat happens after the pre-conv so the mel-width
    matmul runs at the cheapest time resolution."""
    x = jax.nn.leaky_relu(L.conv1d(params["pre"],
                                   mel.astype(config.dtype)), 0.1)
    x = jnp.repeat(x, config.upsample[0], axis=1)
    for i, stage in enumerate(params["stages"]):
        x = jax.nn.leaky_relu(L.conv1d(stage, x), 0.1)
        x = jnp.repeat(x, config.upsample[i + 1], axis=1)
    source = oscillator_bank(x.shape[1], config, params["freqs"])
    # amplitude-modulate the bank per sample: gates are the learned
    # "filter", the bank is the "source"
    modulated = L.conv1d(params["gate"], x) * source[None]
    x = jnp.concatenate([x, modulated], axis=-1)
    wave = jnp.tanh(L.conv1d(params["post"], x))[..., 0]
    # per-frame exponential gain, upsampled to sample rate: lets the
    # net drive silent frames to true zero (exp(-large)) — additive
    # heads bottom out at conv-noise level, which the log-mel floor
    # then amplifies into the dominant MCD term
    log_gain = L.conv1d(params["gain"],
                        mel.astype(config.dtype))[..., 0]    # [B, T]
    gain = jnp.exp(jnp.repeat(log_gain, config.hop, axis=1))
    return wave * gain
