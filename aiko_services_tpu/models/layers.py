# Shared neural-net building blocks: functional jax, param pytrees, and
# logical sharding axes.
#
# No reference counterpart — the reference wraps external CUDA models
# (WhisperX: examples/speech/speech_elements.py:174-180; its framework code
# contains no model math).  Style: every block is a pair of pure functions
# (init(key, ...) -> params, apply(params, x, ...)) plus an axes() tree of
# logical axis names consumed by parallel.shard_pytree, so any model built
# from these blocks is sharding-annotated by construction.
#
# dtype policy: params live in float32 (or bfloat16 for serving), compute
# runs in the dtype of the activations, matmul accumulation is always
# float32 (preferred_element_type) — the MXU-native recipe.

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "linear_init", "linear", "linear_axes",
    "layer_norm_init", "layer_norm", "layer_norm_axes",
    "rms_norm_init", "rms_norm", "rms_norm_axes",
    "embedding_init", "embedding", "embedding_axes",
    "conv1d_init", "conv1d", "conv1d_axes",
    "mha_init", "mha", "mha_axes", "precompute_kv", "init_kv_cache",
    "update_kv_cache", "quantize_linear", "quantize_linear_tree",
    "quantize_kv_cache", "dequantize_kv_cache",
    "slice_kv_rows", "split_kv_blocks", "concat_kv_rows",
    "kv_rows_nbytes",
    "gather_paged_kv", "scatter_paged_rows", "write_paged_blocks",
    "slice_paged_block",
    "linear_logits",
    "sinusoid_position_encoding", "gelu", "rope_frequencies", "apply_rope",
]


# -- linear ------------------------------------------------------------------

def linear_init(key, in_dim: int, out_dim: int, bias: bool = True,
                dtype=jnp.float32, scale: float | None = None):
    scale = scale if scale is not None else 1.0 / math.sqrt(in_dim)
    params = {"w": (jax.random.normal(key, (in_dim, out_dim)) *
                    scale).astype(dtype)}
    if bias:
        params["b"] = jnp.zeros((out_dim,), dtype)
    return params


def linear(params, x):
    if "w8" in params:
        # weight-only int8 (quantize_linear): the int8->activation-dtype
        # convert is the dot operand (fuses — no materialized copy) and
        # the per-output-channel scale lands exactly on the f32
        # accumulator: y = (x @ W8) * s + b is exact algebra, not an
        # approximation of the dequantized matmul
        y = jnp.einsum("...i,io->...o", x, params["w8"].astype(x.dtype),
                       preferred_element_type=jnp.float32) * params["s"]
    else:
        y = jnp.einsum("...i,io->...o", x, params["w"],
                       preferred_element_type=jnp.float32)
    if "b" in params:
        y = y + params["b"]
    return y.astype(x.dtype)


def linear_axes(in_axis: str, out_axis: str, bias: bool = True):
    axes = {"w": (in_axis, out_axis)}
    if bias:
        axes["b"] = (out_axis,)
    return axes


def linear_logits(params, x):
    """Vocab/classifier projection kept in f32 — no activation-dtype
    downcast, because rounding logits to bf16 before an argmax can
    flip near-ties against an f32 oracle.  Consumes plain {"w"} or
    quantized {"w8", "s"} linears: besides linear(), this is the ONLY
    place the weight-quantized format is interpreted, so format
    changes stay in this module."""
    if "w8" in params:
        logits = jnp.einsum("...d,dv->...v", x,
                            params["w8"].astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return logits * params["s"]
    return jnp.einsum("...d,dv->...v", x, params["w"],
                      preferred_element_type=jnp.float32)


def quantize_linear(params):
    """Weight-only int8 for a linear: one f32 scale per OUTPUT channel
    (max|w| over the input axis), so y = (x @ W8) * s + b reproduces
    the bf16 matmul up to int8 rounding of the weights — activations
    stay full precision (W8A16).

    Measured r5 at the llama 1b/256-slot serving shape
    (tools/ab_w8.py): device step 11.32 → 11.02 ms (−2.6%) and a
    closed-loop wash — the weight-byte halving does NOT buy the ~3 ms
    its share of a bandwidth-bound step would predict, so the step is
    scheduling-bound there (or XLA hoists the converted weights out
    of the decode scan; undiagnosed).  Treat W8 as a MEMORY lever: it
    frees 1.24 GB of the 1b weight set for more KV slots.  Returns
    {"w8": int8 [in,out], "s": f32 [out]} (+"b" passthrough), which
    linear() consumes transparently."""
    w = params["w"]
    scale = (jnp.max(jnp.abs(w), axis=0).astype(jnp.float32) / 127.0
             + 1e-12)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale),
                 -127, 127).astype(jnp.int8)
    out = {"w8": q, "s": scale}
    if "b" in params:
        out["b"] = params["b"]
    return out


def quantize_linear_tree(params, exclude=("router",)):
    """Recursively replace every linear param dict ({"w": 2-D, ["b"]})
    in a pytree with its quantize_linear form.  Leaves everything else
    untouched: conv1d ("w" is 3-D), embeddings ("table"), norms
    (scale/bias), bare arrays.  Keys in `exclude` are skipped whole —
    the default skips MoE routers, where int8 rounding could flip
    top-k expert selection for negligible byte savings."""
    def walk(node):
        if isinstance(node, dict):
            if "w" in node and getattr(node["w"], "ndim", 0) == 2 \
                    and set(node) <= {"w", "b"}:
                return quantize_linear(node)
            return {key: (value if key in exclude else walk(value))
                    for key, value in node.items()}
        if isinstance(node, (list, tuple)):
            return type(node)(walk(value) for value in node)
        return node
    return walk(params)


# -- norms -------------------------------------------------------------------

def layer_norm_init(dim: int, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype), "bias": jnp.zeros((dim,),
                                                                dtype)}


def layer_norm(params, x, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (y * params["scale"] + params["bias"]).astype(x.dtype)


def layer_norm_axes():
    return {"scale": ("embed",), "bias": ("embed",)}


def rms_norm_init(dim: int, dtype=jnp.float32):
    return {"scale": jnp.ones((dim,), dtype)}


def rms_norm(params, x, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                            + eps)
    return (y * params["scale"]).astype(x.dtype)


def rms_norm_axes():
    return {"scale": ("embed",)}


# -- embedding ---------------------------------------------------------------

def embedding_init(key, vocab: int, dim: int, dtype=jnp.float32):
    return {"table": (jax.random.normal(key, (vocab, dim)) *
                      0.02).astype(dtype)}


def embedding(params, token_ids):
    return jnp.take(params["table"], token_ids, axis=0)


def embedding_axes():
    return {"table": ("vocab", "embed")}


# -- conv1d ------------------------------------------------------------------

def conv1d_init(key, in_ch: int, out_ch: int, kernel: int,
                dtype=jnp.float32):
    scale = 1.0 / math.sqrt(in_ch * kernel)
    return {"w": (jax.random.normal(key, (kernel, in_ch, out_ch)) *
                  scale).astype(dtype),
            "b": jnp.zeros((out_ch,), dtype)}


def conv1d(params, x, stride: int = 1, padding=None):
    """x: [B, T, C_in] → [B, T', C_out] (maps onto the MXU as a matmul
    over the unrolled kernel window).

    Default padding is SYMMETRIC (k-1)//2 both sides — torch Conv1d's
    `padding=k//2` convention, which whisper checkpoints are trained
    under.  XLA's "SAME" pads asymmetrically under stride>1 (left 0 /
    right 1 for k=3, s=2), silently shifting every strided frame by one
    sample relative to the checkpoint.  The symmetric default only
    preserves length for ODD kernels; even kernels must pass an
    explicit `padding`."""
    if padding is None:
        k = params["w"].shape[0]
        if k % 2 == 0:
            raise ValueError(
                f"conv1d default padding requires an odd kernel, got "
                f"{k}; pass padding explicitly for even kernels")
        padding = [((k - 1) // 2, (k - 1) // 2)]
    y = jax.lax.conv_general_dilated(
        x, params["w"], window_strides=(stride,), padding=padding,
        dimension_numbers=("NWC", "WIO", "NWC"),
        preferred_element_type=jnp.float32)
    return (y + params["b"]).astype(x.dtype)


def conv1d_axes():
    return {"w": (None, None, "embed"), "b": ("embed",)}


# -- attention ---------------------------------------------------------------

def mha_init(key, dim: int, num_heads: int, num_kv_heads: int | None = None,
             bias: bool = True, dtype=jnp.float32):
    """Multi-head attention params.  num_kv_heads < num_heads = GQA."""
    num_kv_heads = num_kv_heads or num_heads
    head_dim = dim // num_heads
    keys = jax.random.split(key, 4)
    return {
        "q": linear_init(keys[0], dim, num_heads * head_dim, bias, dtype),
        "k": linear_init(keys[1], dim, num_kv_heads * head_dim, False,
                         dtype),
        "v": linear_init(keys[2], dim, num_kv_heads * head_dim, bias,
                         dtype),
        "o": linear_init(keys[3], num_heads * head_dim, dim, bias, dtype),
    }


def mha_axes(bias: bool = True):
    return {
        "q": linear_axes("embed", "heads", bias),
        "k": linear_axes("embed", "kv_heads", False),
        "v": linear_axes("embed", "kv_heads", bias),
        "o": linear_axes("heads", "embed", bias),
    }


def _split_heads(x, num_heads):
    b, t, _ = x.shape
    return x.reshape(b, t, num_heads, -1).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, t, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * d)


def init_kv_cache(batch: int, max_len: int, num_kv_heads: int,
                  head_dim: int, dtype=jnp.float32):
    """Static-shape KV cache: [B, H_kv, T_max, D] + write index."""
    shape = (batch, num_kv_heads, max_len, head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "index": jnp.zeros((), jnp.int32)}


def update_kv_cache(cache, k_new, v_new):
    """Write new K/V at the cache cursor (static shapes; donation-friendly
    under jit so decode steps update in place on TPU)."""
    index = cache["index"]
    k = jax.lax.dynamic_update_slice_in_dim(cache["k"], k_new, index,
                                            axis=2)
    v = jax.lax.dynamic_update_slice_in_dim(cache["v"], v_new, index,
                                            axis=2)
    return {"k": k, "v": v, "index": index + k_new.shape[2]}


def precompute_kv(params, kv_input, num_kv_heads: int):
    """Project K/V once for reuse across many queries (e.g. encoder output
    attended by every decode step).  Returns (k, v): [B, H_kv, T, D]."""
    k = _split_heads(linear(params["k"], kv_input), num_kv_heads)
    v = _split_heads(linear(params["v"], kv_input), num_kv_heads)
    return k, v


def quantize_kv(tensor, mode: str = "position"):
    """Symmetric int8 quantization of a K or V tensor [..., T, D].
    Halves the HBM footprint of a precomputed KV cache — and, in
    "tensor" mode, halves the decode tail's dominant read.

    mode="position": scale over the last axis (per-position, bf16
    scales).  Finer-grained, but the dequant is a broadcast MULTIPLY —
    measured in-program, XLA re-materializes the dequantized bf16 KV
    every scan step and throughput LOSES ~24%.  Memory lever only.

    mode="tensor": ONE f32 scale per leading-axis element (per batch
    item for a [B, H, T, D] cache — NOT one global scalar: a single
    loud co-batched stream would coarsen every other stream's
    quantization and make transcripts depend on batch composition).
    The scale is constant along the head/position/feature axes, so
    the dequant is a bare int8→bf16 convert as the dot operand (mha
    folds the scale into the softmax scale / output as a per-batch
    broadcast), which XLA fuses instead of materializing — measured
    r5 at the whisper decode shape: 38% faster per step than the
    bf16 read in isolation (tools/diag_attn_patterns.py: 1334 vs
    2156 us/rep), −14% whole-round in the fused program (a global
    scalar measured −17% but couples co-batched streams).  Coarser
    scale than "position", so slightly larger error.

    Returns {"q": int8, "s": scale} — dequantize_kv handles both
    (the scale broadcasts)."""
    if mode == "tensor":
        axes = tuple(range(1, tensor.ndim))
        scale = (jnp.max(jnp.abs(tensor), axis=axes, keepdims=True)
                 .astype(jnp.float32) / 127.0 + 1e-12)
        q = jnp.clip(jnp.round(tensor.astype(jnp.float32) / scale),
                     -127, 127).astype(jnp.int8)
        return {"q": q, "s": scale}
    if mode != "position":
        raise ValueError(f"unknown quantize_kv mode {mode!r}")
    scale = (jnp.max(jnp.abs(tensor), axis=-1, keepdims=True)
             .astype(jnp.float32) / 127.0 + 1e-12).astype(jnp.bfloat16)
    q = jnp.clip(jnp.round(tensor.astype(jnp.float32) /
                           scale.astype(jnp.float32)),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize_kv(kv, dtype):
    """Inverse of quantize_kv; passes plain arrays through."""
    if isinstance(kv, dict) and "q" in kv:
        return (kv["q"].astype(dtype) * kv["s"].astype(dtype))
    return kv


def quantize_kv_cache(tensor):
    """Symmetric int8 for the SERVING KV cache (continuous batching):
    one f32 scale per (..., position) — for a [S, H, T, D] slot cache
    that is per (slot, head, position), the finest grain whose dequant
    still FOLDS instead of materializing.  Unlike quantize_kv's
    "position" mode (a [..., T, 1] broadcast-multiply the decode scan
    re-materializes every step, measured −24%), this scale's shape
    [..., T] is consumed by serving's decode attention as a fold along
    the score/weight time axis: scores·s_k on the QK pass and
    weights·s_v before the PV pass — exact algebra, so the int8 buffer
    stays the dot operand (the convert fuses) and the cache read is
    halved, which is the HBM-bound decode step's dominant byte.

    Returns {"q": int8 [..., T, D], "s": f32 [..., T]}."""
    scale = (jnp.max(jnp.abs(tensor), axis=-1).astype(jnp.float32)
             / 127.0 + 1e-12)
    q = jnp.clip(jnp.round(tensor.astype(jnp.float32) /
                           scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return {"q": q, "s": scale}


def dequantize_kv_cache(kv, dtype):
    """Inverse of quantize_kv_cache; passes plain arrays through.  The
    materializing path — serving's prefill-extend uses it OFF the
    decode critical path; the decode scan folds instead."""
    if isinstance(kv, dict) and "q" in kv:
        return kv["q"].astype(dtype) * kv["s"][..., None].astype(dtype)
    return kv


def slice_kv_rows(cache, slot, start: int, stop: int):
    """One slot's K/V rows [start, stop) from a SERVING slot cache leaf
    — a plain [S, H, T, D] array or the int8 serving form
    {"q" int8 [S, H, T, D], "s" f32 [S, H, T]} (quantize_kv_cache).
    Returns [H, t, D] (or the dict with s [H, t]) as a device-side
    slice COPY: the harvest read behind serving's prefix/KV reuse
    cache.  Slicing the quantized form keeps q and s together, so a
    cached block stores exactly the bytes decode would read — a later
    hit is a bytes win AND bit-faithful to the donor's cache."""
    if isinstance(cache, dict):
        return {"q": cache["q"][slot, :, start:stop],
                "s": cache["s"][slot, :, start:stop]}
    return cache[slot, :, start:stop]


def split_kv_blocks(rows, block_tokens: int):
    """Split harvested rows [H, n*B, D] (or the quantized dict form)
    into n per-block leaves [H, B, D] along the time axis — the unit
    the prefix cache stores and hash-addresses."""
    if isinstance(rows, dict):
        count = rows["q"].shape[1] // block_tokens
        return [{"q": rows["q"][:, i * block_tokens:
                                (i + 1) * block_tokens],
                 "s": rows["s"][:, i * block_tokens:
                                (i + 1) * block_tokens]}
                for i in range(count)]
    count = rows.shape[1] // block_tokens
    return [rows[:, i * block_tokens:(i + 1) * block_tokens]
            for i in range(count)]


def concat_kv_rows(blocks):
    """Concatenate per-block K/V leaves back into contiguous rows along
    the time axis (inverse of split_kv_blocks) — the copy-in side of a
    prefix-cache hit.  Handles the quantized dict form leaf-wise so an
    int8 chain lands in the slot cache without a dequantize/requantize
    round trip (no double rounding)."""
    if isinstance(blocks[0], dict):
        return {"q": jnp.concatenate([b["q"] for b in blocks], axis=1),
                "s": jnp.concatenate([b["s"] for b in blocks], axis=1)}
    return jnp.concatenate(blocks, axis=1)


def kv_rows_nbytes(rows) -> int:
    """Accounting bytes of one K or V rows leaf (array or quantized
    dict) — the prefix cache's budget currency."""
    return int(sum(leaf.size * jnp.dtype(leaf.dtype).itemsize
                   for leaf in jax.tree_util.tree_leaves(rows)))


# -- paged KV block pool primitives (ISSUE 15) -------------------------------
# The paged serving cache (serving_paged.BlockPool) stores KV in one
# [N, H, B, D] pool of fixed B-token blocks per layer (int8 pools carry
# the {"q" i8 [N, H, B, D], "s" f32 [N, H, B]} serving form), addressed
# by per-slot int32 block tables.  These primitives are the whole
# device-side vocabulary of the paged path: a gather that materializes
# a slot-major [S, H, T, D] view for the attention einsums (the one
# place paged and dense numerics must agree BIT-for-bit — the gathered
# view is value-identical to the dense slot cache, so every attention
# body downstream is shared, not forked), a per-position scatter for
# the decode round's side-buffer merge, a whole-block scatter for the
# admit prefill, a block slice read for harvest-free wire shipping,
# and a plane split for the pallas paged-attention kernel (ISSUE 16),
# which reads pool blocks straight through the table and demotes the
# gather to the bit-parity oracle role.  Out-of-range destination ids
# drop (mode="drop") — the paged analogue of the dense path's
# _POS_INVALID discipline.

def gather_paged_kv(pool, tables):
    """Assemble a slot-major KV view from a block pool: `tables` is
    [S, nb] int32 block ids; returns [S, H, nb*B, D] (or the int8 dict
    with s [S, H, nb*B]).  Position p of slot s reads
    pool[tables[s, p // B], :, p % B] — the block-table indirection of
    vLLM's PagedAttention, expressed as an XLA gather.  The gather
    materializes once per compiled program (hoisted out of the decode
    scan: the main cache is read-only through a round), so the scan's
    per-step HBM traffic is identical to the dense cache's."""
    if isinstance(pool, dict):
        return {"q": gather_paged_kv(pool["q"], tables),
                "s": gather_paged_kv(pool["s"], tables)}
    g = jnp.take(pool, tables, axis=0)     # [S, nb, H, B, ...]
    if g.ndim == 5:                        # values [S, nb, H, B, D]
        s, nb, h, b, d = g.shape
        return g.transpose(0, 2, 1, 3, 4).reshape(s, h, nb * b, d)
    s, nb, h, b = g.shape                  # scales [S, nb, H, B]
    return g.transpose(0, 2, 1, 3).reshape(s, h, nb * b)


def paged_pool_planes(pool):
    """(value plane, scale plane or None) for one paged-pool leaf —
    the int8 serving dict splits into its i8 values [N, H, B, D] and
    f32 per-position scales [N, H, B] (separate DMA operands for the
    pallas paged-attention kernel); native pools carry no scale.  The
    pool-grain sibling of serving._kv_planes, kept here so the int8
    pool layout is decoded in exactly one module."""
    if isinstance(pool, dict):
        return pool["q"], pool["s"]
    return pool, None


def scatter_paged_rows(pool, dest_blocks, offsets, rows):
    """Scatter per-position rows into pool blocks: rows is
    [S, H, W, D] (or the scale form [S, H, W]); dest_blocks/offsets are
    [S, W] — row (s, w) lands at pool[dest_blocks[s, w], :,
    offsets[s, w]].  Out-of-range dest ids DROP (inactive slots,
    rejected speculative drafts, positions past the table) instead of
    clamping into a live block."""
    if isinstance(pool, dict):
        return {"q": scatter_paged_rows(pool["q"], dest_blocks,
                                        offsets, rows["q"]),
                "s": scatter_paged_rows(pool["s"], dest_blocks,
                                        offsets, rows["s"])}
    if rows.ndim == 4:                     # values [S, H, W, D]
        vals = rows.transpose(0, 2, 1, 3)  # [S, W, H, D]
    else:                                  # scales [S, H, W]
        vals = rows.transpose(0, 2, 1)     # [S, W, H]
    return pool.at[dest_blocks, :, offsets].set(vals, mode="drop")


def write_paged_blocks(pool, block_ids, rows):
    """Whole-block scatter for the admit prefill: rows is
    [A, H, nb*B, D] (or scales [A, H, nb*B]) covering nb =
    block_ids.shape[1] complete blocks per admit row; each block lands
    at pool[block_ids[a, j]].  Invalid rows carry out-of-range ids and
    drop."""
    if isinstance(pool, dict):
        return {"q": write_paged_blocks(pool["q"], block_ids,
                                        rows["q"]),
                "s": write_paged_blocks(pool["s"], block_ids,
                                        rows["s"])}
    nb = block_ids.shape[1]
    if rows.ndim == 4:
        a, h, t, d = rows.shape
        vals = rows.reshape(a, h, nb, t // nb, d).transpose(0, 2, 1, 3,
                                                            4)
    else:
        a, h, t = rows.shape
        vals = rows.reshape(a, h, nb, t // nb).transpose(0, 2, 1, 3)
    return pool.at[block_ids].set(vals, mode="drop")


def slice_paged_block(pool, block_id: int):
    """One block's rows [H, B, D] (or the int8 dict) from the pool —
    the read behind shipping a pool-resident cache block over the
    disaggregated wire.  A device-side slice view; np.asarray at the
    call site makes the host copy."""
    if isinstance(pool, dict):
        return {"q": pool["q"][block_id], "s": pool["s"][block_id]}
    return pool[block_id]


def mha(params, x, kv_input=None, mask=None, cache=None,
        num_heads: int = 8, num_kv_heads: int | None = None,
        qk_transform=None, precomputed_kv=None, fused: bool = True):
    """Attention: self (kv_input None), cross (kv_input or precomputed_kv),
    optional KV cache.

    mask: broadcastable to [B, H, Tq, Tk], True = attend.
    qk_transform(q, k) -> (q, k): applied after head split, before the
    cache write (RoPE hook — cached keys are stored already-positioned).
    precomputed_kv: (k, v) already projected+split (cross-attention cache).
    Returns (output, new_cache)."""
    num_kv_heads = num_kv_heads or num_heads
    q = _split_heads(linear(params["q"], x), num_heads)
    # mode="tensor"-quantized KV: keep the int8 buffer as the dot
    # operand (a bare convert fuses; a per-POSITION scale multiply
    # materializes a bf16 copy per decode step — measured −24%) and
    # fold the per-batch scales into the score scale / output.  A
    # scale qualifies for folding iff it is constant along every axis
    # but the batch one (scalar, or [B,1,...,1]).
    def _foldable(s):
        return jnp.ndim(s) == 0 or all(d == 1 for d in s.shape[1:])

    k_scale = v_scale = None
    if precomputed_kv is not None:
        k, v = precomputed_kv
        # fold only when BOTH k and v are quantized dicts with foldable
        # scales — a mixed pair (or a per-position v scale) must take
        # the dequantize path, not crash or mis-scale (ADVICE r5)
        if isinstance(k, dict) and isinstance(v, dict) and \
                _foldable(k["s"]) and _foldable(v["s"]):
            # scale shapes [B,1,1,1] broadcast against scores
            # [B,H,Tq,Tk] and output [B,H,Tq,D] directly
            k_scale, v_scale = k["s"], v["s"]
            k, v = k["q"].astype(x.dtype), v["q"].astype(x.dtype)
        else:
            k = dequantize_kv(k, x.dtype)
            v = dequantize_kv(v, x.dtype)
    else:
        k, v = precompute_kv(params, x if kv_input is None else kv_input,
                             num_kv_heads)
    if qk_transform is not None:
        q, k = qk_transform(q, k)

    if cache is not None:
        cache = update_kv_cache(cache, k, v)
        k, v = cache["k"], cache["v"]
        # valid-position mask for the unwritten cache tail
        valid = (jnp.arange(k.shape[2]) < cache["index"])[None, None, None]
        mask = valid if mask is None else (mask & valid)

    if num_kv_heads != num_heads:                  # GQA: repeat KV groups
        repeat = num_heads // num_kv_heads
        k = jnp.repeat(k, repeat, axis=1)
        v = jnp.repeat(v, repeat, axis=1)

    if fused and mask is None and cache is None and k_scale is None \
            and q.shape[2] == k.shape[2]:
        # mask-free self/cross attention: fused flash path (pallas on TPU
        # when shapes tile, XLA otherwise)
        from ..ops.attention import attention
        out = attention(q, k, v)
        return linear(params["o"], _merge_heads(out)), cache

    scale = 1.0 / math.sqrt(q.shape[-1])
    if k_scale is not None:
        scale = scale * k_scale
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", weights, v,
                     preferred_element_type=jnp.float32)
    if v_scale is not None:
        out = out * v_scale
    out = out.astype(x.dtype)
    return linear(params["o"], _merge_heads(out)), cache


# -- positional encodings ----------------------------------------------------

def sinusoid_position_encoding(length: int, dim: int,
                               max_timescale: float = 10000.0):
    """Whisper-style sinusoids: [length, dim]."""
    half = dim // 2
    log_increment = math.log(max_timescale) / max(half - 1, 1)
    inv_timescales = jnp.exp(-log_increment * jnp.arange(half))
    scaled = jnp.arange(length)[:, None] * inv_timescales[None, :]
    return jnp.concatenate([jnp.sin(scaled), jnp.cos(scaled)], axis=1)


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0):
    """RoPE cos/sin tables: each [max_len, head_dim//2]."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2) / head_dim))
    angles = jnp.arange(max_len)[:, None] * inv[None, :]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, position_offset=0):
    """x: [B, H, T, D]; rotates pairs (even, odd) by position angle.

    position_offset: scalar (shared), or [B] vector — per-example
    offsets for continuous batching, where each slot sits at its own
    sequence position."""
    t = x.shape[2]
    offset = jnp.asarray(position_offset)
    if offset.ndim == 0:
        positions = offset + jnp.arange(t)                   # [T]
        cos_t = jnp.take(cos, positions, axis=0)[None, None]  # [1,1,T,D/2]
        sin_t = jnp.take(sin, positions, axis=0)[None, None]
    else:
        positions = offset[:, None] + jnp.arange(t)[None]    # [B, T]
        cos_t = jnp.take(cos, positions, axis=0)[:, None]    # [B,1,T,D/2]
        sin_t = jnp.take(sin, positions, axis=0)[:, None]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rotated = jnp.stack([x1 * cos_t - x2 * sin_t,
                         x1 * sin_t + x2 * cos_t], axis=-1)
    return rotated.reshape(x.shape).astype(x.dtype)


def gelu(x):
    # exact (erf) gelu: what whisper/HF "gelu" checkpoints are trained
    # under — the tanh approximation drifts logits by ~5e-3, enough to
    # flip near-tie argmax decodes on real weights
    return jax.nn.gelu(x, approximate=False)
