# Object detector: anchor-free center-point detection, TPU-native.
#
# Parity target: BASELINE.md config 4 ("gstreamer video → YOLOv8 detect →
# tracker") — the reference names YOLO but ships no detector (SURVEY.md
# §2).  Architecture: ResNet backbone → upsampled feature map → three
# conv heads (class heatmap, box size, center offset), CenterNet-style.
# Chosen over anchor-box designs because decode is pure tensor ops
# (3×3 max-pool peak detection + top-k) — no NMS loops, no dynamic
# shapes, everything jits onto the MXU/VPU.

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from .resnet import (
    ResNetConfig, _bn, _bn_init, _conv, _conv_init, resnet_axes,
    resnet_features, resnet_init)

__all__ = ["DetectorConfig", "detector_init", "detector_axes",
           "detector_forward", "detect", "DETECTOR_PRESETS"]


@dataclass(frozen=True)
class DetectorConfig:
    num_classes: int = 80
    backbone: ResNetConfig = ResNetConfig(stage_sizes=(2, 2, 2, 2),
                                          num_classes=1)
    head_channels: int = 64
    max_detections: int = 32
    dtype: object = jnp.float32


DETECTOR_PRESETS = {
    "detector_r18": DetectorConfig(),
    # CI/smoke geometry
    "detector_test": DetectorConfig(
        num_classes=4,
        backbone=ResNetConfig(stage_sizes=(1, 1), num_classes=1, width=8),
        head_channels=8, max_detections=8),
}


def detector_init(key, config: DetectorConfig):
    keys = jax.random.split(key, 6)
    dtype = config.dtype
    backbone = resnet_init(keys[0], config.backbone)
    backbone.pop("head")                # classification head unused
    feature_ch = config.backbone.width * \
        (2 ** (len(config.backbone.stage_sizes) - 1))
    ch = config.head_channels
    return {
        "backbone": backbone,
        "neck": _conv_init(keys[1], 3, feature_ch, ch, dtype),
        "bn_neck": _bn_init(ch, dtype),
        "head_heat": _conv_init(keys[2], 3, ch, config.num_classes,
                                dtype),
        "head_size": _conv_init(keys[3], 3, ch, 2, dtype),
        "head_offset": _conv_init(keys[4], 3, ch, 2, dtype),
    }


def detector_axes(params):
    backbone_axes = resnet_axes(
        {**params["backbone"], "head": {"w": None, "b": None}})
    backbone_axes.pop("head")
    return {
        "backbone": backbone_axes,
        "neck": (None, None, None, "channels"),
        "bn_neck": {"scale": ("channels",), "bias": ("channels",)},
        "head_heat": (None, None, None, None),
        "head_size": (None, None, None, None),
        "head_offset": (None, None, None, None),
    }


def detector_forward(params, config: DetectorConfig, images):
    """images [B, H, W, 3] → (heatmap [B, h, w, C] logits,
    sizes [B, h, w, 2], offsets [B, h, w, 2]) at backbone stride."""
    x = images.astype(config.dtype)
    features = resnet_features(params["backbone"], x)
    neck = jax.nn.relu(_bn(params["bn_neck"],
                           _conv(params["neck"], features)))
    heatmap = _conv(params["head_heat"], neck)
    sizes = jax.nn.softplus(_conv(params["head_size"], neck))
    offsets = _conv(params["head_offset"], neck)
    return heatmap, sizes, offsets


def detect(params, config: DetectorConfig, images,
           score_threshold: float = 0.3):
    """Full detection: forward + peak decode.  Returns
    (boxes [B, K, 4] in input pixels (x1,y1,x2,y2), scores [B, K],
    classes [B, K]) with K = config.max_detections, zero-padded —
    static shapes throughout (one compilation per image size)."""
    heatmap, sizes, offsets = detector_forward(params, config, images)
    b, h, w, c = heatmap.shape
    stride = images.shape[1] // h
    scores_map = jax.nn.sigmoid(heatmap.astype(jnp.float32))

    # peaks: a cell survives when it equals its 3x3 neighbourhood max
    pooled = jax.lax.reduce_window(
        scores_map, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 1, 1, 1),
        "SAME")
    peaks = jnp.where(scores_map == pooled, scores_map, 0.0)

    flat = peaks.reshape(b, h * w * c)
    k = min(config.max_detections, h * w * c)
    top_scores, top_idx = jax.lax.top_k(flat, k)
    cell = top_idx // c
    classes = top_idx % c
    ys = (cell // w).astype(jnp.float32)
    xs = (cell % w).astype(jnp.float32)

    def gather_hw(grid):
        # f32 regression regardless of backbone dtype: bf16 box coords
        # at 256-pixel scale quantize to whole pixels
        flat_grid = grid.astype(jnp.float32).reshape(b, h * w,
                                                     grid.shape[-1])
        return jnp.take_along_axis(flat_grid, cell[..., None], axis=1)

    size = gather_hw(sizes)                          # [B, K, 2] in cells
    offset = jnp.tanh(gather_hw(offsets))            # [-1,1] cell units

    cx = (xs + 0.5 + offset[..., 0]) * stride
    cy = (ys + 0.5 + offset[..., 1]) * stride
    half_w = size[..., 0] * stride * 0.5
    half_h = size[..., 1] * stride * 0.5
    boxes = jnp.stack([cx - half_w, cy - half_h,
                       cx + half_w, cy + half_h], axis=-1)
    # suppressed cells carry exactly 0.0 after the peak mask: require a
    # strictly positive score so padding rows honour the zero-padded
    # contract even at threshold <= 0
    keep = (top_scores >= score_threshold) & (top_scores > 0.0)
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    scores = jnp.where(keep, top_scores, 0.0)
    classes = jnp.where(keep, classes, -1)
    return boxes, scores, classes
