# Model zoo: TPU-native implementations of the model families the
# reference reaches through external CUDA/HTTP dependencies (SURVEY.md §2:
# WhisperX ASR, ResNet-class vision, LLM agent).
#
# jax imports are deliberately NOT triggered by the package root —
# `import aiko_services_tpu` stays control-plane-cheap; import
# aiko_services_tpu.models explicitly for the compute plane.

from .whisper import (                                      # noqa: F401
    WHISPER_PRESETS, WhisperConfig, whisper_init, whisper_axes,
    encode, decode_step, greedy_decode, forward,
)
from .resnet import (                                       # noqa: F401
    RESNET_PRESETS, ResNetConfig, resnet_init, resnet_axes, resnet_forward,
)
from .llama import (                                        # noqa: F401
    LLAMA_PRESETS, LlamaConfig, llama_init, llama_axes, llama_forward,
    llama_decode_step, llama_greedy_decode, init_llama_caches,
)
from .moe import (                                          # noqa: F401
    MoeConfig, moe_init, moe_axes, moe_forward,
)
from .tokenizer import (                                    # noqa: F401
    BPETokenizer, ByteTokenizer, WhisperTokens, load_tokenizer,
)
from .tts import (                                          # noqa: F401
    TTSConfig, TTS_PRESETS, tts_init, tts_axes, tts_forward, synthesize,
    predict_durations, regulate,
)
from . import layers                                        # noqa: F401
