# Tokenizers: byte-level BPE (the GPT-2 scheme Whisper and Llama-2-era
# checkpoints use on disk) plus a byte-direct tokenizer for tests.
#
# Capability parity: the reference gets text out of faster-whisper's
# bundled tokenizer (reference: examples/speech/speech_elements.py:217-250
# — transcription segments arrive as strings).  This framework runs the
# model math itself, so it needs its own id↔text path: a self-contained
# BPE implementation that loads standard vocab.json/merges.txt files
# (produced from a real checkpoint by tools/convert_whisper.py) with no
# network or external tokenizer library.
#
# Implemented fresh from the published BPE algorithm (Sennrich et al.;
# byte-level variant per GPT-2): greedy lowest-rank pair merging over a
# reversible byte→unicode alphabet.

from __future__ import annotations

import json
import os
import re

__all__ = ["BPETokenizer", "ByteTokenizer", "WhisperTokens",
           "load_tokenizer", "byte_to_unicode"]


def byte_to_unicode() -> dict:
    """Reversible byte→printable-unicode map (byte-level BPE alphabet).

    Printable ASCII + two latin-1 ranges map to themselves; the remaining
    68 bytes map to 256+n so every byte has a distinct printable symbol
    and vocab files stay valid JSON text."""
    keep = (list(range(ord("!"), ord("~") + 1)) +
            list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    mapping = {}
    next_code = 256
    for byte in range(256):
        if byte in keep:
            mapping[byte] = chr(byte)
        else:
            mapping[byte] = chr(next_code)
            next_code += 1
    return mapping


# GPT-2's pre-tokenizer split (contractions, letter runs, digit runs,
# punctuation runs, whitespace) expressed with re's unicode classes:
# [^\W\d_] ≈ \p{L}.  Merges never cross these boundaries — required for
# canonical ids vs the checkpoint's tokenizer, and it bounds the merge
# loop to one word instead of the whole text (O(w²) per word, not O(L²)).
_PRETOKENIZE = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?[^\W\d_]+| ?\d+| ?[^\s\w]+|_+|\s+(?!\S)|\s+")

# llama-3's tiktoken-style split, approximated with re's unicode
# classes: case-insensitive contractions, at most one leading
# non-letter before a letter run, digit runs broken into GROUPS OF ≤3,
# punctuation runs swallowing trailing newlines.  Ids diverge from the
# checkpoint's training tokenization if the GPT-2 split is used
# instead (digit runs and "DON'T" style contractions differ).
_PRETOKENIZE_LLAMA3 = re.compile(
    r"'(?i:s|t|re|ve|m|ll|d)"
    r"|(?:(?![\r\n])[\W_])?[^\W\d_]+"
    r"|\d{1,3}"
    r"| ?(?:[^\s\w]|_)+[\r\n]*"
    r"|\s*[\r\n]+"
    r"|\s+(?!\S)|\s+")


class BPETokenizer:
    """Byte-level BPE over a vocab dict + ranked merge list.

    encode: text → pre-token split → utf-8 bytes → unicode alphabet →
    greedy merges per pre-token → ids.
    decode: ids → tokens → bytes → utf-8 text (special ids skipped)."""

    def __init__(self, vocab: dict, merges: list, special_ids=(),
                 pretokenize=None):
        self.vocab = dict(vocab)                      # token str → id
        self.inverse = {i: t for t, i in self.vocab.items()}
        self.ranks = {tuple(pair): rank
                      for rank, pair in enumerate(merges)}
        self.special_ids = set(int(i) for i in special_ids)
        self.pretokenize = pretokenize or _PRETOKENIZE
        self._b2u = byte_to_unicode()
        self._u2b = {u: b for b, u in self._b2u.items()}

    def _merge_word(self, symbols: list) -> list:
        while len(symbols) > 1:
            best_rank, best_i = None, None
            for i in range(len(symbols) - 1):
                rank = self.ranks.get((symbols[i], symbols[i + 1]))
                if rank is not None and (best_rank is None or
                                         rank < best_rank):
                    best_rank, best_i = rank, i
            if best_i is None:
                break
            symbols = (symbols[:best_i] +
                       [symbols[best_i] + symbols[best_i + 1]] +
                       symbols[best_i + 2:])
        return symbols

    def encode(self, text: str) -> list:
        ids = []
        for word in self.pretokenize.findall(text):
            symbols = [self._b2u[b] for b in word.encode("utf-8")]
            for symbol in self._merge_word(symbols):
                if symbol in self.vocab:
                    ids.append(self.vocab[symbol])
                else:   # unmergeable multi-byte run: emit per-byte ids
                    ids.extend(self.vocab[ch] for ch in symbol
                               if ch in self.vocab)
        return ids

    def decode(self, ids) -> str:
        data = bytearray()
        for token_id in ids:
            token_id = int(token_id)
            if token_id in self.special_ids:
                continue
            token = self.inverse.get(token_id)
            if token is None:
                continue
            data.extend(self._u2b.get(ch, ord("?")) for ch in token)
        return data.decode("utf-8", errors="replace")


class ByteTokenizer:
    """Id == byte value (vocab 256): the deterministic tokenizer for the
    'test' whisper preset (sot=254, eot=255 double as bytes the test
    language never uses).  Lets golden transcription tests run with no
    vocab files."""

    def __init__(self, special_ids=(254, 255)):
        self.special_ids = set(special_ids)

    def encode(self, text: str) -> list:
        return [b for b in text.encode("utf-8")
                if b not in self.special_ids]

    def decode(self, ids) -> str:
        data = bytes(int(i) for i in ids
                     if int(i) not in self.special_ids and 0 <= int(i) < 256)
        return data.decode("utf-8", errors="replace")


class WhisperTokens:
    """Special-token ids for the multilingual whisper vocabulary, derived
    from the vocab size (matches openai/whisper's layout: specials start
    right after the text vocab at 50257)."""

    def __init__(self, vocab_size: int = 51865):
        base = 50257
        self.eot = base
        self.sot = base + 1
        self.translate = base + 100 + 1
        self.transcribe = base + 100 + 2
        self.no_timestamps = base + 106
        self.timestamp_begin = base + 107
        # timestamps run to the end of the model's output space
        # (51865 for the multilingual layout), NOT just to len(vocab.json)
        self.vocab_size = vocab_size

    def special_ids(self):
        """Everything decode should skip: control tokens + timestamps."""
        return set(range(self.eot, self.vocab_size))


def load_tokenizer(path: str):
    """Load a tokenizer from a path.

    - "builtin:byte" → ByteTokenizer (test preset).
    - directory with vocab.json + merges.txt (GPT-2/whisper layout) or
      a HF tokenizer.json (llama-3 layout: model.vocab/model.merges) →
      BPETokenizer with whisper special ids skipped on decode."""
    if path == "builtin:byte":
        return ByteTokenizer()
    vocab_file = os.path.join(path, "vocab.json")
    merges_file = os.path.join(path, "merges.txt")
    tokenizer_json = os.path.join(path, "tokenizer.json")
    if not os.path.exists(vocab_file) and os.path.exists(tokenizer_json):
        return _load_hf_tokenizer_json(tokenizer_json)
    with open(vocab_file, encoding="utf-8") as handle:
        vocab = json.load(handle)
    merges = []
    with open(merges_file, encoding="utf-8") as handle:
        for line in handle:
            line = line.rstrip("\n")
            if not line or line.startswith("#version"):
                continue
            parts = line.split(" ")
            if len(parts) == 2:
                merges.append((parts[0], parts[1]))
    special = set()
    if len(vocab) >= 50257 or any(t.startswith("<|") for t in vocab):
        special = WhisperTokens(max(len(vocab), 51865)).special_ids()
    return BPETokenizer(vocab, merges, special)


def _load_hf_tokenizer_json(pathname: str):
    """HF `tokenizers`-format file (llama-3 checkpoints ship only this):
    the BPE vocab/merges live under model.vocab / model.merges.
    (llama-2's sentencepiece tokenizer.model is NOT supported — convert
    with HF's transformers first.)"""
    with open(pathname, encoding="utf-8") as handle:
        spec = json.load(handle)
    model = spec.get("model", {})
    if model.get("type") != "BPE" or "vocab" not in model:
        raise ValueError(
            f"{pathname}: unsupported tokenizer (model.type="
            f"{model.get('type')!r}); only HF BPE tokenizer.json works")
    vocab = model["vocab"]
    merges = []
    for merge in model.get("merges", []):
        pair = merge.split(" ") if isinstance(merge, str) else merge
        if len(pair) == 2:
            merges.append((pair[0], pair[1]))
    special = {entry["id"] for entry in spec.get("added_tokens", [])}
    # llama-3-family tokenizers split with the tiktoken pattern (digit
    # groups of ≤3 etc.) — detect it STRUCTURALLY from the Split
    # pre-tokenizer's own Regex strings (not a substring of the dumped
    # spec) so ids match what the checkpoint was trained on
    from ..utils import get_logger
    logger = get_logger("models.tokenizer")
    patterns = _split_regex_patterns(spec.get("pre_tokenizer", {}))
    pretokenize, chosen = _choose_pretokenizer(patterns)
    logger.info("%s: pre-tokenizer = %s", pathname, chosen)
    return BPETokenizer(vocab, merges, special, pretokenize=pretokenize)


def _choose_pretokenizer(patterns):
    """Best available split for the checkpoint's Split patterns:

    1. the checkpoint's OWN Isolated word-split Regex compiled with
       the `regex` module (\\p classes match tiktoken exactly) — no
       hard-coded pattern to drift from the checkpoint;
    2. the re approximation of the llama-3 tiktoken split when the
       spec looks tiktoken-ish but `regex` is unavailable;
    3. None → the GPT-2 default split.

    Returns (compiled-or-None, label)."""
    candidates = [p for p, behavior in patterns
                  if behavior in (None, "Isolated")
                  and r"\p{L}" in p
                  and not re.search(r"\((?![?])", p)]  # findall needs
    #                                  no capturing groups ^
    if candidates:
        try:
            import regex
            return (regex.compile(candidates[0]),
                    "checkpoint-split-regex")
        except Exception:                      # pragma: no cover
            pass
    if any(r"\p{N}{1," in p for p, _ in patterns):
        return _PRETOKENIZE_LLAMA3, "llama3-tiktoken(re-approx)"
    return None, "gpt2-default"


def _split_regex_patterns(node) -> list:
    """(pattern, behavior) for every Split pre-tokenizer under a HF
    pre_tokenizer spec (handles Sequence nesting:
    {"pretokenizers": [...]} and the flat Split form
    {"pattern": {"Regex": "..."}, "behavior": "Isolated"})."""
    patterns = []
    if isinstance(node, dict):
        pattern = node.get("pattern")
        if isinstance(pattern, dict) and isinstance(
                pattern.get("Regex"), str):
            patterns.append((pattern["Regex"], node.get("behavior")))
        for value in node.values():
            if isinstance(value, (dict, list)):
                patterns.extend(_split_regex_patterns(value))
    elif isinstance(node, list):
        for value in node:
            patterns.extend(_split_regex_patterns(value))
    return patterns
