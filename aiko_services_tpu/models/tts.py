# Neural text-to-speech: compact FastSpeech-style acoustic model + the
# Griffin-Lim vocoder leg from ops/audio.
#
# Capability parity target: the reference's TTS element wraps Coqui VITS
# on the host (reference: examples/speech/speech_elements.py:96-131).
# Here the acoustic model is a jax conv-transformer: byte/BPE tokens →
# hidden states → fixed-factor upsample → log-mel frames, all static
# shapes so batched synthesis jits onto the MXU alongside the ASR
# programs; mel → waveform is mel_to_linear + griffin_lim (deterministic,
# weight-free).  Weights load via the same flat-npz scheme as whisper
# (elements/speech.py load_flat_npz), so a trained checkpoint drops in.

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["TTSConfig", "TTS_PRESETS", "tts_init", "tts_axes",
           "tts_forward", "synthesize"]


@dataclass(frozen=True)
class TTSConfig:
    vocab: int = 256              # byte-level input
    dim: int = 128
    num_layers: int = 4
    num_heads: int = 4
    n_mels: int = 80
    frames_per_token: int = 8     # fixed-length regulator (~12 chars/s)
    max_tokens: int = 128
    dtype: object = jnp.float32


TTS_PRESETS = {
    "test": TTSConfig(dim=64, num_layers=2, num_heads=4,
                      frames_per_token=6, max_tokens=32),
    "base": TTSConfig(),
}


def _block_init(key, config: TTSConfig):
    keys = jax.random.split(key, 3)
    dim, dtype = config.dim, config.dtype
    return {
        "ln_attn": L.layer_norm_init(dim, dtype),
        "attn": L.mha_init(keys[0], dim, config.num_heads, dtype=dtype),
        "ln_mlp": L.layer_norm_init(dim, dtype),
        "mlp_in": L.linear_init(keys[1], dim, dim * 4, dtype=dtype),
        "mlp_out": L.linear_init(keys[2], dim * 4, dim, dtype=dtype),
    }


def _block_axes():
    return {
        "ln_attn": L.layer_norm_axes(),
        "attn": L.mha_axes(),
        "ln_mlp": L.layer_norm_axes(),
        "mlp_in": L.linear_axes("embed", "ffn"),
        "mlp_out": L.linear_axes("ffn", "embed"),
    }


def tts_init(key, config: TTSConfig):
    keys = jax.random.split(key, config.num_layers + 3)
    return {
        "embed": L.embedding_init(keys[0], config.vocab, config.dim,
                                  config.dtype),
        "blocks": [_block_init(keys[i + 1], config)
                   for i in range(config.num_layers)],
        "ln_out": L.layer_norm_init(config.dim, config.dtype),
        "mel_head": L.linear_init(keys[-1], config.dim, config.n_mels,
                                  dtype=config.dtype),
    }


def tts_axes(config: TTSConfig):
    return {
        "embed": L.embedding_axes(),
        "blocks": [_block_axes()] * config.num_layers,
        "ln_out": L.layer_norm_axes(),
        "mel_head": L.linear_axes("embed", None),
    }


def tts_forward(params, config: TTSConfig, tokens):
    """tokens: [B, S] int32 (pad with 0) →
    log-mel [B, S * frames_per_token, n_mels] (whisper-normalized)."""
    x = L.embedding(params["embed"], tokens).astype(config.dtype)
    positions = L.sinusoid_position_encoding(tokens.shape[1], config.dim)
    x = x + positions[None].astype(x.dtype)
    for block in params["blocks"]:
        attn_out, _ = L.mha(block["attn"],
                            L.layer_norm(block["ln_attn"], x),
                            num_heads=config.num_heads)
        x = x + attn_out
        x = x + L.linear(block["mlp_out"], L.gelu(
            L.linear(block["mlp_in"],
                     L.layer_norm(block["ln_mlp"], x))))
    x = L.layer_norm(params["ln_out"], x)
    # length regulator: every token expands to frames_per_token frames
    # (static-shape stand-in for a duration predictor — XLA-friendly)
    x = jnp.repeat(x, config.frames_per_token, axis=1)
    return L.linear(params["mel_head"], x)


def synthesize(params, config: TTSConfig, tokens, n_iter: int = 32):
    """tokens → waveform [B, samples] via mel → linear → Griffin-Lim.
    One jittable program: batched synthesis runs on device end-to-end."""
    from ..ops.audio import griffin_lim, mel_to_linear

    mel = tts_forward(params, config, tokens)
    magnitude = mel_to_linear(mel.astype(jnp.float32),
                              num_mels=config.n_mels)
    return griffin_lim(magnitude, n_iter=n_iter)
