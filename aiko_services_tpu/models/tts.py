# Neural text-to-speech: compact FastSpeech-style acoustic model + the
# Griffin-Lim vocoder leg from ops/audio.
#
# Capability parity target: the reference's TTS element wraps Coqui VITS
# on the host (reference: examples/speech/speech_elements.py:96-131).
# Here the acoustic model is a jax conv-transformer: byte/BPE tokens →
# hidden states → LEARNED duration predictor → static-shape length
# regulation → log-mel frames, all static shapes so batched synthesis
# jits onto the MXU alongside the ASR programs; mel → waveform is
# mel_to_linear + griffin_lim (deterministic, weight-free).  Weights
# load via the same flat-npz scheme as whisper (elements/speech.py
# load_flat_npz), so a trained checkpoint drops in.
#
# TPU-first length regulation: predicted per-token durations expand to
# frames through a [T_max, S] alignment built from cumsum boundaries —
# pure vectorized comparisons, one compile per geometry, no
# data-dependent shapes (FastSpeech trains the duration head
# supervised, so the hard alignment needs no gradient through d).

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from . import layers as L

__all__ = ["TTSConfig", "TTS_PRESETS", "tts_init", "tts_axes",
           "tts_forward", "predict_durations", "regulate", "synthesize"]


@dataclass(frozen=True)
class TTSConfig:
    vocab: int = 256              # byte-level input
    dim: int = 128
    num_layers: int = 4
    num_heads: int = 4
    n_mels: int = 80
    frames_per_token: int = 8     # duration PRIOR (mean log-d bias)
    max_tokens: int = 128
    max_frames: int = 1024        # static regulator output length
    dtype: object = jnp.float32


TTS_PRESETS = {
    "test": TTSConfig(dim=64, num_layers=2, num_heads=4,
                      frames_per_token=6, max_tokens=32, max_frames=96),
    "base": TTSConfig(),
}


def _block_init(key, config: TTSConfig):
    keys = jax.random.split(key, 3)
    dim, dtype = config.dim, config.dtype
    return {
        "ln_attn": L.layer_norm_init(dim, dtype),
        "attn": L.mha_init(keys[0], dim, config.num_heads, dtype=dtype),
        "ln_mlp": L.layer_norm_init(dim, dtype),
        "mlp_in": L.linear_init(keys[1], dim, dim * 4, dtype=dtype),
        "mlp_out": L.linear_init(keys[2], dim * 4, dim, dtype=dtype),
    }


def _block_axes():
    return {
        "ln_attn": L.layer_norm_axes(),
        "attn": L.mha_axes(),
        "ln_mlp": L.layer_norm_axes(),
        "mlp_in": L.linear_axes("embed", "ffn"),
        "mlp_out": L.linear_axes("ffn", "embed"),
    }


def tts_init(key, config: TTSConfig):
    keys = jax.random.split(key, config.num_layers + 4)
    return {
        "embed": L.embedding_init(keys[0], config.vocab, config.dim,
                                  config.dtype),
        "blocks": [_block_init(keys[i + 1], config)
                   for i in range(config.num_layers)],
        "ln_out": L.layer_norm_init(config.dim, config.dtype),
        "mel_head": L.linear_init(keys[-2], config.dim, config.n_mels,
                                  dtype=config.dtype),
        # predicts log-duration per token (FastSpeech-style, trained
        # supervised against ground-truth alignments)
        "dur_head": L.linear_init(keys[-1], config.dim, 1,
                                  dtype=config.dtype),
    }


def tts_axes(config: TTSConfig):
    return {
        "embed": L.embedding_axes(),
        "blocks": [_block_axes()] * config.num_layers,
        "ln_out": L.layer_norm_axes(),
        "mel_head": L.linear_axes("embed", None),
        "dur_head": L.linear_axes("embed", None),
    }


def _encode(params, config: TTSConfig, tokens):
    x = L.embedding(params["embed"], tokens).astype(config.dtype)
    positions = L.sinusoid_position_encoding(tokens.shape[1], config.dim)
    x = x + positions[None].astype(x.dtype)
    for block in params["blocks"]:
        attn_out, _ = L.mha(block["attn"],
                            L.layer_norm(block["ln_attn"], x),
                            num_heads=config.num_heads)
        x = x + attn_out
        x = x + L.linear(block["mlp_out"], L.gelu(
            L.linear(block["mlp_in"],
                     L.layer_norm(block["ln_mlp"], x))))
    return L.layer_norm(params["ln_out"], x)


def _durations_from_hidden(params, config: TTSConfig, tokens, hidden):
    """(log-durations [B, S], durations [B, S] with pad tokens at 0).
    The frames_per_token prior is the head's log bias, so an untrained
    head regulates near the old fixed factor."""
    log_d = L.linear(params["dur_head"], hidden)[..., 0] + \
        jnp.log(float(config.frames_per_token))
    return log_d, jnp.where(tokens > 0, jnp.exp(log_d), 0.0)


def predict_durations(params, config: TTSConfig, tokens):
    """tokens [B, S] → (log-durations, durations) — see
    _durations_from_hidden."""
    hidden = _encode(params, config, tokens)
    return _durations_from_hidden(params, config, tokens, hidden)


def regulate(hidden, durations, max_frames: int):
    """Static-shape length regulation: token i owns frames
    [cumsum_{<i}, cumsum_{<=i}); frame t gathers its owner via a
    [T, S] boundary comparison — no dynamic shapes, one compile per
    geometry."""
    ends = jnp.cumsum(durations, axis=1)                  # [B, S]
    starts = ends - durations
    t = jnp.arange(max_frames, dtype=durations.dtype)[None, :, None]
    owner = ((t >= starts[:, None, :]) &
             (t < ends[:, None, :])).astype(hidden.dtype)  # [B, T, S]
    return owner @ hidden, ends[:, -1]


def tts_forward(params, config: TTSConfig, tokens, durations=None):
    """tokens: [B, S] int32 (pad with 0) →
    (log-mel [B, max_frames, n_mels], total frames [B]).

    durations=None predicts them (inference); training passes
    ground-truth durations (teacher forcing) so the mel loss does not
    need a gradient through the hard alignment."""
    hidden = _encode(params, config, tokens)
    if durations is None:
        _, durations = _durations_from_hidden(params, config, tokens,
                                              hidden)
    frames, total = regulate(hidden, durations.astype(jnp.float32),
                             config.max_frames)
    return L.linear(params["mel_head"], frames), total


def synthesize(params, config: TTSConfig, tokens, n_iter: int = 32,
               vocoder=None, vocoder_config=None):
    """tokens → (waveform [B, samples], voiced sample counts [B]) via
    predicted durations → mel → waveform.  One jittable program:
    batched synthesis runs on device end-to-end; callers trim each row
    to its sample count (the static tail past the predicted length
    synthesizes silence-garbage).

    The mel→waveform leg is the trained neural vocoder when `vocoder`
    params are given (models/vocoder.py — the Coqui-VITS-grade leg the
    reference wraps, speech_elements.py:96-131), else weight-free
    Griffin-Lim phase recovery (`n_iter` rounds)."""
    from ..ops.audio import WHISPER_HOP, griffin_lim, mel_to_linear

    mel, total_frames = tts_forward(params, config, tokens)
    if vocoder is not None:
        from .vocoder import vocoder_forward
        audio = vocoder_forward(vocoder, vocoder_config,
                                mel.astype(jnp.float32))
    else:
        magnitude = mel_to_linear(mel.astype(jnp.float32),
                                  num_mels=config.n_mels)
        audio = griffin_lim(magnitude, n_iter=n_iter)
    samples = jnp.clip(jnp.ceil(total_frames), 0,
                       config.max_frames).astype(jnp.int32) * WHISPER_HOP
    return audio, samples
