# Dashboard: terminal UI over the live service table and EC shares.
#
# Capability parity with the reference dashboard
# (reference: aiko_services/dashboard.py:279-750 — asciimatics TUI):
#   * services page: live table from the ServicesCache (registrar replica);
#   * selecting a service ECConsumes its share and shows the variables
#     live (reference: dashboard.py:337-352);
#   * update a share variable (publishes "(update name value)" to the
#     service's control topic, reference: dashboard.py:225-228);
#   * log page: tail of the selected service's log topic.
#
# Built on stdlib curses (no asciimatics dependency); rendering is
# separated from state (DashboardState) so the UI logic is testable
# headless, and `run_dashboard` drives the EventEngine and the screen from
# one loop.

from __future__ import annotations

import itertools
from collections import deque

from .service import ServiceFields, ServiceTopicPath
from .share import ECConsumer, ServicesCache
from .utils import generate, generate_sexpr, parse
from .utils.configuration import get_hostname, pid_verified
from .utils.sexpr import parse_int

__all__ = ["DashboardState", "run_dashboard", "register_plugin"]

_LOG_LIMIT = 256
_history_counter = itertools.count(1)   # unique response-topic suffixes

# Plugin pages keyed by protocol name (reference: dashboard.py:719-723 +
# dashboard_plugins.py): a plugin renders extra lines for a selected
# service of its protocol.
_PLUGINS: dict = {}


def register_plugin(protocol_name: str, render) -> None:
    """render(state, fields) -> list[str] shown under the share table."""
    _PLUGINS[protocol_name] = render


class DashboardState:
    """UI-independent dashboard model: the services table, the selected
    service's mirrored share, and its log tail."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.cache = ServicesCache(runtime)
        self.selected_index = 0
        # services | variables | log | history | metrics
        self.page = "services"
        self.share: dict = {}
        self._consumer = None
        self._log_topic = None
        self.log_lines: deque = deque(maxlen=_LOG_LIMIT)
        self.metrics_doc: dict | None = None    # latest snapshot JSON
        self._metrics_topic = None
        self.history_rows: list = []    # departed ServiceFields
        self._history_topic = None
        self._history_expected = None
        self.status = ""                # one-line feedback (kill, errors)
        # SLO alert records (ISSUE 11): retained {namespace}/alert/+
        # from every HealthAggregator — always subscribed, so the
        # metrics pane leads with what is FIRING right now
        self.alerts: dict[str, dict] = {}
        self._alert_topic = f"{runtime.namespace}/alert/+"
        runtime.add_message_handler(self._on_alert, self._alert_topic)

    # -- services table -----------------------------------------------------
    def services(self) -> list:
        return sorted(self.cache.services,
                      key=lambda fields: fields.topic_path)

    def selected(self):
        services = self.services()
        if not services:
            return None
        self.selected_index %= len(services)
        return services[self.selected_index]

    def move(self, delta: int) -> None:
        services = self.services()
        if services:
            self.selected_index = (self.selected_index + delta) % \
                len(services)

    # -- share mirror -------------------------------------------------------
    def open_variables(self) -> None:
        fields = self.selected()
        if fields is None:
            return
        self.close_consumer()
        self.share = {}
        self._consumer = ECConsumer(self.runtime, self.share,
                                    f"{fields.topic_path}/control")
        self.page = "variables"

    def update_variable(self, name: str, value) -> None:
        fields = self.selected()
        if fields is not None:
            # double-encode like ECProducer._notify does: the receiving
            # side parse_sexpr-inverts every wire value, so a
            # single-encoded structured string would get over-parsed
            self.runtime.publish(
                f"{fields.topic_path}/control",
                generate("update", [name, generate_sexpr(value)]))

    def close_consumer(self) -> None:
        if self._consumer is not None:
            self._consumer.terminate()
            self._consumer = None

    # -- log tail -----------------------------------------------------------
    def open_log(self) -> None:
        fields = self.selected()
        if fields is None:
            return
        self.close_log()
        self.log_lines.clear()
        self._log_topic = f"{fields.topic_path}/log"
        self.runtime.add_message_handler(self._on_log, self._log_topic)
        self.page = "log"

    def _on_log(self, _topic, payload) -> None:
        # audited: deque(maxlen=_LOG_LIMIT)  # graft: disable=lint-unbounded-queue
        self.log_lines.append(str(payload))

    def close_log(self) -> None:
        if self._log_topic is not None:
            self.runtime.remove_message_handler(self._on_log,
                                                self._log_topic)
            self._log_topic = None

    # -- metrics pane (ISSUE 5) ---------------------------------------------
    def open_metrics(self) -> None:
        """Subscribe to the selected service's PROCESS metrics topic
        ({namespace}/{host}/{pid}/0/metrics — retained snapshots from
        observe.MetricsPublisher) and render the latest snapshot."""
        fields = self.selected()
        if fields is None:
            return
        self.close_metrics()
        self.metrics_doc = None
        process_path = fields.topic_path.rsplit("/", 1)[0]
        from .observe.export import METRICS_TOPIC_SUFFIX
        self._metrics_topic = f"{process_path}/{METRICS_TOPIC_SUFFIX}"
        self.runtime.add_message_handler(self._on_metrics,
                                         self._metrics_topic)
        self.page = "metrics"

    def _on_metrics(self, _topic, payload) -> None:
        from .observe.export import parse_retained_json
        document = parse_retained_json(payload)
        if document is not None:
            self.metrics_doc = document

    def _on_alert(self, _topic, payload) -> None:
        from .observe.export import parse_retained_json
        record = parse_retained_json(payload, require_key="rule")
        if record is not None:
            # keyed by configured SLO rule names — bounded:
            # graft: disable=lint-unbounded-cache
            self.alerts[str(record["rule"])] = record

    def alert_lines(self) -> list:
        """One line per FIRING SLO alert (empty when healthy)."""
        lines = []
        for rule in sorted(self.alerts):
            record = self.alerts[rule]
            if record.get("state") != "firing":
                continue
            description = record.get("description", "")
            lines.append(f"ALERT {rule} firing since "
                         f"{record.get('since', '?')}"
                         + (f" — {description}" if description else ""))
        return lines

    def close_metrics(self) -> None:
        if self._metrics_topic is not None:
            self.runtime.remove_message_handler(self._on_metrics,
                                                self._metrics_topic)
            self._metrics_topic = None

    def metrics_lines(self) -> list:
        """The metrics page body: the latest published snapshot as
        aligned text rows (counters/gauges by series, histograms as
        count / mean / approximate p50+p95 from bucket counts)."""
        doc = self.metrics_doc
        # firing alerts lead even with no snapshot yet: a dead fleet
        # (no live publisher) with retained alerts is exactly when the
        # pane matters most
        lines = self.alert_lines()
        if not doc:
            return lines + ["waiting for a metrics snapshot on "
                            f"{self._metrics_topic} ..."]
        from .observe.export import series_key, series_quantile
        lines.append(f"process: {doc.get('process', '?')}  "
                     f"time: {doc.get('time', '?')}")
        snapshot = doc.get("snapshot", {})
        # per-tenant SLO rows (ISSUE 12): deadline attainment + merged
        # sketch p95s lead the pane — the per-series listing below is
        # forensics, this is the verdict
        from .observe.journey import tenant_slo_rows
        rows = tenant_slo_rows([snapshot])
        if rows:
            lines.append("  tenant SLO (journeys + merged sketches):")
            for row in rows:
                attainment = "-" if row["attainment"] is None else \
                    f"{row['attainment']:.3f}"
                ttft = "-" if row["ttft_p95_ms"] is None else \
                    f"{row['ttft_p95_ms']:.1f}ms"
                itl = "-" if row["itl_p95_ms"] is None else \
                    f"{row['itl_p95_ms']:.2f}ms"
                lines.append(
                    f"    {row['tenant']:16.16s} met={attainment} "
                    f"ttft_p95={ttft} itl_p95={itl} "
                    f"shed={row['shed']} rejected={row['rejected']}")
        lines.extend(self._memory_lines(snapshot, rows))
        for name in sorted(snapshot):
            entry = snapshot[name]
            for series in entry.get("series", []):
                shown = series_key(name, series.get("labels", {}))
                if entry.get("type") == "histogram":
                    count = series.get("count", 0)
                    mean = (series.get("sum", 0.0) / count) if count \
                        else 0.0
                    p50 = series_quantile(series, 0.5)
                    p95 = series_quantile(series, 0.95)
                    lines.append(f"  {shown:46.46s} n={count} "
                                 f"mean={mean * 1000.0:.2f}ms "
                                 f"p50<={p50 * 1000.0:.2f}ms "
                                 f"p95<={p95 * 1000.0:.2f}ms")
                else:
                    lines.append(f"  {shown:46.46s} "
                                 f"{series.get('value', 0)}")
        return lines

    def _memory_lines(self, snapshot: dict, rows: list) -> list:
        """KV memory section (ISSUE 20): per-tier occupancy, top
        tenants by attributed bytes, and firing ledger-violation
        alerts — empty when the snapshot carries no ledger families."""
        lines = []
        occupancy = []
        for series in snapshot.get("kv_pool_occupancy",
                                   {}).get("series", []):
            labels = series.get("labels", {}) or {}
            occupancy.append(
                f"pool {labels.get('pool', '?')} "
                f"{float(series.get('value', 0)):.0%}")
        for series in snapshot.get("kv_ledger_host_pressure",
                                   {}).get("series", []):
            occupancy.append(
                f"host {float(series.get('value', 0)):.0%}")
        by_bytes = sorted(
            (row for row in rows
             if row.get("device_bytes") or row.get("host_bytes")),
            key=lambda r: -(r["device_bytes"] + r["host_bytes"]))
        violations = sum(
            float(series.get("value", 0))
            for series in snapshot.get("kv_ledger_violations",
                                       {}).get("series", []))
        if not (occupancy or by_bytes or violations):
            return lines
        lines.append("  KV memory (ledger):")
        if occupancy:
            lines.append("    occupancy: " + "  ".join(occupancy))
        for row in by_bytes[:4]:
            lines.append(
                f"    {row['tenant']:16.16s} "
                f"device={row['device_bytes']:,d}B "
                f"host={row['host_bytes']:,d}B "
                f"byte_s={row['byte_seconds']:,.0f} "
                f"demote/promote={row['demotions']}/"
                f"{row['promotions']}")
        if violations:
            lines.append(f"    VIOLATIONS: {int(violations)} "
                         f"(kv_ledger_violations latched)")
        for rule in sorted(self.alerts):
            record = self.alerts[rule]
            if record.get("state") == "firing" and \
                    "ledger" in rule.lower():
                lines.append(f"    ALERT {rule} firing — "
                             f"{record.get('description', '')}")
        return lines

    # -- registrar history (reference: dashboard.py:279-509 history table) --
    def open_history(self, count: int = 64) -> None:
        """Ask the primary registrar for its ring buffer of departed
        services (`(history response count)` protocol,
        reference registrar.py:263-288)."""
        registrar = self.runtime.registrar
        if registrar is None:
            self.status = "no registrar"
            return
        self.close_history()
        self.history_rows = []
        self._history_topic = (f"{self.runtime.topic_path}/0/history/"
                               f"{next(_history_counter)}")
        self._history_expected = None
        self.runtime.add_message_handler(self._on_history,
                                         self._history_topic)
        self.runtime.publish(
            f"{registrar['topic_path']}/in",
            generate("history", [self._history_topic, str(count)]))
        self.page = "history"

    def _on_history(self, _topic, payload) -> None:
        try:
            command, params = parse(payload)
        except Exception:
            return
        if command == "item_count" and params:
            self._history_expected = parse_int(params[0], 0)
        elif command == "history" and params:
            try:
                # audited: reset per history request, bounded by the
                # registrar's requested count  # graft: disable=lint-unbounded-queue
                self.history_rows.append(ServiceFields.from_record(
                    params[0]))
            except Exception:
                pass

    @property
    def history_complete(self) -> bool:
        return (self._history_expected is not None and
                len(self.history_rows) >= self._history_expected)

    def close_history(self) -> None:
        if self._history_topic is not None:
            self.runtime.remove_message_handler(self._on_history,
                                                self._history_topic)
            self._history_topic = None

    # -- process kill (reference: dashboard.py:361-370, local kill -9) ------
    def kill_selected(self) -> None:
        """Terminate the selected service's process: SIGKILL when it is
        on this host (the reference's behavior); for remote processes —
        which the reference cannot kill at all — fall back to a graceful
        `(control_stop)` to the service."""
        fields = self.selected()
        if fields is None:
            return
        topic_path = ServiceTopicPath.parse(fields.topic_path)
        pid = None
        if topic_path is not None:
            try:
                pid = int(topic_path.process_id.split("-")[0])
            except ValueError:
                pid = None
        import os
        if topic_path is not None and pid is not None and \
                topic_path.hostname == get_hostname() and \
                pid != os.getpid():
            # a stale table row whose pid was recycled by an unrelated
            # process must not be SIGKILLed — only signal pids whose
            # cmdline still looks like one of ours
            if not pid_verified(pid):
                self.runtime.publish(f"{fields.topic_path}/in",
                                     "(control_stop)")
                self.status = (f"pid {pid} not verified as aiko — "
                               f"sent control_stop to {fields.name}")
                return
            import signal
            try:
                os.kill(pid, signal.SIGKILL)
                self.status = f"killed pid {pid} ({fields.name})"
            except OSError as exc:
                self.status = f"kill {pid} failed: {exc}"
            return
        self.runtime.publish(f"{fields.topic_path}/in", "(control_stop)")
        self.status = f"sent control_stop to {fields.name}"

    # -- clipboard (reference: dashboard.py 'c' key handler) ----------------
    def copy_topic_path(self) -> str | None:
        """Copy the selected service's topic path to the system
        clipboard ('c' key, as in the reference dashboard).  Tries the
        usual clipboard tools; headless hosts still get the path in
        the status line (and the return value) to select manually."""
        fields = self.selected()
        if fields is None:
            return None
        text = fields.topic_path
        import shutil
        import subprocess
        for tool in (["wl-copy"], ["xclip", "-selection", "clipboard"],
                     ["xsel", "--clipboard", "--input"], ["pbcopy"]):
            if shutil.which(tool[0]):
                try:
                    subprocess.run(tool, input=text.encode(),
                                   timeout=2, check=True)
                    self.status = f"copied {text}"
                    return text
                except (OSError, subprocess.SubprocessError):
                    continue
        self.status = f"no clipboard tool; topic: {text}"
        return text

    # -- log level (reference: dashboard.py:663-707 popup) ------------------
    def set_log_level(self, level: str) -> None:
        """Publish `(update log_level LEVEL)` to the selected service —
        every actor's share applies it live."""
        self.update_variable("log_level", str(level).upper())
        self.status = f"log_level → {str(level).upper()}"

    def back(self) -> None:
        self.close_consumer()
        self.close_log()
        self.close_history()
        self.close_metrics()
        self.status = ""
        self.page = "services"

    def plugin_lines(self) -> list:
        """Extra page content from the plugin registered for the selected
        service's protocol."""
        fields = self.selected()
        if fields is None:
            return []
        protocol_name = fields.protocol.rsplit("/", 1)[-1].split(":")[0]
        plugin = _PLUGINS.get(protocol_name)
        if plugin is None:
            return []
        try:
            return list(plugin(self, fields))
        except Exception as exc:
            return [f"plugin error: {exc!r}"]

    def flat_share(self) -> list:
        rows = []
        for key, value in sorted(self.share.items()):
            if isinstance(value, dict):
                for sub, sub_value in sorted(value.items()):
                    rows.append((f"{key}.{sub}", sub_value))
            else:
                rows.append((key, value))
        return rows

    def terminate(self) -> None:
        self.back()
        self.runtime.remove_message_handler(self._on_alert,
                                            self._alert_topic)
        self.cache.terminate()


def _render(screen, state: DashboardState) -> None:
    import curses

    screen.erase()
    height, width = screen.getmaxyx()
    title = (f" aiko_tpu dashboard — {state.page} — "
             f"{state.runtime.namespace} ")
    screen.addnstr(0, 0, title.ljust(width - 1), width - 1,
                   curses.A_REVERSE)

    if state.page == "services":
        header = f"{'SERVICE':32.32s} {'PROTOCOL':24.24s} TOPIC"
        screen.addnstr(1, 0, header, width - 1, curses.A_BOLD)
        for row, fields in enumerate(state.services()[:height - 3]):
            attribute = curses.A_REVERSE if row == state.selected_index \
                else curses.A_NORMAL
            protocol = fields.protocol.rsplit("/", 1)[-1]
            line = (f"{fields.name:32.32s} {protocol:24.24s} "
                    f"{fields.topic_path}")
            screen.addnstr(2 + row, 0, line, width - 1, attribute)
        footer = ("↑/↓ select · ⏎ variables · l log · h history · "
                  "m metrics · x kill · q quit")
    elif state.page == "variables":
        fields = state.selected()
        screen.addnstr(1, 0, f"share: {fields.name if fields else '?'}",
                       width - 1, curses.A_BOLD)
        # plugin lines first: they must stay visible even when the share
        # table alone exceeds the screen
        rows = state.plugin_lines()
        rows += [f"{key:40.40s} {value}"
                 for key, value in state.flat_share()]
        for row, line in enumerate(rows[:height - 3]):
            screen.addnstr(2 + row, 0, line, width - 1)
        footer = "d/i/w/e log-level · b back · q quit"
    elif state.page == "metrics":
        screen.addnstr(1, 0, f"metrics: {state._metrics_topic}",
                       width - 1, curses.A_BOLD)
        for row, line in enumerate(state.metrics_lines()[:height - 3]):
            screen.addnstr(2 + row, 0, line, width - 1)
        footer = "b back · q quit"
    elif state.page == "history":
        header = f"{'DEPARTED SERVICE':32.32s} {'PROTOCOL':24.24s} TOPIC"
        screen.addnstr(1, 0, header, width - 1, curses.A_BOLD)
        for row, fields in enumerate(state.history_rows[:height - 3]):
            protocol = fields.protocol.rsplit("/", 1)[-1]
            line = (f"{fields.name:32.32s} {protocol:24.24s} "
                    f"{fields.topic_path}")
            screen.addnstr(2 + row, 0, line, width - 1)
        footer = "b back · q quit"
    else:
        screen.addnstr(1, 0, f"log: {state._log_topic}", width - 1,
                       curses.A_BOLD)
        lines = list(state.log_lines)[-(height - 3):]
        for row, line in enumerate(lines):
            screen.addnstr(2 + row, 0, line, width - 1)
        footer = "b back · q quit"
    if state.status:
        footer = f"{state.status} · {footer}"
    screen.addnstr(height - 1, 0, footer.ljust(width - 1), width - 1,
                   curses.A_REVERSE)
    screen.refresh()


def run_dashboard(runtime, tick: float = 0.05) -> None:
    """Blocking curses loop; drives the runtime's EventEngine inline
    (reference refresh: 20 FPS, dashboard.py:217-219)."""
    import curses

    from .dashboard_plugins import register_builtins
    register_builtins()

    state = DashboardState(runtime)

    def loop(screen):
        curses.curs_set(0)
        screen.nodelay(True)
        while True:
            for _ in range(8):
                runtime.event.step()
            key = screen.getch()
            if key in (ord("q"), 27):
                break
            elif key in (curses.KEY_UP, ord("k")):
                state.move(-1)
            elif key in (curses.KEY_DOWN, ord("j")):
                state.move(1)
            elif key in (curses.KEY_ENTER, 10, 13) and \
                    state.page == "services":
                state.open_variables()
            elif key == ord("l") and state.page == "services":
                state.open_log()
            elif key == ord("h") and state.page == "services":
                state.open_history()
            elif key == ord("m") and state.page == "services":
                state.open_metrics()
            elif key == ord("x") and state.page == "services":
                state.kill_selected()
            elif key == ord("c"):
                state.copy_topic_path()
            elif state.page == "variables" and key in (
                    ord("d"), ord("i"), ord("w"), ord("e")):
                state.set_log_level({"d": "DEBUG", "i": "INFO",
                                     "w": "WARNING",
                                     "e": "ERROR"}[chr(key)])
            elif key == ord("b"):
                state.back()
            _render(screen, state)
            import time
            time.sleep(tick)

    try:
        curses.wrapper(loop)
    finally:
        state.terminate()
