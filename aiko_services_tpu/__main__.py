# `python -m aiko_services_tpu ...` — same surface as the aiko_tpu
# console script (pyproject [project.scripts]).

from .cli import main

if __name__ == "__main__":
    main()
