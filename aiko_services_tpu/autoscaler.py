# Autoscaler: metrics-driven elastic capacity for serving runtimes
# (ISSUE 9, ROADMAP item 2 — the third leg of the overload-control
# plane beside deadline-aware admission and per-tenant fair queuing).
#
# Every process already publishes retained metrics snapshots on
# {topic_path}/0/metrics (observe/export.py MetricsPublisher) and the
# LifeCycleManager already supervises a fleet under a RestartPolicy
# (ISSUE 4).  This actor closes the loop: it subscribes to the
# namespace's metrics topics, extracts the three load signals the
# roadmap names — event mailbox depth, remote-hop p95 latency, and
# batch-former queue wait — and scales the fleet through
# LifeCycleManager.scale_to with hysteresis, so a threshold-straddling
# load step cannot flap capacity up and down every evaluation:
#
#   * scale UP when ANY signal has breached its up-threshold for
#     `hysteresis` consecutive evaluations (overload is urgent; one
#     healthy signal must not veto);
#   * scale DOWN when EVERY signal has been below its down-threshold
#     for `hysteresis` consecutive evaluations (shrinking is cheap to
#     delay, expensive to regret);
#   * hold the floor immediately: a fleet below min_clients (a crash
#     the restart policy has not yet replaced, a crash-looping
#     manager) respawns on the next evaluation without waiting out the
#     streak — capacity loss is the one signal that needs no
#     confirmation;
#   * a cooldown after every action lets the new capacity's metrics
#     arrive before the next verdict.
#
# Scale decisions are themselves observable: counted into
# autoscaler_decisions_total{action, reason}, mirrored into gauges, and
# recorded as tracer spans when tracing is enabled.

from __future__ import annotations

import json
import time
from dataclasses import dataclass

from .actor import Actor
from .observe import tracing
from .observe.export import METRICS_TOPIC_SUFFIX, series_quantile
from .observe.metrics import default_registry
from .service import ServiceProtocol
from .utils import get_logger

__all__ = ["Autoscaler", "ScalePolicy", "PROTOCOL_AUTOSCALER"]

PROTOCOL_AUTOSCALER = ServiceProtocol("autoscaler")

# a snapshot older than this many seconds is a corpse (its process died
# or its publisher stopped) and must not keep voting on load
_SNAPSHOT_HORIZON = 30.0


@dataclass(frozen=True)
class ScalePolicy:
    """Thresholds and pacing for the scale loop.  Up-thresholds trip on
    ANY signal; down-thresholds require ALL signals quiet."""
    min_clients: int = 1
    max_clients: int = 4
    mailbox_depth_up: float = 64.0      # queued events, worst process
    hop_p95_up: float = 1.0             # seconds, pipeline_hop_seconds
    batch_wait_up: float = 100.0        # ms, batch_mean_wait_ms
    mailbox_depth_down: float = 4.0
    hop_p95_down: float = 0.25
    batch_wait_down: float = 20.0
    hysteresis: int = 3                 # consecutive breaching evals
    cooldown: float = 10.0              # seconds between scale actions
    step: int = 1                       # clients added/removed per action


class Autoscaler(Actor):
    """Watches retained {topic}/0/metrics snapshots and drives a
    LifeCycleManager's fleet size.

    `manager` is the LifeCycleManager whose spawner builds one serving
    runtime per client (under its RestartPolicy — the autoscaler and
    the crash supervisor share one actuator, so they cannot fight over
    the same fleet).  `topic_filter` defaults to every process in the
    runtime's namespace; narrow it when several fleets share a
    namespace."""

    def __init__(self, runtime, name: str = "autoscaler", manager=None,
                 policy: ScalePolicy | None = None,
                 interval: float = 2.0, topic_filter: str | None = None):
        super().__init__(runtime, name, PROTOCOL_AUTOSCALER)
        self.logger = get_logger(f"autoscaler.{name}")
        self.manager = manager
        self.policy = policy or ScalePolicy()
        self.interval = float(interval)
        # topic_path is {namespace}/{host}/{pid}; metrics snapshots ride
        # {topic_path}/0/metrics
        self._filter = topic_filter or \
            f"{runtime.namespace}/+/+/{METRICS_TOPIC_SUFFIX}"
        self._snapshots: dict[str, dict] = {}    # topic_path -> document
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: float | None = None
        registry = default_registry()
        labels = {"autoscaler": name}
        self._decision_counters: dict = {}
        self._registry = registry
        self._labels = labels
        self._clients_gauge = registry.gauge(
            "autoscaler_clients", "fleet size the autoscaler manages",
            labels)
        self._signal_gauges = {
            "mailbox_depth": registry.gauge(
                "autoscaler_signal_mailbox_depth",
                "worst observed event mailbox depth", labels),
            "hop_p95": registry.gauge(
                "autoscaler_signal_hop_p95_s",
                "worst observed remote-hop p95 seconds", labels),
            "batch_wait": registry.gauge(
                "autoscaler_signal_batch_wait_ms",
                "worst observed batch-former mean wait ms", labels),
        }
        runtime.add_message_handler(self._metrics_handler, self._filter)
        self._timer = runtime.event.add_timer_handler(self.evaluate,
                                                      self.interval)

    # -- snapshot intake ----------------------------------------------------
    def _metrics_handler(self, topic: str, payload) -> None:
        try:
            if isinstance(payload, (bytes, bytearray)):
                payload = payload.decode("utf-8")
            document = json.loads(payload)
        except Exception:
            self.logger.debug("autoscaler %s: unparseable snapshot on "
                              "%s", self.name, topic)
            return
        if not isinstance(document, dict) or "snapshot" not in document:
            return
        document["_received"] = self.runtime.event.clock.now()
        self._snapshots[str(document.get("topic_path", topic))] = document

    # -- signal extraction --------------------------------------------------
    def signals(self) -> dict:
        """Worst-case load signals across every live snapshot:
        {"mailbox_depth", "hop_p95", "batch_wait"} (0.0 when a family
        has no series yet)."""
        now = self.runtime.event.clock.now()
        mailbox = hop_p95 = batch_wait = 0.0
        # prune corpses outright: under restart churn every dead
        # process left its last full snapshot behind under a unique
        # pid topic_path — skipping them is not enough, the dict (and
        # the per-tick iteration) must not grow without bound
        stale = [key for key, document in self._snapshots.items()
                 if now - document.get("_received", now)
                 > _SNAPSHOT_HORIZON]
        for key in stale:
            del self._snapshots[key]
        for document in self._snapshots.values():
            snapshot = document.get("snapshot", {})
            for series in snapshot.get("event_mailbox_depth",
                                       {}).get("series", []):
                mailbox = max(mailbox, float(series.get("value", 0)))
            for series in snapshot.get("pipeline_hop_seconds",
                                       {}).get("series", []):
                hop_p95 = max(hop_p95, series_quantile(series, 0.95))
            for series in snapshot.get("batch_mean_wait_ms",
                                       {}).get("series", []):
                batch_wait = max(batch_wait,
                                 float(series.get("value", 0)))
        return {"mailbox_depth": mailbox, "hop_p95": hop_p95,
                "batch_wait": batch_wait}

    # -- the scale loop -----------------------------------------------------
    def _count_decision(self, action: str, reason: str) -> None:
        key = (action, reason)
        counter = self._decision_counters.get(key)
        if counter is None:
            counter = self._registry.counter(
                "autoscaler_decisions_total",
                "scale loop verdicts by action and reason",
                labels={**self._labels, "action": action,
                        "reason": reason})
            self._decision_counters[key] = counter
        counter.inc()

    def _in_cooldown(self, now: float) -> bool:
        return self._last_action_at is not None and \
            now - self._last_action_at < self.policy.cooldown

    def _act(self, delta: int, reason: str, now: float,
             signals: dict) -> None:
        action = "up" if delta > 0 else "down"
        target = len(self.manager.clients) + delta
        target = min(max(target, 0), self.policy.max_clients)
        if delta < 0:
            # a step larger than the headroom must not shrink below
            # the floor — it would trigger a below-floor respawn next
            # tick and flap forever
            target = max(target, self.policy.min_clients)
        started = time.perf_counter()
        applied = self.manager.scale_to(target)
        if applied == 0:
            return
        self._last_action_at = now
        self._up_streak = 0
        self._down_streak = 0
        self._count_decision(action, reason)
        self.logger.warning(
            "autoscaler %s: scale %s (%+d -> %d clients, reason=%s, "
            "signals=%s)", self.name, action, applied,
            len(self.manager.clients), reason,
            {k: round(v, 3) for k, v in signals.items()})
        trc = tracing.tracer
        if trc.enabled:
            trc.record(f"autoscale:{action}", started,
                       time.perf_counter() - started,
                       context=tracing.new_trace(), cat="autoscale",
                       proc=self.name,
                       args={"reason": reason, "delta": applied,
                             **{k: round(v, 4)
                                for k, v in signals.items()}})

    def evaluate(self) -> None:
        """One scale-loop tick (engine timer, so virtual-clock tests
        drive it deterministically)."""
        if self.manager is None:
            return
        policy = self.policy
        now = self.runtime.event.clock.now()
        signals = self.signals()
        self._signal_gauges["mailbox_depth"].set(
            signals["mailbox_depth"])
        self._signal_gauges["hop_p95"].set(signals["hop_p95"])
        self._signal_gauges["batch_wait"].set(signals["batch_wait"])
        total = len(self.manager.clients)
        self._clients_gauge.set(total)

        # floor restoration needs no hysteresis: lost capacity (a crash
        # the restart supervisor gave up on, a slow respawn) is not a
        # noisy signal — but it still honours the cooldown, or a
        # handshaking replacement would be double-spawned every tick
        if total < policy.min_clients:
            if not self._in_cooldown(now):
                self._act(policy.min_clients - total, "below-floor",
                          now, signals)
            return
        overload = (
            signals["mailbox_depth"] >= policy.mailbox_depth_up
            or signals["hop_p95"] >= policy.hop_p95_up
            or signals["batch_wait"] >= policy.batch_wait_up)
        underload = (
            signals["mailbox_depth"] <= policy.mailbox_depth_down
            and signals["hop_p95"] <= policy.hop_p95_down
            and signals["batch_wait"] <= policy.batch_wait_down)
        if overload:
            self._up_streak += 1
            self._down_streak = 0
        elif underload:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # the dead band between the thresholds: this is what
            # absorbs a threshold-straddling load step — neither streak
            # may keep growing on ambiguous evidence
            self._up_streak = 0
            self._down_streak = 0
            self._count_decision("hold", "dead-band")
            return
        if self._in_cooldown(now):
            self._count_decision("hold", "cooldown")
            return
        if overload and self._up_streak >= policy.hysteresis:
            if total < policy.max_clients:
                self._act(policy.step, "overload", now, signals)
            else:
                self._count_decision("hold", "at-max")
        elif underload and self._down_streak >= policy.hysteresis:
            if total > policy.min_clients:
                self._act(-policy.step, "underload", now, signals)
            else:
                self._count_decision("hold", "at-min")

    def stop(self) -> None:
        if self._timer is not None:
            self.runtime.event.remove_timer_handler(self._timer)
            self._timer = None
        self.runtime.remove_message_handler(self._metrics_handler,
                                            self._filter)
        super().stop()
