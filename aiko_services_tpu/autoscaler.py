# Autoscaler: metrics-driven elastic capacity for serving runtimes
# (ISSUE 9, ROADMAP item 2 — the third leg of the overload-control
# plane beside deadline-aware admission and per-tenant fair queuing).
#
# Every process already publishes retained metrics snapshots on
# {topic_path}/0/metrics (observe/export.py MetricsPublisher) and the
# LifeCycleManager already supervises a fleet under a RestartPolicy
# (ISSUE 4).  This actor closes the loop: it subscribes to the
# namespace's metrics topics, extracts the three load signals the
# roadmap names — event mailbox depth, remote-hop p95 latency, and
# batch-former queue wait — and scales the fleet through
# LifeCycleManager.scale_to with hysteresis, so a threshold-straddling
# load step cannot flap capacity up and down every evaluation:
#
#   * scale UP when ANY signal has breached its up-threshold for
#     `hysteresis` consecutive evaluations (overload is urgent; one
#     healthy signal must not veto);
#   * scale DOWN when EVERY signal has been below its down-threshold
#     for `hysteresis` consecutive evaluations (shrinking is cheap to
#     delay, expensive to regret);
#   * hold the floor immediately: a fleet below min_clients (a crash
#     the restart policy has not yet replaced, a crash-looping
#     manager) respawns on the next evaluation without waiting out the
#     streak — capacity loss is the one signal that needs no
#     confirmation;
#   * a cooldown after every action lets the new capacity's metrics
#     arrive before the next verdict.
#
# Scale decisions are themselves observable: counted into
# autoscaler_decisions_total{action, reason}, mirrored into gauges, and
# recorded as tracer spans when tracing is enabled.
#
# Since ISSUE 11 the intake is the fleet health plane's SeriesStore
# (observe/series.py) instead of a latest-snapshot dict: every snapshot
# appends into per-(process, series) ring history, staleness falls out
# of the store's window (the old ad-hoc _SNAPSHOT_HORIZON pruning is
# gone), hop p95 is a WINDOWED delta-quantile (a cumulative histogram
# polluted before this autoscaler started cannot vote forever), the
# underload veto reads the window's WORST value (a spike inside the
# window blocks shrinking even if the latest tick looks quiet), and an
# optional TREND signal (mailbox-depth slope) scales up on the leading
# edge of a ramp before the level threshold trips.

from __future__ import annotations

import time
from dataclasses import dataclass

from .actor import Actor
from .observe import tracing
from .observe.export import METRICS_TOPIC_SUFFIX, parse_retained_json
from .observe.metrics import default_registry
from .observe.series import HistogramSeries, ScalarSeries, SeriesStore
from .service import ServiceProtocol
from .utils import get_logger

__all__ = ["Autoscaler", "ScalePolicy", "PROTOCOL_AUTOSCALER"]

PROTOCOL_AUTOSCALER = ServiceProtocol("autoscaler")

# series families the scale loop reads — the intake appends only these
# (the aggregator keeps full history; the autoscaler needs four, plus
# the serving TTFT sketches only when the policy arms that signal —
# retaining sketch payloads nobody reads would cost per-snapshot copies
# scaling with fleet size)
_SIGNAL_FAMILIES = ("event_mailbox_depth", "pipeline_hop_seconds",
                    "batch_mean_wait_ms", "admission_queue_depth",
                    "prefill_queue_depth", "serving_active_slots")


@dataclass(frozen=True)
class ScalePolicy:
    """Thresholds and pacing for the scale loop.  Up-thresholds trip on
    ANY signal; down-thresholds require ALL signals quiet."""
    min_clients: int = 1
    max_clients: int = 4
    mailbox_depth_up: float = 64.0      # queued events, worst process
    hop_p95_up: float = 1.0             # seconds, pipeline_hop_seconds
    batch_wait_up: float = 100.0        # ms, batch_mean_wait_ms
    # frames queued in the admission fair queue (worst tenant) — the
    # serving-side backlog the overload plane sheds from (ISSUE 11:
    # the fair queue's own pressure is a first-class scale signal)
    queue_depth_up: float = 256.0
    mailbox_depth_down: float = 4.0
    hop_p95_down: float = 0.25
    batch_wait_down: float = 20.0
    queue_depth_down: float = 8.0
    # leading-edge signal: worst mailbox-depth SLOPE (events/second
    # over the window) that votes overload.  None = level-only (the
    # pre-ISSUE-11 behaviour); a ramp that will cross mailbox_depth_up
    # in a few windows can then add capacity before it does.
    mailbox_trend_up: float | None = None
    # fleet-true TTFT p95 (seconds) from the MERGED serving sketches
    # (ISSUE 12): unlike every other signal this is not worst-of-
    # process — the store merges each runtime's windowed delta sketch,
    # so the autoscaler scales on the latency the fleet actually
    # served.  None = signal off.
    ttft_p95_up: float | None = None
    ttft_p95_down: float = 0.05
    # per-role pool signals (ISSUE 14, disaggregated prefill/decode):
    # a PREFILL-pool autoscaler arms prefill_queue_up (worst
    # prefill_queue_depth gauge — prompts waiting for KV compute, the
    # TTFT backlog) and usually ttft_p95_up; a DECODE-pool autoscaler
    # arms itl_p95_up (fleet-merged serving_itl_seconds sketch — the
    # number a prefill burst dilates) beside its batch-wait signals.
    # Both default OFF so existing single-pool policies are unchanged.
    prefill_queue_up: float | None = None
    prefill_queue_down: float = 1.0
    itl_p95_up: float | None = None
    itl_p95_down: float = 0.005
    # capacity-pressure signals (ISSUE 20, KV memory ledger): worst
    # KV block-pool occupancy fraction (kv_pool_occupancy gauge) and
    # worst host-tier pressure (kv_ledger_host_pressure — host store
    # bytes_used/max_bytes).  A fleet near pool exhaustion preempts
    # and sheds long before latency signals notice; host pressure
    # rising means demoted prefixes are about to start falling off the
    # bottom tier.  Both default OFF.
    pool_occupancy_up: float | None = None
    pool_occupancy_down: float = 0.25
    host_pressure_up: float | None = None
    host_pressure_down: float = 0.25
    # staleness/evidence window: a process silent longer than this
    # stops voting (replaces the old _SNAPSHOT_HORIZON), and the
    # underload veto considers the window's worst value
    window: float = 30.0
    hysteresis: int = 3                 # consecutive breaching evals
    cooldown: float = 10.0              # seconds between scale actions
    step: int = 1                       # clients added/removed per action


class Autoscaler(Actor):
    """Watches retained {topic}/0/metrics snapshots and drives a
    LifeCycleManager's fleet size.

    `manager` is the LifeCycleManager whose spawner builds one serving
    runtime per client (under its RestartPolicy — the autoscaler and
    the crash supervisor share one actuator, so they cannot fight over
    the same fleet).  `topic_filter` defaults to every process in the
    runtime's namespace; narrow it when several fleets share a
    namespace."""

    def __init__(self, runtime, name: str = "autoscaler", manager=None,
                 policy: ScalePolicy | None = None,
                 interval: float = 2.0, topic_filter: str | None = None,
                 drain_s: float | None = None):
        super().__init__(runtime, name, PROTOCOL_AUTOSCALER)
        self.logger = get_logger(f"autoscaler.{name}")
        self.manager = manager
        self.policy = policy or ScalePolicy()
        self.interval = float(interval)
        # graceful-drain arming (ISSUE 19): with drain_s set, every
        # shrink routes through LifeCycleManager.scale_to(...,
        # drain_s=) — retired runtimes drain and migrate instead of
        # being killed.  Unarmed, a shrink whose victims still report
        # live decode slots (the serving_active_slots gauge) is
        # REFUSED and counted: the pre-drain behaviour silently
        # dropped that work.
        self.drain_s = None if drain_s is None else float(drain_s)
        # topic_path is {namespace}/{host}/{pid}; metrics snapshots ride
        # {topic_path}/0/metrics
        self._filter = topic_filter or \
            f"{runtime.namespace}/+/+/{METRICS_TOPIC_SUFFIX}"
        # windowed series history (ISSUE 11): the store's window doubles
        # as the staleness horizon and its prune() as the corpse
        # collection the old snapshot dict did by hand
        self.store = SeriesStore(window=self.policy.window)
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_at: float | None = None
        registry = default_registry()
        labels = {"autoscaler": name}
        self._decision_counters: dict = {}
        self._registry = registry
        self._labels = labels
        self._clients_gauge = registry.gauge(
            "autoscaler_clients", "fleet size the autoscaler manages",
            labels)
        self._signal_gauges = {
            "mailbox_depth": registry.gauge(
                "autoscaler_signal_mailbox_depth",
                "worst observed event mailbox depth", labels),
            "hop_p95": registry.gauge(
                "autoscaler_signal_hop_p95_s",
                "worst windowed remote-hop p95 seconds", labels),
            "batch_wait": registry.gauge(
                "autoscaler_signal_batch_wait_ms",
                "worst observed batch-former mean wait ms", labels),
            "mailbox_trend": registry.gauge(
                "autoscaler_signal_mailbox_trend",
                "worst mailbox-depth slope (events/s over the window)",
                labels),
            "queue_depth": registry.gauge(
                "autoscaler_signal_queue_depth",
                "worst admission fair-queue depth", labels),
            "ttft_p95": registry.gauge(
                "autoscaler_signal_ttft_p95_s",
                "fleet-merged serving TTFT p95 seconds (sketch)",
                labels),
            "prefill_queue": registry.gauge(
                "autoscaler_signal_prefill_queue",
                "worst prefill-runtime queue depth", labels),
            "itl_p95": registry.gauge(
                "autoscaler_signal_itl_p95_s",
                "fleet-merged serving ITL p95 seconds (sketch)",
                labels),
            "pool_occupancy": registry.gauge(
                "autoscaler_signal_pool_occupancy",
                "worst KV block-pool occupancy fraction", labels),
            "host_pressure": registry.gauge(
                "autoscaler_signal_host_pressure",
                "worst host KV tier pressure (bytes_used/max_bytes)",
                labels),
        }
        self._families = set(_SIGNAL_FAMILIES)
        if self.policy.ttft_p95_up is not None:
            self._families.add("serving_ttft_seconds")
        if self.policy.itl_p95_up is not None:
            self._families.add("serving_itl_seconds")
        if self.policy.pool_occupancy_up is not None:
            self._families.add("kv_pool_occupancy")
        if self.policy.host_pressure_up is not None:
            self._families.add("kv_ledger_host_pressure")
        runtime.add_message_handler(self._metrics_handler, self._filter)
        self._timer = runtime.event.add_timer_handler(self.evaluate,
                                                      self.interval)

    # -- snapshot intake ----------------------------------------------------
    def _metrics_handler(self, topic: str, payload) -> None:
        document = parse_retained_json(payload, require_key="snapshot")
        if document is None:
            self.logger.debug("autoscaler %s: unparseable snapshot on "
                              "%s", self.name, topic)
            return
        self.store.append_snapshot(
            str(document.get("topic_path", topic)),
            document["snapshot"], self.runtime.event.clock.now(),
            families=self._families)

    # -- signal extraction --------------------------------------------------
    def _worst(self, family: str, read,
               kind: type = ScalarSeries) -> float:
        """Worst value of `read(ring)` across a family's rings, rings
        of the wrong series kind skipped: the store is fed from
        NETWORK-received snapshots, and a foreign/cross-version
        publisher shipping a family under the other metric type must
        not crash every evaluate tick with an AttributeError."""
        worst = 0.0
        for _, ring in self.store.rings(family):
            if not isinstance(ring, kind):
                continue
            value = read(ring)
            if value is not None:
                worst = max(worst, float(value))
        return worst

    def signals(self) -> dict:
        """Worst-case load signals across every process with evidence
        inside the policy window: levels read the LATEST sample (a
        silent process stops voting once its history ages out — the
        store's window IS the staleness horizon), hop p95 is the
        windowed delta-quantile, and mailbox_trend is the worst
        depth slope in events/second (the leading-edge signal)."""
        now = self.runtime.event.clock.now()
        window = self.policy.window
        self.store.prune(now)
        return {
            "mailbox_depth": self._worst(
                "event_mailbox_depth",
                lambda r: r.latest(now, window)),
            # baseline_empty: the FIRST snapshot a process ever sends
            # reports everything its cumulative histogram holds — one
            # sample is still evidence for capacity decisions (unlike
            # SLO alerting, which demands a real delta)
            "hop_p95": self._worst(
                "pipeline_hop_seconds",
                lambda r: r.delta_quantile(0.95, now, window,
                                           baseline_empty=True),
                kind=HistogramSeries),
            "batch_wait": self._worst(
                "batch_mean_wait_ms",
                lambda r: r.latest(now, window)),
            "mailbox_trend": self._worst(
                "event_mailbox_depth",
                lambda r: r.trend(now, window)),
            "queue_depth": self._worst(
                "admission_queue_depth",
                lambda r: r.latest(now, window)),
            "ttft_p95": self._merged_p95(
                "serving_ttft_seconds", self.policy.ttft_p95_up,
                now, window),
            "prefill_queue": self._worst(
                "prefill_queue_depth",
                lambda r: r.latest(now, window)),
            "itl_p95": self._merged_p95(
                "serving_itl_seconds", self.policy.itl_p95_up,
                now, window),
            "pool_occupancy": self._worst(
                "kv_pool_occupancy",
                lambda r: r.latest(now, window)),
            "host_pressure": self._worst(
                "kv_ledger_host_pressure",
                lambda r: r.latest(now, window)),
        }

    def _merged_p95(self, family: str, armed: float | None,
                    now: float, window: float) -> float:
        """Quantile of a CROSS-SOURCE merged windowed sketch family —
        fleet-true, not worst-of (ISSUE 12; ISSUE 14 adds the ITL
        family for the decode pool).  baseline_empty for the same
        reason as hop_p95: one snapshot is still capacity evidence.
        Computed only when the policy USES the signal (`armed` set) —
        reconstructing and merging every source's delta sketch per
        evaluate tick is not free, and the default policy ignores the
        result."""
        if armed is None:
            return 0.0
        merged = self.store.merged_sketch(
            family, now, window, baseline_empty=True)
        value = merged.quantile(0.95) if merged is not None else None
        return float(value) if value is not None else 0.0

    def _windowed_quiet(self, signals: dict, now: float) -> bool:
        """The underload veto reads the window's WORST values, not the
        latest tick: capacity shrinks only when the whole window was
        quiet — a spike two evaluations ago still blocks the shrink
        (shrinking is cheap to delay, expensive to regret)."""
        policy = self.policy
        window = policy.window
        worst_mailbox = self._worst("event_mailbox_depth",
                                    lambda r: r.maximum(now, window))
        worst_batch = self._worst("batch_mean_wait_ms",
                                  lambda r: r.maximum(now, window))
        worst_queue = self._worst("admission_queue_depth",
                                  lambda r: r.maximum(now, window))
        worst_prefill = self._worst("prefill_queue_depth",
                                    lambda r: r.maximum(now, window))
        worst_occupancy = self._worst("kv_pool_occupancy",
                                      lambda r: r.maximum(now, window))
        worst_host = self._worst("kv_ledger_host_pressure",
                                 lambda r: r.maximum(now, window))
        return (worst_mailbox <= policy.mailbox_depth_down
                and signals["hop_p95"] <= policy.hop_p95_down
                and worst_batch <= policy.batch_wait_down
                and worst_queue <= policy.queue_depth_down
                and (policy.ttft_p95_up is None
                     or signals["ttft_p95"] <= policy.ttft_p95_down)
                and (policy.prefill_queue_up is None
                     or worst_prefill <= policy.prefill_queue_down)
                and (policy.itl_p95_up is None
                     or signals["itl_p95"] <= policy.itl_p95_down)
                and (policy.pool_occupancy_up is None
                     or worst_occupancy <= policy.pool_occupancy_down)
                and (policy.host_pressure_up is None
                     or worst_host <= policy.host_pressure_down))

    # -- the scale loop -----------------------------------------------------
    def _count_decision(self, action: str, reason: str) -> None:
        key = (action, reason)
        counter = self._decision_counters.get(key)
        if counter is None:
            counter = self._registry.counter(
                "autoscaler_decisions_total",
                "scale loop verdicts by action and reason",
                labels={**self._labels, "action": action,
                        "reason": reason})
            self._decision_counters[key] = counter
        counter.inc()

    def _in_cooldown(self, now: float) -> bool:
        return self._last_action_at is not None and \
            now - self._last_action_at < self.policy.cooldown

    def live_slots(self) -> float:
        """Worst serving_active_slots gauge (live decode slots +
        queued requests) across every process with evidence inside
        the policy window — the shrink-safety signal.  0.0 when no
        decoder publishes the gauge (non-serving fleets keep the
        pre-ISSUE-19 shrink behaviour)."""
        now = self.runtime.event.clock.now()
        return self._worst("serving_active_slots",
                           lambda r: r.latest(now, self.policy.window))

    def _act(self, delta: int, reason: str, now: float,
             signals: dict) -> None:
        action = "up" if delta > 0 else "down"
        target = len(self.manager.clients) + delta
        target = min(max(target, 0), self.policy.max_clients)
        if delta < 0:
            # a step larger than the headroom must not shrink below
            # the floor — it would trigger a below-floor respawn next
            # tick and flap forever
            target = max(target, self.policy.min_clients)
            live = self.live_slots()
            if live > 0 and self.drain_s is None:
                # ISSUE 19 bugfix: shrink used to fire scale_to with
                # no in-flight check — the newest-first victim's live
                # generations died cold.  Without drain armed the
                # shrink is refused (and counted) until the fleet
                # reports zero live slots.
                self._count_decision("hold", "in-flight")
                self.logger.warning(
                    "autoscaler %s: shrink refused — %d live slot(s) "
                    "reported and drain is not armed", self.name,
                    int(live))
                return
        started = time.perf_counter()
        if delta < 0 and self.drain_s is not None:
            applied = self.manager.scale_to(target,
                                            drain_s=self.drain_s)
        else:
            applied = self.manager.scale_to(target)
        if applied == 0:
            return
        self._last_action_at = now
        self._up_streak = 0
        self._down_streak = 0
        self._count_decision(action, reason)
        self.logger.warning(
            "autoscaler %s: scale %s (%+d -> %d clients, reason=%s, "
            "signals=%s)", self.name, action, applied,
            len(self.manager.clients), reason,
            {k: round(v, 3) for k, v in signals.items()})
        trc = tracing.tracer
        if trc.enabled:
            trc.record(f"autoscale:{action}", started,
                       time.perf_counter() - started,
                       context=tracing.new_trace(), cat="autoscale",
                       proc=self.name,
                       args={"reason": reason, "delta": applied,
                             **{k: round(v, 4)
                                for k, v in signals.items()}})

    def evaluate(self) -> None:
        """One scale-loop tick (engine timer, so virtual-clock tests
        drive it deterministically)."""
        if self.manager is None:
            return
        policy = self.policy
        now = self.runtime.event.clock.now()
        signals = self.signals()
        self._signal_gauges["mailbox_depth"].set(
            signals["mailbox_depth"])
        self._signal_gauges["hop_p95"].set(signals["hop_p95"])
        self._signal_gauges["batch_wait"].set(signals["batch_wait"])
        self._signal_gauges["mailbox_trend"].set(
            signals["mailbox_trend"])
        self._signal_gauges["queue_depth"].set(signals["queue_depth"])
        self._signal_gauges["ttft_p95"].set(signals["ttft_p95"])
        self._signal_gauges["prefill_queue"].set(
            signals["prefill_queue"])
        self._signal_gauges["itl_p95"].set(signals["itl_p95"])
        self._signal_gauges["pool_occupancy"].set(
            signals["pool_occupancy"])
        self._signal_gauges["host_pressure"].set(
            signals["host_pressure"])
        total = len(self.manager.clients)
        self._clients_gauge.set(total)

        # floor restoration needs no hysteresis: lost capacity (a crash
        # the restart supervisor gave up on, a slow respawn) is not a
        # noisy signal — but it still honours the cooldown, or a
        # handshaking replacement would be double-spawned every tick
        if total < policy.min_clients:
            if not self._in_cooldown(now):
                self._act(policy.min_clients - total, "below-floor",
                          now, signals)
            return
        overload = (
            signals["mailbox_depth"] >= policy.mailbox_depth_up
            or signals["hop_p95"] >= policy.hop_p95_up
            or signals["batch_wait"] >= policy.batch_wait_up
            or signals["queue_depth"] >= policy.queue_depth_up
            or (policy.mailbox_trend_up is not None
                and signals["mailbox_trend"] >=
                policy.mailbox_trend_up)
            or (policy.ttft_p95_up is not None
                and signals["ttft_p95"] >= policy.ttft_p95_up)
            or (policy.prefill_queue_up is not None
                and signals["prefill_queue"] >= policy.prefill_queue_up)
            or (policy.itl_p95_up is not None
                and signals["itl_p95"] >= policy.itl_p95_up)
            or (policy.pool_occupancy_up is not None
                and signals["pool_occupancy"] >=
                policy.pool_occupancy_up)
            or (policy.host_pressure_up is not None
                and signals["host_pressure"] >= policy.host_pressure_up))
        underload = not overload and self._windowed_quiet(signals, now)
        if overload:
            self._up_streak += 1
            self._down_streak = 0
        elif underload:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # the dead band between the thresholds: this is what
            # absorbs a threshold-straddling load step — neither streak
            # may keep growing on ambiguous evidence
            self._up_streak = 0
            self._down_streak = 0
            self._count_decision("hold", "dead-band")
            return
        if self._in_cooldown(now):
            self._count_decision("hold", "cooldown")
            return
        if overload and self._up_streak >= policy.hysteresis:
            if total < policy.max_clients:
                self._act(policy.step, "overload", now, signals)
            else:
                self._count_decision("hold", "at-max")
        elif underload and self._down_streak >= policy.hysteresis:
            if total > policy.min_clients:
                self._act(-policy.step, "underload", now, signals)
            else:
                self._count_decision("hold", "at-min")

    def stop(self) -> None:
        if self._timer is not None:
            self.runtime.event.remove_timer_handler(self._timer)
            self._timer = None
        self.runtime.remove_message_handler(self._metrics_handler,
                                            self._filter)
        super().stop()
