# Tiered KV: host-RAM block offload with async promotion (ISSUE 17,
# ROADMAP item 3).
#
# Million-user conversation serving dies on HBM long before FLOPs:
# session-resident KV handles (PR 13) pin pool blocks for a lease's
# lifetime, so resident conversations × mean history is bounded by one
# chip's HBM.  The CachedAttention/AttentionStore pattern is the fix —
# idle conversations' KV lives in host memory and streams back
# just-in-time:
#
#   * HostBlockStore — the host tier.  Same block geometry as the
#     device BlockPool (per-layer [H, B, D] rows, int8 {"q", "s"}
#     dicts included), keyed by the SAME content-addressed chain keys
#     the prefix cache uses, with its own LRU + global/per-tenant byte
#     budgets and kv_host_bytes{tenant} gauges.  Demotion
#     (PrefixKVCache._evict with a host store attached, and the
#     SessionTable's on_demoted/on_expired wheel via demote_sessions)
#     copies a pool block's rows down ONCE and frees the device block
#     — the chain key survives, so the session's history is
#     recoverable instead of re-prefilled.
#
#   * AsyncPromoter — the off-event-loop prefetcher.  Admission
#     probes (estimated_admit_wait / the DeadlineRouter's next-hop
#     knowledge), the disagg client's submit, and PE_LlamaAgent's
#     session touch kick prefetch(tenant, tokens): host rows for the
#     chain's non-device-resident tail are captured ON the event loop
#     (GC-safe against concurrent host eviction) and a worker thread
#     stages them — per-layer [M, H, B, D] stacks, device_put'd off
#     the loop, so the H2D overlaps event-loop work.  poll() (the
#     decoder's admit round) and promote_for() (the sync fallback at
#     the actual admit probe) land staged stacks into freshly
#     allocated pool blocks + insert_block registrations — a warm
#     session's hit is then a table edit plus one overlapped H2D
#     instead of a cold prefill.
#
# The device↔host copies live HERE, behind the prefetcher seam —
# graft-check's lint-host-transfer rule refuses pool-block
# device_put/np.asarray inline in event-handler or hot-path contexts
# (a blocking H2D on the event loop stalls every stream it serves).
#
# Single-threaded discipline: every structure mutation (store dicts,
# cache inserts, pool alloc/write) happens on the event loop; the
# worker thread only reads row references it was handed and builds
# fresh arrays.  Fully CPU-verifiable — tests/test_tiered_kv.py proves
# greedy bit-parity across demote→promote cycles and a zero-block leak
# audit on both tiers.

from __future__ import annotations

import queue
import threading

import numpy as np

from .utils import Lock, get_logger

__all__ = ["HostBlockStore", "AsyncPromoter"]


def _host_leaf(leaf):
    """One block leaf copied to a host ndarray (the D2H of demotion).
    int8 storage keeps its {"q", "s"} dict form — the host tier holds
    the SAME geometry the pool does, so promotion is a pure write."""
    if isinstance(leaf, dict):
        return {"q": np.asarray(leaf["q"]), "s": np.asarray(leaf["s"])}
    return np.asarray(leaf)


class _HostBlock:
    """One demoted block: per-layer host K/V rows plus the chain
    bookkeeping promotion needs (parent key, tenant, bytes)."""

    __slots__ = ("key", "parent", "tenant", "k_rows", "v_rows",
                 "nbytes")

    def __init__(self, key, parent, tenant, k_rows, v_rows, nbytes):
        self.key = key
        self.parent = parent
        self.tenant = tenant
        self.k_rows = k_rows
        self.v_rows = v_rows
        self.nbytes = int(nbytes)


class HostBlockStore:
    """Host-RAM tier of the two-tier KV store (ISSUE 17).

    Holds demoted prefix-cache blocks as host ndarrays under their
    content-addressed chain keys.  LRU over one OrderedDict (oldest
    first, like PrefixKVCache) with a global byte budget plus an
    optional per-tenant residency cap — the host twin of the device
    tier's pin caps, so one tenant's idle history cannot evict
    everyone else's.  Gauges: kv_host_bytes{store, tenant} per tenant
    and kv_host_blocks{store}; counters kv_host_events_total{event=
    demoted|promoted|evicted|refused}.

    Single-threaded: called only from the event loop (the promoter's
    worker thread never touches the dicts — it reads row references
    captured at kick time)."""

    def __init__(self, max_bytes: int | None = 2 << 30,
                 tenant_max_bytes: int | None = None,
                 name: str = "host_kv", registry=None):
        self.max_bytes = int(max_bytes) if max_bytes else None
        self.tenant_max_bytes = int(tenant_max_bytes) \
            if tenant_max_bytes else None
        self.name = str(name)
        from collections import OrderedDict
        self._nodes: OrderedDict = OrderedDict()
        self._tenant_bytes: dict = {}
        self.bytes_used = 0
        self.logger = get_logger(f"serving.host_kv.{name}")
        from .observe.metrics import MirroredStats, default_registry
        self._registry = registry or default_registry()
        self.stats = MirroredStats(
            {"demoted": 0, "promoted": 0, "evicted": 0, "refused": 0,
             "demote_bytes": 0, "promote_bytes": 0},
            metric="kv_host_events_total",
            help="host KV tier events by kind",
            registry=self._registry,
            skip=("demote_bytes", "promote_bytes"),
            labels={"store": self.name})
        self._gauge_blocks = self._registry.gauge(
            "kv_host_blocks", "host-tier resident KV blocks",
            labels={"store": self.name})
        self._tenant_gauges: dict = {}
        # KV memory ledger (ISSUE 20): host-tier byte deltas report
        # at exactly the points _tenant_bytes moves, so ledger host
        # totals conserve against bytes_used by construction
        self._ledger = None

    def attach_ledger(self, ledger) -> None:
        self._ledger = ledger
        if ledger is not None:
            ledger.attach_host(self)

    # -- residency ---------------------------------------------------------
    def has(self, key: str) -> bool:
        return key in self._nodes

    def get(self, key: str):
        return self._nodes.get(key)

    def __len__(self) -> int:
        return len(self._nodes)

    def tenant_bytes(self, tenant: str) -> int:
        return self._tenant_bytes.get(str(tenant or "default"), 0)

    def put_from_device(self, tenant: str, parent: str, key: str,
                        k_rows, v_rows, nbytes: int) -> bool:
        """Demote one block: host-copy the pool's per-layer row views
        (the D2H — this IS the prefetcher seam's demotion half) and
        register them under the chain key.  Returns False when the
        host budgets refused it (the block is then truly evicted —
        demote-not-forget only holds while host bytes last)."""
        tenant = str(tenant or "default")
        if key in self._nodes:
            self._nodes.move_to_end(key)
            return True
        if self.max_bytes is not None and nbytes > self.max_bytes:
            self.stats["refused"] += 1
            return False
        node = _HostBlock(key, parent, tenant,
                          [_host_leaf(leaf) for leaf in k_rows],
                          [_host_leaf(leaf) for leaf in v_rows],
                          nbytes)
        self._nodes[key] = node
        self.bytes_used += node.nbytes
        self._tenant_bytes[tenant] = \
            self._tenant_bytes.get(tenant, 0) + node.nbytes
        self.stats["demoted"] += 1
        self.stats["demote_bytes"] += node.nbytes
        if self._ledger is not None:
            self._ledger.host_delta(tenant, node.nbytes, "demote")
        self._evict_to_budget(tenant)
        if key not in self._nodes:      # budget evicted the newcomer
            self.stats["refused"] += 1
            self._publish_gauges(tenant)
            return False
        if self._ledger is not None:
            self._ledger.move(tenant, "demote")
        self._publish_gauges(tenant)
        return True

    def touch(self, key: str) -> None:
        if key in self._nodes:
            self._nodes.move_to_end(key)

    def chain_nodes(self, keys) -> list:
        """Contiguous host-resident run of `keys` from the front —
        the promotable segment (a gap ends it: promotion past a
        missing block could never be longest-matched)."""
        nodes = []
        for key in keys:
            node = self._nodes.get(key)
            if node is None:
                break
            nodes.append(node)
        return nodes

    def pop_promoted(self, keys) -> int:
        """Drop promoted blocks from the host tier (they live on the
        device again); returns bytes released."""
        released = 0
        tenants = set()
        for key in keys:
            node = self._nodes.pop(key, None)
            if node is None:
                continue
            released += node.nbytes
            self._drop_bytes(node)
            tenants.add(node.tenant)
            self.stats["promoted"] += 1
            self.stats["promote_bytes"] += node.nbytes
            if self._ledger is not None:
                self._ledger.host_delta(node.tenant, -node.nbytes,
                                        "promote")
                self._ledger.move(node.tenant, "promote")
        for tenant in tenants:
            self._publish_gauges(tenant)
        return released

    # -- budgets -----------------------------------------------------------
    def _drop_bytes(self, node: _HostBlock) -> None:
        self.bytes_used -= node.nbytes
        remaining = self._tenant_bytes.get(node.tenant, 0) - node.nbytes
        if remaining > 0:
            self._tenant_bytes[node.tenant] = remaining
        else:
            self._tenant_bytes.pop(node.tenant, None)

    def _over_budget(self, tenant: str) -> str | None:
        if self.tenant_max_bytes is not None and \
                self._tenant_bytes.get(tenant, 0) > \
                self.tenant_max_bytes:
            return tenant
        if self.max_bytes is not None and \
                self.bytes_used > self.max_bytes:
            return ""                   # global breach: any tenant
        return None

    def _evict_to_budget(self, tenant: str) -> None:
        # plain LRU from the front — host blocks are terminal (there
        # is no third tier), and a mid-chain eviction only shortens
        # the promotable run, never corrupts it (content-addressed)
        while True:
            scope = self._over_budget(tenant)
            if scope is None:
                return
            victim = None
            for node in self._nodes.values():
                if scope and node.tenant != scope:
                    continue
                victim = node
                break
            if victim is None:
                return
            del self._nodes[victim.key]
            self._drop_bytes(victim)
            self.stats["evicted"] += 1
            if self._ledger is not None:
                self._ledger.host_delta(victim.tenant,
                                        -victim.nbytes, "host_evict")
            self._publish_gauges(victim.tenant)

    def _publish_gauges(self, tenant: str) -> None:
        self._gauge_blocks.set(len(self._nodes))
        gauge = self._tenant_gauges.get(tenant)
        if gauge is None:
            gauge = self._registry.gauge(
                "kv_host_bytes",
                "host-tier resident KV bytes by tenant",
                labels={"store": self.name, "tenant": tenant})
            self._tenant_gauges[tenant] = gauge
        gauge.set(self._tenant_bytes.get(tenant, 0))


class _PromoteJob:
    __slots__ = ("key", "tenant", "keys", "parent", "rows", "stacks",
                 "done")

    def __init__(self, key, tenant, keys, parent, rows):
        self.key = key              # dedup key: first host-tier key
        self.tenant = tenant
        self.keys = keys            # chain keys being promoted
        self.parent = parent        # device-resident parent ("" root)
        self.rows = rows            # [(k_rows, v_rows), ...] captured
        self.stacks = None          # staged (k_layers, v_layers)
        self.done = threading.Event()


class AsyncPromoter:
    """Off-event-loop H2D prefetcher for the host KV tier (ISSUE 17).

    prefetch() captures host row references on the event loop and
    hands them to ONE daemon worker that stacks them per layer and
    device_puts the stacks — the only place pool-shaped host arrays
    cross to the device (the lint-host-transfer seam).  poll() (every
    admit round) and promote_for() (the admit-time sync fallback) run
    back on the loop: allocate pool blocks, scatter the staged stacks
    in, register the chain with insert_block, and drop the host
    copies.  A prompt whose prefetch landed before its admit round
    pays nothing at admit (installs_async); one that races its admit
    waits out the in-flight staging (installs_wait) or stages inline
    (installs_sync) — all three beat the cold re-prefill."""

    def __init__(self, cache, store: HostBlockStore,
                 name: str | None = None, registry=None,
                 wait_s: float = 2.0, max_batch_blocks: int = 16,
                 max_inflight: int = 4):
        self.cache = cache
        self.store = store
        self.name = str(name or f"{store.name}.promote")
        self.wait_s = float(wait_s)
        # staging bounds (ISSUE 19 satellite, ROADMAP item 3 residue
        # d): one prefetch stages at most `max_batch_blocks` blocks
        # and at most `max_inflight` chains stage concurrently — a
        # 100-block history cannot park an admit round behind one
        # whole-chain H2D.  The deferred remainder re-kicks on the
        # next touch/probe (both paths call prefetch again), and the
        # admit-time promote_for fallback stays uncapped: by then the
        # chain is needed NOW, not opportunistically.
        self.max_batch_blocks = max(1, int(max_batch_blocks))
        self.max_inflight = max(1, int(max_inflight))
        self._jobs: dict = {}           # first key -> _PromoteJob
        self._ready: list = []          # staged, awaiting install
        self._lock = Lock(f"{self.name}._ready")
        self._queue: queue.Queue = queue.Queue()
        self._thread = None
        from .observe.metrics import MirroredStats, default_registry
        self._registry = registry or default_registry()
        self.stats = MirroredStats(
            {"kicks": 0, "staged": 0, "installs": 0,
             "installs_async": 0, "installs_sync": 0,
             "installs_wait": 0, "stale": 0},
            metric="kv_promote_events_total",
            help="host-tier KV promotion events by kind",
            registry=self._registry,
            labels={"promoter": self.name})
        self._deferred = self._registry.counter(
            "kv_promote_deferred_total",
            "prefetch blocks deferred by the staging depth cap or "
            "the in-flight chain limit",
            labels={"promoter": self.name})

    # -- event-loop side ---------------------------------------------------
    @property
    def ready(self) -> bool:
        """Cheap hot-path probe: staged promotions are waiting for
        poll() (plain list truthiness — GIL-atomic)."""
        return bool(self._ready)

    def _segment(self, tenant: str, tokens) -> tuple:
        """(keys, device_hit_blocks, host nodes) for the chain's
        promotable tail: the device-resident run first, then the
        host-resident continuation."""
        cache = self.cache
        block = cache.block_tokens
        count = max(0, len(tokens) - 1) // block
        if count == 0 or not len(self.store):
            return [], 0, []
        keys = cache.keys_for(tenant, tokens[:count * block])
        device = 0
        while device < count and cache.has(keys[device]):
            device += 1
        if device >= count:
            return keys, device, []
        return keys, device, self.store.chain_nodes(keys[device:])

    def prefetch(self, tenant: str, tokens) -> int:
        """Kick an async promotion for the host-resident tail of this
        prompt's chain; returns the tokens being promoted (0: nothing
        host-resident, already device-resident, already in flight, or
        deferred by the staging bounds).  Non-blocking — safe from
        admission probes and session touches on the event loop.
        Bounded (ISSUE 19 satellite): at most max_batch_blocks stage
        per kick and max_inflight chains stage concurrently; the
        remainder counts kv_promote_deferred_total and re-kicks on
        the chain's next probe (the leading run is then device-
        resident, so staging resumes exactly where it stopped)."""
        keys, device, nodes = self._segment(tenant, tokens)
        if not nodes:
            return 0
        first = keys[device]
        if first in self._jobs:
            return 0                     # already staging/staged
        if len(self._jobs) >= self.max_inflight:
            self._deferred.inc(len(nodes))
            return 0
        if len(nodes) > self.max_batch_blocks:
            self._deferred.inc(len(nodes) - self.max_batch_blocks)
            nodes = nodes[:self.max_batch_blocks]
        job = _PromoteJob(
            first, str(tenant or "default"),
            keys[device:device + len(nodes)],
            keys[device - 1] if device else "",
            [(node.k_rows, node.v_rows) for node in nodes])
        self._jobs[first] = job
        self._ensure_thread()
        self._queue.put(job)
        self.stats["kicks"] += 1
        return len(nodes) * self.cache.block_tokens

    def poll(self) -> int:
        """Install every staged promotion (event loop only); returns
        tokens landed.  Called at the top of the decoder's admit round
        so a prefetch kicked N rounds ago is a cache hit by the time
        its prompt admits."""
        if not self._ready:
            return 0
        with self._lock:
            jobs, self._ready = self._ready, []
        landed = 0
        for job in jobs:
            landed += self._install(job, kind="installs_async")
        return landed

    def promote_for(self, tenant: str, tokens) -> int:
        """Synchronous admit-time fallback: make the host-resident
        tail of this prompt's chain device-resident NOW.  A staged job
        installs immediately; an in-flight one is waited out (bounded
        by wait_s — still cheaper than the cold re-prefill it
        replaces); no job at all stages inline.  Returns tokens
        promoted."""
        self.poll()
        keys, device, nodes = self._segment(tenant, tokens)
        if not nodes:
            return 0
        job = self._jobs.get(keys[device])
        if job is not None:
            if not job.done.wait(self.wait_s):
                return 0                 # mid-stage: lands next round
            with self._lock:
                if job in self._ready:
                    self._ready.remove(job)
            return self._install(job, kind="installs_wait")
        job = _PromoteJob(
            keys[device], str(tenant or "default"),
            keys[device:device + len(nodes)],
            keys[device - 1] if device else "",
            [(node.k_rows, node.v_rows) for node in nodes])
        self._jobs[job.key] = job
        self._stage(job)
        return self._install(job, kind="installs_sync")

    def _install(self, job: _PromoteJob, kind: str) -> int:
        self._jobs.pop(job.key, None)
        cache = self.cache
        pool = cache.pool
        if pool is None or job.stacks is None:
            self.stats["stale"] += 1
            return 0
        if job.parent and not cache.has(job.parent):
            # the device-resident parent demoted while we staged: an
            # install would land unreachable-by-match blocks — drop;
            # the next kick re-segments from the new boundary
            self.stats["stale"] += 1
            return 0
        skip = 0
        while skip < len(job.keys) and cache.has(job.keys[skip]):
            skip += 1                   # re-prefilled while staging
        keys = job.keys[skip:]
        if not keys:
            self.stats["stale"] += 1
            return 0
        k_layers, v_layers = job.stacks
        if skip:
            k_layers = [_slice_stack(s, skip) for s in k_layers]
            v_layers = [_slice_stack(s, skip) for s in v_layers]
        ids = pool.alloc_blocks(len(keys), tenant=job.tenant)
        pool.write_blocks(ids, k_layers, v_layers)
        parent = job.keys[skip - 1] if skip else job.parent
        installed = 0
        for j, key in enumerate(keys):
            if not cache.insert_block(job.tenant, parent, key,
                                      ids[j]):
                break                   # device budget refused: stop
            parent = key
            installed += 1
        pool.release_blocks(ids, tenant=job.tenant)
        if installed:
            self.store.pop_promoted(keys[:installed])
            cache.stats["promoted"] += installed
            self.stats["installs"] += installed
            self.stats[kind] += installed
        return installed * cache.block_tokens

    # -- worker side -------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name=self.name, daemon=True)
            self._thread.start()

    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                return
            try:
                self._stage(job)
            except Exception:
                self.logger.exception("promotion staging failed")
                job.stacks = None
            with self._lock:
                self._ready.append(job)
            job.done.set()

    def _stage(self, job: _PromoteJob) -> None:
        """Build the per-layer [M, H, B, D] stacks write_blocks wants
        and move them to the device — the H2D half of the prefetcher
        seam, off the event loop when the worker runs it."""
        import jax
        from .serving import _stack_block_leaves
        layers = len(job.rows[0][0])
        job.stacks = (
            [jax.device_put(_stack_block_leaves(
                [rows[0][i] for rows in job.rows]))
             for i in range(layers)],
            [jax.device_put(_stack_block_leaves(
                [rows[1][i] for rows in job.rows]))
             for i in range(layers)])
        self.stats["staged"] += len(job.keys)

    def stop(self) -> None:
        """Drain the worker (idempotent).  In-flight jobs finish
        staging and are dropped unpolled — stop() is a teardown path,
        the store keeps the host copies."""
        thread = self._thread
        if thread is not None and thread.is_alive():
            self._queue.put(None)
            thread.join(timeout=5.0)
        self._thread = None


def _slice_stack(stack, skip: int):
    if isinstance(stack, dict):
        return {"q": stack["q"][skip:], "s": stack["s"][skip:]}
    return stack[skip:]
