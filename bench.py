# benchmark CLI: the console/JSON report is the product, not telemetry
# graft: disable-file=lint-print
# Benchmark: Whisper-small streaming ASR on one chip — PIPELINE level.
#
# The BASELINE.md headline metric is "speech pipeline real-time-factor":
# how many concurrent real-time audio streams one chip sustains at
# <150 ms p50.  The reference wraps faster-whisper on CUDA, single
# stream, tensors serialized through an MQTT broker (reference:
# examples/speech/speech_elements.py:174-250); it publishes no numbers,
# so the implied baseline is 1.0 real-time stream.
#
# Two sections:
#   A. model ladder — batched greedy decode (encoder + KV-cache token
#      scan, bfloat16, flagship Whisper-small geometry) across batch
#      sizes; picks the largest batch meeting the 150 ms p50 budget.
#   B. pipeline measurement — N open-loop REAL-TIME streams (one 5 s
#      chunk per stream per 5 s, staggered) drive the REAL serving path:
#      Pipeline frame walk → PE_LogMel (host cpu) → PE_WhisperASR →
#      BatchingScheduler coalescing → ComputeRuntime (pipelined results:
#      next batch uploads while current computes) → resume.  Reported
#      latency spans frame post to frame completion: batch-formation
#      wait, host marshalling, event loop ticks, and device compute are
#      all inside the measured window.
#
# The reported headline is the PIPELINE number (section B): the largest
# stream count that keeps up with real-time arrivals (no backlog
# growth).  p50 is reported alongside with latency_budget_met — on this
# bench machine the chip sits behind a tunnel with a ~0.3-0.8 s fixed
# per-batch transfer+dispatch cost that host-attached production TPUs do
# not have, so sustained throughput is the tunnel-honest number.
#
# --debug additionally asserts which attention path compiled
# (ops.attention.dispatch_stats): at the 5 s geometry (seq 250) the
# measured-faster XLA path must be taken, the pallas flash kernel only
# at long-sequence geometries (>= 1024); see ops/attention.py for the
# crossover measurements.
#
# Prints ONE JSON line:
#   {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import sys
import time

import numpy as np

import jax

# the axon TPU plugin force-sets jax_platforms at import time, ignoring
# JAX_PLATFORMS env — an explicit config.update is the only override that
# sticks (used by the CPU smoke path: AIKO_BENCH_PLATFORM=cpu)
if os.environ.get("AIKO_BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["AIKO_BENCH_PLATFORM"])

import jax.numpy as jnp

from aiko_services_tpu.models import WhisperConfig, whisper_init
from aiko_services_tpu.models.whisper import WHISPER_PRESETS, greedy_decode

CHUNK_SECONDS = 5.0           # streaming chunk size (audio_io.py-style)
FRAMES_PER_SECOND = 100       # whisper log-mel frame rate
SAMPLE_RATE = 16000
BATCH_LADDER = (8, 16, 24, 32, 48, 64)
LATENCY_BUDGET = 0.150        # north-star p50 bound (BASELINE.md)
MAX_TOKENS = 24               # tokens decoded per 5 s chunk
REPEATS = 8
# env overrides so the harness can smoke-test on CPU (preset=test)
PRESET = os.environ.get("AIKO_BENCH_PRESET", "small")
PIPELINE_SECONDS = float(os.environ.get("AIKO_BENCH_WINDOW", "12"))
# int8 cross-attention KV (layers.quantize_kv) — OFF by default so the
# headline stays apples-to-apples bf16 across rounds.
#   AIKO_BENCH_KV_QUANT=1       per-POSITION scales: memory lever only
#     (the dequant multiply re-materializes per step; measured 512 vs
#     410 ms/round @ batch 256, +25%);
#   AIKO_BENCH_KV_QUANT=tensor  per-BATCH-element scale folded into
#     the softmax scale (r5): the bare convert fuses into the
#     attention dot — measured 352 vs 407 ms/round @ batch 256, −14%
#     (the chip_kv_tensor_* A/B fields carry this in every artifact).
_KV_ENV = os.environ.get("AIKO_BENCH_KV_QUANT", "0").lower()
KV_QUANT = _KV_ENV if _KV_ENV in ("tensor", "position") \
    else _KV_ENV == "1"


def model_config(frames: int) -> WhisperConfig:
    return dataclasses.replace(WHISPER_PRESETS[PRESET],
                               n_audio_ctx=frames // 2,
                               n_text_ctx=MAX_TOKENS + 8,
                               dtype=jnp.bfloat16)


# -- chip efficiency (MFU) ---------------------------------------------------
# Exact program FLOPs come from XLA's own cost model
# (compiled.cost_analysis()), not hand formulas; the assumed peak is the
# public bf16 number for the chip generation actually attached.
PEAK_TFLOPS_BF16 = {
    "TPU v5 lite": 197.0,       # v5e (cloud.google.com/tpu spec sheet)
    "TPU v5e": 197.0,
    "TPU v5": 459.0,            # v5p
    "TPU v4": 275.0,
    "TPU v6 lite": 918.0,       # v6e / Trillium
}


PEAK_HBM_GBPS = {
    "TPU v5 lite": 819.0,       # v5e (cloud.google.com/tpu spec sheet)
    "TPU v5e": 819.0,
    "TPU v5": 2765.0,           # v5p
    "TPU v4": 1228.0,
    "TPU v6 lite": 1640.0,      # v6e / Trillium
}


def device_peak_flops():
    kind = jax.devices()[0].device_kind
    tflops = PEAK_TFLOPS_BF16.get(kind)
    return (tflops * 1e12 if tflops else None), kind


def device_peak_membw():
    gbps = PEAK_HBM_GBPS.get(jax.devices()[0].device_kind)
    return gbps * 1e9 if gbps else None


def compiled_flops(compiled) -> float | None:
    """Total FLOPs of a compiled XLA program, or None when the backend
    does not expose a cost analysis."""
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        flops = float(analysis.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _transient_compile_error(exc: Exception) -> bool:
    """Retry ONLY transport-layer compile failures.  Deterministic
    failures (OOM — which the batch ladders rely on to fail fast —
    shape/tracer errors) must surface immediately."""
    text = repr(exc)
    if "RESOURCE_EXHAUSTED" in text or "ResourceExhausted" in text:
        return False
    if isinstance(exc, (TypeError, ValueError)):
        return False
    return ("remote_compile" in text or "read body" in text or
            "INTERNAL" in text or "UNAVAILABLE" in text)


def compile_with_retry(fn, *args, attempts: int = 3, delay: float = 5.0):
    """lower+compile with retries: the tunnel's remote-compile service
    occasionally drops a response mid-body (transient), which must not
    abort a 20-minute bench run."""
    for attempt in range(attempts):
        try:
            return jax.jit(fn).lower(*args).compile()
        except Exception as exc:
            if attempt == attempts - 1 or \
                    not _transient_compile_error(exc):
                raise
            print(f"compile attempt {attempt + 1} failed ({exc!r}); "
                  f"retrying in {delay:.0f}s", file=sys.stderr)
            time.sleep(delay)


def measure_compiled(compiled, *args, repeats: int = REPEATS,
                     chain: int = 1):
    """p50 of per-call wall time with hard host-transfer sync
    (block_until_ready does not synchronize through the TPU tunnel).

    chain>1 dispatches that many back-to-back rounds per sync — the
    queue-full pattern of continuous serving — so the tunnel's fixed
    ~0.1 s dispatch+sync latency amortizes out of THROUGHPUT numbers.
    Latency numbers must use chain=1."""
    np.asarray(compiled(*args)[0])            # warmup
    times = []
    for _ in range(repeats):
        start = time.perf_counter()
        out = None
        for _ in range(chain):
            out = compiled(*args)
        np.asarray(out[0])
        times.append((time.perf_counter() - start) / chain)
    return statistics.median(times)


def measure_model(config, params, batch: int):
    """(p50 seconds, program FLOPs) for one batched greedy decode."""
    frames = config.n_audio_ctx * 2
    mel = jax.random.normal(jax.random.PRNGKey(1),
                            (batch, frames, config.n_mels), jnp.bfloat16)
    compiled = compile_with_retry(
        lambda params, mel: greedy_decode(
            params, config, mel, max_tokens=MAX_TOKENS,
            kv_quant=KV_QUANT), params, mel)
    return measure_compiled(compiled, params, mel), \
        compiled_flops(compiled)


def model_ladder():
    """Measure decode p50 across the batch ladder.  Returns
    (config, params, {batch: seconds}, (best_model_streams, latency,
    batch), mfu) — the 'best' pick is the model-only number (largest
    batch under the 150 ms budget); the PIPELINE batch is chosen
    separately from these times + the measured per-batch overhead."""
    frames = int(CHUNK_SECONDS * FRAMES_PER_SECOND)
    config = model_config(frames)
    params = whisper_init(jax.random.PRNGKey(0), config)
    times: dict = {}
    flops_by_batch: dict = {}
    best = None                               # (streams, latency, batch)
    for batch in BATCH_LADDER:
        elapsed, flops = measure_model(config, params, batch)
        times[batch] = elapsed
        flops_by_batch[batch] = flops
        streams = batch * CHUNK_SECONDS / elapsed
        if elapsed <= LATENCY_BUDGET and (best is None or
                                          streams > best[0]):
            best = (streams, elapsed, batch)
        if elapsed > 4 * LATENCY_BUDGET:
            break                     # far past any useful ladder point
    if best is None:
        batch = BATCH_LADDER[0]
        best = (batch * CHUNK_SECONDS / times[batch], times[batch], batch)
    peak, _ = device_peak_flops()
    flops = flops_by_batch.get(best[2])
    mfu = (flops / best[1] / peak) if (peak and flops) else None
    return config, params, times, best, mfu


def bench_chip_asr(config, params, batch: int):
    """Device-resident-source variant of the SAME fused program the
    pipeline serves (μ-law uint8 → mel → greedy decode): what the chip
    sustains with the host→device wire out of the picture.  The
    'chip sustains X streams' claim is measured here, not inferred.
    Walks a short batch ladder (bigger batches amortize decode-scan
    overhead); returns the best
    (streams, round_s, mfu, batch, phases)."""
    from aiko_services_tpu.models.whisper import (encode,
                                                  precompute_cross_kv)
    from aiko_services_tpu.ops.audio import (WHISPER_HOP,
                                             log_mel_spectrogram,
                                             mulaw_decode)
    samples = config.n_audio_ctx * 2 * WHISPER_HOP
    peak, _ = device_peak_flops()

    def frontend(pcm):
        audio = mulaw_decode(pcm)
        mel = log_mel_spectrogram(audio, num_mels=config.n_mels)
        return mel.astype(config.dtype)

    def fused(params, pcm):
        return greedy_decode(params, config, frontend(pcm),
                             max_tokens=MAX_TOKENS, kv_quant=KV_QUANT)

    # phase programs return device-side SCALAR reductions: returning
    # the real activations would ship ~100 MB per sync through the
    # tunnel and time the wire, not the phase
    def enc_only(params, pcm):
        return (jnp.sum(encode(params, config, frontend(pcm)),
                        dtype=jnp.float32),)

    def enc_kv(params, pcm):
        audio = encode(params, config, frontend(pcm))
        kv = precompute_cross_kv(params, config, audio,
                                 quantize=KV_QUANT)
        return (sum(jnp.sum(leaf, dtype=jnp.float32)
                    for leaf in jax.tree_util.tree_leaves(kv)),)

    best = None
    for chip_batch in (batch, 2 * batch, 4 * batch):
        try:
            codes = jax.random.randint(
                jax.random.PRNGKey(2), (chip_batch, samples), 0, 256,
                jnp.int32).astype(jnp.uint8)  # resident on device
            compiled = compile_with_retry(fused, params, codes)
            # queue-full throughput (how serving runs): the tunnel's
            # fixed dispatch+sync latency amortizes away
            elapsed = measure_compiled(compiled, params, codes, chain=4)
        except Exception as exc:
            print(f"chip asr batch {chip_batch} failed: {exc!r}",
                  file=sys.stderr)
            break
        flops = compiled_flops(compiled)
        mfu = (flops / elapsed / peak) if (peak and flops) else None
        streams = chip_batch * CHUNK_SECONDS / elapsed
        if best is None or streams > best[0]:
            best = (streams, elapsed, mfu, chip_batch, codes, compiled)
    if best is None:
        raise RuntimeError("no chip ASR rung completed")

    # phase decomposition at the winning batch: where do the non-MFU
    # milliseconds go?  encoder (MXU-bound), cross-KV projection, and
    # the autoregressive decode tail (bandwidth-bound: every token
    # re-reads the decoder weights AND the full cross-KV)
    streams, elapsed, mfu, chip_batch, codes, best_compiled = best
    phases = {}
    try:
        enc_compiled = compile_with_retry(enc_only, params, codes)
        enc_s = measure_compiled(enc_compiled, params, codes, chain=4)
        enc_flops = compiled_flops(enc_compiled)
        kv_compiled = compile_with_retry(enc_kv, params, codes)
        kv_s = measure_compiled(kv_compiled, params, codes, chain=4)
        phases = {
            "chip_encoder_ms": round(enc_s * 1000.0, 1),
            "chip_cross_kv_ms": round(max(0.0, kv_s - enc_s) * 1000.0,
                                      1),
            "chip_decode_tail_ms": round(max(0.0, elapsed - kv_s) *
                                         1000.0, 1),
        }
        if peak and enc_flops:
            phases["chip_encoder_mfu"] = round(enc_flops / enc_s / peak,
                                               4)
        del enc_compiled, kv_compiled
    except Exception as exc:
        print(f"chip asr phase split failed: {exc!r}", file=sys.stderr)

    # decode-tail bytes-per-step model (r4 verdict item 3 — the same
    # arithmetic the llama section carries): every greedy token re-reads
    # the decoder weight set and the full cross-KV.  At spec HBM
    # bandwidth that is the tail's floor; reported next to the measured
    # tail so bandwidth-bound is a checkable claim, not a shrug.
    membw = device_peak_membw()
    if membw:
        itemsize = jnp.dtype(config.dtype).itemsize
        dec_weight_bytes = int(sum(
            int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            for path, leaf in jax.tree_util.tree_leaves_with_path(params)
            if any(k in str(path[0]) for k in
                   ("dec_blocks", "ln_dec", "tok_embed"))))
        kv_itemsize = 1 if KV_QUANT else itemsize
        cross_kv_bytes = (chip_batch * config.dec_layers * 2 *
                          config.n_audio_ctx * config.dim * kv_itemsize)
        self_kv_bytes = (chip_batch * config.dec_layers * 2 *
                         config.n_text_ctx * config.dim * itemsize)
        step_bytes = dec_weight_bytes + cross_kv_bytes + self_kv_bytes
        tail_roofline_ms = MAX_TOKENS * step_bytes / membw * 1000.0
        phases |= {
            "chip_tail_step_gb": round(step_bytes / 1e9, 3),
            "chip_decode_tail_roofline_ms": round(tail_roofline_ms, 1),
        }
        if "chip_decode_tail_ms" in phases:
            phases["chip_tail_hbm_bw_util"] = round(
                tail_roofline_ms / max(phases["chip_decode_tail_ms"],
                                       1e-9), 3)

    # int8 cross-KV A/B at the winning batch: throughput delta +
    # greedy-token parity vs the shipping bf16 program, for BOTH int8
    # modes (layers.quantize_kv).  Measured r5 @ batch 256:
    #   "position" (per-position scales): +25% round time — the
    #     dequant multiply re-materializes per step; memory lever only;
    #   "tensor" (per-batch scale folded into the softmax scale):
    #     −14% round time / +16% streams — the bare convert fuses
    #     into the attention dot, so the tail streams half the bytes.
    # Token match 0.82-0.87 on RANDOM weights (both modes) is greedy
    # divergence cascade — a near-tie argmax flips under the ±0.4%
    # quantization error and rewrites the suffix; the match-rate
    # floor is gated in
    # tests/test_speech_quality.py::test_kv_quant_tensor_parity.
    if KV_QUANT:
        # base program already quantized: the delta labels would be
        # nonsense (and the base decode round would be wasted work)
        return streams, elapsed, mfu, chip_batch, phases
    try:
        base_tokens, base_lengths = [
            np.asarray(x)
            for x in best_compiled(params, codes)[:2]]
        for mode, tag in (("position", "chip_kv_quant"),
                          ("tensor", "chip_kv_tensor")):

            def fused_alt(params, pcm, mode=mode):
                return greedy_decode(params, config, frontend(pcm),
                                     max_tokens=MAX_TOKENS,
                                     kv_quant=mode)

            alt_compiled = compile_with_retry(fused_alt, params, codes)
            alt_elapsed = measure_compiled(alt_compiled, params, codes,
                                           chain=4)
            alt_tokens, alt_lengths = [
                np.asarray(x) for x in alt_compiled(params, codes)[:2]]
            valid = np.arange(base_tokens.shape[1])[None, :] < \
                np.minimum(base_lengths, alt_lengths)[:, None]
            match = float((base_tokens == alt_tokens)[valid].mean()) \
                if valid.any() else 1.0
            phases |= {
                f"{tag}_round_ms": round(alt_elapsed * 1000.0, 1),
                f"{tag}_token_match": round(match, 4),
                f"{tag}_delta": round(
                    (alt_elapsed - elapsed) / elapsed, 3),
            }
            if tag == "chip_kv_tensor":
                phases[f"{tag}_streams"] = round(
                    chip_batch * CHUNK_SECONDS / alt_elapsed, 1)
            del alt_compiled
    except Exception as exc:
        print(f"chip kv_quant A/B failed: {exc!r}", file=sys.stderr)
    return streams, elapsed, mfu, chip_batch, phases


_FRONTENDS = ("audio", "mel")
# audio: raw f32 audio ships to the device, mel fused into the decode
#   program (host does nothing per frame) — more wire bytes;
# mel: host computes the log-mel per frame (4× fewer wire bytes, but a
#   serial ~tens-of-ms host cost per item that caps throughput).
# Which wins depends on the machine (tunnel bandwidth vs host CPU), so
# the bench probes both and keeps the faster.


class PE_BenchAudioSource:
    """Source element: emits a fixed synthetic chunk per frame (host
    memory only — generation cost is negligible, as a real mic ring
    buffer's would be).  Chunk length comes from the class attribute so
    the latency section can run a sub-second variant (subclass via
    make_audio_source)."""

    chunk_seconds = CHUNK_SECONDS

    def __init__(self, runtime, name, definition, pipeline=None):
        self.name = name
        self.definition = definition
        rng = np.random.default_rng(0)
        self._chunk = (0.1 * rng.standard_normal(
            int(self.chunk_seconds * SAMPLE_RATE))).astype(np.float32)

    def start_stream(self, stream) -> None:
        pass

    def stop_stream(self, stream) -> None:
        pass

    def process_frame(self, frame, **_):
        from aiko_services_tpu.pipeline import FrameOutput
        return FrameOutput(True, {"audio": self._chunk})


def make_audio_source(chunk_s: float):
    return type("PE_BenchAudioSource", (PE_BenchAudioSource,),
                {"chunk_seconds": chunk_s})


class PE_BenchWireSource:
    """Source element for the WIRE rung: emits a fixed chunk PRE-ENCODED
    as µ-law uint8 codes (a real mic ingest element encodes once at
    capture).  The codes ship inside the binary wire envelope untouched
    (zero-copy), and PE_WhisperASR's collate passes uint8 straight into
    the device batch — no per-frame transcode anywhere on the host."""

    chunk_seconds = CHUNK_SECONDS

    def __init__(self, runtime, name, definition, pipeline=None):
        from aiko_services_tpu.ops.audio import mulaw_encode
        self.name = name
        self.definition = definition
        rng = np.random.default_rng(0)
        audio = (0.1 * rng.standard_normal(
            int(self.chunk_seconds * SAMPLE_RATE))).astype(np.float32)
        self._chunk = mulaw_encode(audio)          # uint8, encoded ONCE

    def start_stream(self, stream) -> None:
        pass

    def stop_stream(self, stream) -> None:
        pass

    def process_frame(self, frame, **_):
        from aiko_services_tpu.pipeline import FrameOutput
        return FrameOutput(True, {"audio": self._chunk})


def make_wire_source(chunk_s: float):
    return type("PE_BenchWireSource", (PE_BenchWireSource,),
                {"chunk_seconds": chunk_s})


def pipeline_definition(batch: int, frontend: str = "mel",
                        max_wait: float = 0.1,
                        chunk_seconds: float = CHUNK_SECONDS,
                        max_tokens: int = MAX_TOKENS,
                        deadline_ms: float = 0.0):
    frames = int(chunk_seconds * FRAMES_PER_SECOND)
    parameters = {
        "PE_WhisperASR.preset": PRESET,
        "PE_WhisperASR.mode": "batched",
        "PE_WhisperASR.pipelined": True,
        "PE_WhisperASR.max_tokens": max_tokens,
        "PE_WhisperASR.buckets": [frames],
        "PE_WhisperASR.max_batch": batch,
        "PE_WhisperASR.deadline_ms": deadline_ms,
        "PE_WhisperASR.kv_quant": KV_QUANT,
        # pad_batch means the device ALWAYS runs the full batch shape —
        # firing sparse batches wastes lanes, so the wait is tuned to
        # roughly one device round (latency here is tunnel-dominated
        # anyway; see measure/bench_pipeline)
        "PE_WhisperASR.max_wait": max_wait,
        "PE_WhisperASR.max_in_flight": DEPTH,
    }
    if frontend == "audio":
        # mel fused into the device program: zero host work per frame;
        # μ-law wire opt-in (element default is lossless int16) — the
        # tunnel is the bottleneck here and halving bytes wins
        parameters["PE_WhisperASR.frontend"] = "audio"
        parameters["PE_WhisperASR.wire"] = "mulaw"
        return {
            "version": 0, "name": "p_bench", "runtime": "jax",
            "graph": ["(PE_BenchAudioSource (PE_WhisperASR))"],
            "parameters": parameters,
            "elements": [
                {"name": "PE_BenchAudioSource", "input": [],
                 "output": [{"name": "audio"}]},
                {"name": "PE_WhisperASR", "input": [{"name": "audio"}],
                 "output": [{"name": "tokens"}, {"name": "text"}]},
            ],
        }
    parameters["PE_LogMel.device"] = "cpu"
    return {
        "version": 0, "name": "p_bench", "runtime": "jax",
        "graph": ["(PE_BenchAudioSource (PE_LogMel (PE_WhisperASR)))"],
        "parameters": parameters,
        "elements": [
            {"name": "PE_BenchAudioSource", "input": [],
             "output": [{"name": "audio"}]},
            {"name": "PE_LogMel", "input": [{"name": "audio"}],
             "output": [{"name": "mel"}]},
            {"name": "PE_WhisperASR", "input": [{"name": "mel"}],
             "output": [{"name": "tokens"}, {"name": "text"}]},
        ],
    }


class PipelineBench:
    """Open-loop real-time load generator over the full serving path.

    Each of N streams posts one 5 s chunk every 5 s (staggered phases) —
    the arrival pattern the metric names, NOT a closed saturation loop.
    A configuration "sustains" N streams when every posted frame
    completes inside the window (no backlog growth) with p50 latency
    under budget; latency spans frame post → frame completion."""

    def __init__(self, batch: int, frontend: str = "mel",
                 max_wait: float = 0.1,
                 chunk_seconds: float = CHUNK_SECONDS,
                 max_tokens: int = MAX_TOKENS,
                 deadline_ms: float = 0.0):
        from aiko_services_tpu.compute import ComputeRuntime
        from aiko_services_tpu.event import EventEngine
        from aiko_services_tpu.pipeline import Pipeline, \
            parse_pipeline_definition
        from aiko_services_tpu.process import ProcessRuntime
        from aiko_services_tpu.transport.memory import (MemoryBroker,
                                                        MemoryMessage)

        self.chunk_seconds = chunk_seconds

        self.engine = EventEngine()           # real clock
        broker = MemoryBroker()

        def transport_factory(on_message, lwt_topic, lwt_payload,
                              lwt_retain):
            return MemoryMessage(
                on_message=on_message, broker=broker,
                lwt_topic=lwt_topic, lwt_payload=lwt_payload,
                lwt_retain=lwt_retain)

        self.runtime = ProcessRuntime(name="bench", engine=self.engine,
                                      transport_factory=transport_factory)
        self.runtime.initialize()
        self.compute = ComputeRuntime(self.runtime, "compute")
        self.pipeline = Pipeline(
            self.runtime,
            parse_pipeline_definition(
                pipeline_definition(batch, frontend, max_wait,
                                    chunk_seconds, max_tokens,
                                    deadline_ms)),
            stream_lease_time=0,
            element_classes={
                "PE_BenchAudioSource": make_audio_source(chunk_seconds)})
        self.pipeline.add_frame_handler(self._on_frame)
        self._init_load_accounting()

    def _init_load_accounting(self) -> None:
        # per-stream FIFO of post times: frames of one stream complete in
        # order, so popleft pairs each completion with its own post even
        # when several frames of a stream are in flight.  Shared by the
        # wire-mode subclass so both rungs measure identically.
        import collections

        from aiko_services_tpu.observe import default_registry
        self._post_times = collections.defaultdict(collections.deque)
        self._latencies: list[float] = []
        self._posted = 0
        self._completed = 0
        # mergeable round-latency sketch (ISSUE 12): the same post →
        # completion wall the _latencies list keeps, in the fleet-
        # aggregatable form — lat_wire_round_* percentiles derive from
        # it, exemplar ids name the worst rounds' streams
        self.round_sketch = default_registry().sketch(
            "wire_round_seconds",
            "bench frame post -> completion wall (mergeable sketch)",
            labels={"bench": "wire"})

    def round_sketch_quantiles(self) -> dict:
        """{p50_ms, p95_ms} of the CURRENT rung's sketch (callers
        clear() it at rung boundaries, like recent_waits)."""
        out = {}
        for q, suffix in ((0.5, "p50_ms"), (0.95, "p95_ms")):
            value = self.round_sketch.quantile(q)
            out[suffix] = None if value is None else value * 1000.0
        return out

    def _ensure_streams(self, n: int) -> None:
        # membership check, not a high-water counter: a transient
        # tunnel failure destroys a stream (per-stream failure
        # isolation), and every later rung would silently post into
        # the void — the constant-192-lost-frames ladder collapse
        for i in range(n):
            if f"s{i}" not in self.pipeline.streams:
                # tenant tag rides the wire on remote hops (ISSUE 9):
                # the serving gate's admission counters label by it
                self.pipeline.create_stream(
                    f"s{i}", lease_time=0,
                    parameters={"tenant": "bench", "tier": 1})

    def _post(self, stream_id: str) -> None:
        self._post_times[stream_id].append(time.perf_counter())
        self._posted += 1
        self.pipeline.post("process_frame", stream_id, {})

    def _on_frame(self, frame) -> None:
        queue = self._post_times[frame.stream_id]
        if queue:
            elapsed = time.perf_counter() - queue.popleft()
            self._latencies.append(elapsed)
            self.round_sketch.observe(elapsed,
                                      exemplar=frame.stream_id)
        self._completed += 1

    def warmup(self, batch: int) -> None:
        """Compile the device program (first batch) before measuring."""
        self._ensure_streams(batch)
        for i in range(batch):
            self._post(f"s{i}")
        self.engine.run_until(lambda: self._completed >= batch,
                              timeout=600.0)

    def measure_round(self, batch: int, repeats: int = 3) -> float:
        """Median wall time for one full batch through the pipeline
        (frame walk + mel + marshalling + device + sync) — the per-batch
        cost including the fixed tunnel/dispatch overhead."""
        times = []
        for _ in range(repeats):
            before = self._completed
            start = time.perf_counter()
            for i in range(batch):
                self._post(f"s{i}")
            self.engine.run_until(
                lambda: self._completed >= before + batch, timeout=600.0)
            times.append(time.perf_counter() - start)
        return statistics.median(times)

    def measure(self, n_streams: int, window: float,
                drain_budget: float = 2.0):
        """Run N real-time streams for `window` seconds.  Returns
        (completed_ok, p50, frames, mean_batch_size)."""
        import heapq as _heapq

        self._ensure_streams(n_streams)
        self._latencies.clear()
        # a frame dropped in an earlier rung would permanently shift a
        # stream's post/completion FIFO pairing — start each rung clean
        self._post_times.clear()
        posted_before, completed_before = self._posted, self._completed

        start = time.perf_counter()
        chunk_s = self.chunk_seconds
        due = [(start + i * chunk_s / n_streams, f"s{i}")
               for i in range(n_streams)]
        _heapq.heapify(due)
        deadline = start + window

        def pump() -> None:
            now = time.perf_counter()
            while due and due[0][0] <= now:
                when, sid = _heapq.heappop(due)
                self._post(sid)
                if when + chunk_s < deadline:
                    _heapq.heappush(due, (when + chunk_s, sid))

        timer = self.engine.add_timer_handler(pump, 0.005)
        try:
            self.engine.run_until(
                lambda: time.perf_counter() >= deadline, timeout=window + 30)
            drain_started = time.perf_counter()
            # hard drain between rungs so backlog never bleeds into the
            # next measurement — judged on THIS RUNG'S deltas (frames
            # lost in an earlier rung must not poison this one), and
            # frames KILLED by a transient tunnel failure never
            # complete: stop waiting when completions make no progress
            # instead of burning the full timeout every rung
            def rung_drained():
                return (self._completed - completed_before >=
                        self._posted - posted_before)

            progress = [self._completed, time.perf_counter()]

            def drained_or_stalled():
                if rung_drained():
                    return True
                if self._completed > progress[0]:
                    progress[0] = self._completed
                    progress[1] = time.perf_counter()
                return time.perf_counter() - progress[1] > 20.0

            self.engine.run_until(drained_or_stalled, timeout=180.0)
            drained = rung_drained()
            if not drained:
                print(f"rung n={n_streams}: "
                      f"{(self._posted - posted_before) - (self._completed - completed_before)}"
                      f" frames lost (transient element failures)",
                      file=sys.stderr)
        finally:
            self.engine.remove_timer_handler(timer)

        drain_time = time.perf_counter() - drain_started
        self.last_drained = drained      # retry policy: transient-or-not
        frames = self._completed - completed_before
        posted = self._posted - posted_before
        program = self.compute.programs["whisper_asr.PE_WhisperASR"]
        p50 = statistics.median(self._latencies) if self._latencies \
            else float("inf")
        ordered = sorted(self._latencies) or [float("inf")]
        print(f"rung n={n_streams}: posted={posted} done={frames} "
              f"p50={p50:.2f}s p90={ordered[int(0.9 * (len(ordered)-1))]:.2f}s "
              f"drain={drain_time:.1f}s "
              f"batches={program.scheduler.stats['batches']}",
              file=sys.stderr)
        # sustained = kept up with real-time arrivals: everything drained
        # promptly (small residual at deadline is the last batches in
        # flight, not a growing backlog)
        keeping_up = drained and drain_time <= drain_budget
        return keeping_up, p50, frames, \
            program.scheduler.mean_batch_size()


def bench_pipeline(bench, capacity: float, drain_budget: float = 2.0):
    """Find the largest stream count the pipeline sustains (keeps up with
    real-time arrivals, no backlog growth).  Returns
    (streams_sustained, p50, frames, mean_batch, verified).

    The p50 budget is reported, not gated here: this bench machine
    reaches the chip over a tunnel with a ~0.3-0.8 s fixed
    transfer+dispatch cost per batch, a latency floor that production
    host-attached TPUs do not have; sustained throughput is
    tunnel-honest, absolute p50 is not."""
    last = None
    attempts: dict = {}
    # the ladder starts well ABOVE the serial floor: depth-4 overlap
    # hides most of the wire, so sustained capacity routinely beats the
    # serial estimate (r4: the old 1.5x top rung passed on its first
    # attempt — the ladder was the binding constraint, not the chip)
    for fraction in (2.2, 1.85, 1.5, 1.25, 1.05, 0.9, 0.75, 0.6, 0.45):
        n = max(1, int(capacity * fraction))
        attempts[n] = attempts.get(n, 0) + 1
        ok, p50, frames, mean_batch = bench.measure(
            n, PIPELINE_SECONDS, drain_budget=drain_budget)
        if not ok and fraction <= 1.05 and bench.last_drained:
            # transient-looking failure (backlog DID drain, just late)
            # at a plausibly-sustainable rung: 12 s windows are short
            # enough that one tunnel stall fails a rung the chip
            # sustains.  A pass after a failure must be shown TWICE —
            # a single lucky window must not set the headline.
            print(f"rung n={n}: transient-looking failure, re-testing",
                  file=sys.stderr)
            attempts[n] += 1
            ok, *_ = bench.measure(n, PIPELINE_SECONDS,
                                   drain_budget=drain_budget)
            if ok:
                attempts[n] += 1
                ok, p50, frames, mean_batch = bench.measure(
                    n, PIPELINE_SECONDS, drain_budget=drain_budget)
        if ok:
            return n, p50, frames, mean_batch, True, attempts
        last = (n, p50, frames, mean_batch, False, attempts)
    return last


class WirePipelineBench(PipelineBench):
    """PipelineBench whose frames cross a REAL pub/sub wire (ISSUE 2).

    Two ProcessRuntimes on one indexed MemoryBroker: a caller pipeline
    (source -> remote ASR hop) and a serving pipeline (PE_WhisperASR ->
    BatchingScheduler -> device).  Every frame ships as a binary wire
    envelope (transport/wire.py): µ-law uint8 codes ride out-of-band
    zero-copy, bursts bound for the serving pipeline coalesce into one
    envelope per engine turn, and replies (tokens) coalesce back the
    same way.  Latency spans caller frame post -> reply merged, so
    lat_wire_* measures the full wire path directly — the same
    open-loop real-time arrival methodology as PipelineBench."""

    def __init__(self, batch: int, max_wait: float = 0.1,
                 chunk_seconds: float = CHUNK_SECONDS,
                 max_tokens: int = MAX_TOKENS,
                 deadline_ms: float = 0.0, coalesce_frames: int = 32,
                 depth: int = 0, peer: bool = True):
        from aiko_services_tpu.compute import ComputeRuntime
        from aiko_services_tpu.event import EventEngine
        from aiko_services_tpu.pipeline import Pipeline, \
            parse_pipeline_definition
        from aiko_services_tpu.process import ProcessRuntime
        from aiko_services_tpu.registrar import Registrar
        from aiko_services_tpu.share import ServicesCache
        from aiko_services_tpu.transport.memory import (MemoryBroker,
                                                        MemoryMessage)

        self.chunk_seconds = chunk_seconds
        depth = depth or DEPTH        # module constant defined below
        self.engine = EventEngine()           # real clock
        broker = MemoryBroker()

        def transport_factory(on_message, lwt_topic, lwt_payload,
                              lwt_retain):
            return MemoryMessage(
                on_message=on_message, broker=broker,
                lwt_topic=lwt_topic, lwt_payload=lwt_payload,
                lwt_retain=lwt_retain)

        def make_rt(name):
            return ProcessRuntime(
                name=name, engine=self.engine,
                transport_factory=transport_factory).initialize()

        Registrar(make_rt("bench_reg"))

        serve_rt = make_rt("bench_serve")
        self.runtime = serve_rt
        if peer:
            # peer data plane (ISSUE 6): data envelopes bypass the
            # broker over a registrar-negotiated direct channel; the
            # broker keeps discovery/control only.  peer=False A/Bs the
            # broker-only path at the same stream count.
            serve_rt.enable_peer()
        self.compute = ComputeRuntime(serve_rt, "compute")
        frames = int(chunk_seconds * FRAMES_PER_SECOND)
        serving_def = parse_pipeline_definition({
            "version": 0, "name": "p_bench_serve", "runtime": "jax",
            "graph": ["(PE_WhisperASR)"],
            "parameters": {
                "PE_WhisperASR.preset": PRESET,
                "PE_WhisperASR.mode": "batched",
                "PE_WhisperASR.pipelined": True,
                "PE_WhisperASR.max_tokens": max_tokens,
                "PE_WhisperASR.buckets": [frames],
                "PE_WhisperASR.max_batch": batch,
                "PE_WhisperASR.deadline_ms": deadline_ms,
                "PE_WhisperASR.kv_quant": KV_QUANT,
                "PE_WhisperASR.max_wait": max_wait,
                "PE_WhisperASR.max_in_flight": depth,
                # the source pre-encodes µ-law once; collate passes the
                # uint8 codes straight through to the device batch
                "PE_WhisperASR.frontend": "audio",
                "PE_WhisperASR.wire": "mulaw",
            },
            "elements": [
                {"name": "PE_WhisperASR", "input": [{"name": "audio"}],
                 "output": [{"name": "tokens"}]},
            ],
        })
        # overload-control plane (ISSUE 9): the serving pipeline runs
        # behind a LIVE AdmissionGate — its wait estimator reads the
        # batch scheduler's EWMA+occupancy estimate (estimated_wait),
        # every frame passes the per-tenant DRR queue (caller streams
        # are tagged tenant="bench"), and admission_* counters ride the
        # rung fields.  Shed-early only bites when frames carry an
        # end-to-end deadline: AIKO_BENCH_WIRE_DEADLINE_S > 0 opts the
        # caller in (default off, keeping rung comparability with r05).
        from aiko_services_tpu.ops.admission import AdmissionGate

        def _scheduler_wait():
            waits = [program.scheduler.estimated_wait()
                     for program in self.compute.programs.values()
                     if program.scheduler is not None]
            waits = [w for w in waits if w is not None]
            return max(waits) if waits else None

        self.admission = AdmissionGate(
            inflight_limit=max(4 * batch, 64),
            metrics_labels={"pipeline": "p_bench_serve"})
        self.admission.add_wait_estimator(_scheduler_wait)
        self.serving = Pipeline(serve_rt, serving_def,
                                stream_lease_time=0,
                                auto_create_streams=True,
                                admission=self.admission)

        call_rt = make_rt("bench_call")
        if peer:
            call_rt.enable_peer()
        caller_def = parse_pipeline_definition({
            "version": 0, "name": "p_bench_call", "runtime": "jax",
            "graph": ["(PE_BenchWireSource (asr))"],
            "elements": [
                {"name": "PE_BenchWireSource", "input": [],
                 "output": [{"name": "audio"}]},
                {"name": "asr", "input": [{"name": "audio"}],
                 "output": [{"name": "tokens"}],
                 "deploy": {"remote": {"service_filter":
                                       {"name": "p_bench_serve"}}}},
            ],
        })
        wire_deadline = float(os.environ.get(
            "AIKO_BENCH_WIRE_DEADLINE_S", "0"))
        self.pipeline = Pipeline(
            call_rt, caller_def, stream_lease_time=0,
            element_classes={
                "PE_BenchWireSource": make_wire_source(chunk_seconds)},
            services_cache=ServicesCache(call_rt),
            # hops must survive the first-batch device compile
            remote_timeout=900.0, coalesce_frames=coalesce_frames,
            frame_deadline=wire_deadline)
        self.pipeline.add_frame_handler(self._on_frame)

        self._broker = broker
        self._call_rt = call_rt
        # retained metrics snapshots on {topic_path}/0/metrics for BOTH
        # bench runtimes (ISSUE 7 satellite, closing the PR 5
        # follow-up): a TPU bench run leaves the registry's last state
        # behind on the control plane, so post-hoc analysis can read
        # counters the JSON artifact does not carry
        from aiko_services_tpu.observe import MetricsPublisher
        self.metrics_publishers = [
            # seeded interval jitter (ISSUE 12): a scaled fleet's
            # retained-snapshot publishes must not synchronize into
            # periodic broker bursts
            MetricsPublisher(serve_rt, interval=2.0, jitter=0.2),
            MetricsPublisher(call_rt, interval=2.0, jitter=0.2),
        ]
        # envelope accounting now comes from the metrics registry
        # (ISSUE 5): the SAME pipeline_wire_envelopes_total /
        # pipeline_wire_frames_total / pipeline_recovery_total counters
        # the runtime increments, read per rung via wire_counters() —
        # no publish monkeypatching, and retries are visible too
        self._init_load_accounting()
        if not self.engine.run_until(
                self.pipeline.remote_elements_ready, timeout=30.0):
            raise RuntimeError(
                "wire bench: remote ASR element never discovered")

    def wire_counters(self) -> dict:
        """Snapshot of the caller pipeline's wire telemetry from the
        process metrics registry: request envelopes/frames and retry
        count — cumulative, so rungs diff before/after."""
        from aiko_services_tpu.observe import default_registry
        registry = default_registry()
        caller = self.pipeline.name
        return {
            "envelopes": registry.value(
                "pipeline_wire_envelopes_total",
                {"pipeline": caller, "direction": "request"}),
            "frames": registry.value(
                "pipeline_wire_frames_total",
                {"pipeline": caller, "direction": "request"}),
            "retries": registry.value(
                "pipeline_recovery_total",
                {"pipeline": caller, "kind": "retries"}),
            # the control/data split made measurable (ISSUE 6): peer
            # channel envelopes vs messages the broker still routed —
            # in steady state the broker count stays flat while the
            # peer counter carries the data plane
            "peer_sent": registry.value("peer_events_total",
                                        {"kind": "sent"}),
            "broker_routed": self._broker.stats["routed"],
            # overload-control verdicts (ISSUE 9): per-tenant counters
            # summed across the serving gate's series — shed/rejected
            # stay 0 unless AIKO_BENCH_WIRE_DEADLINE_S arms shed-early
            "admitted": sum(
                m.value for labels, m in registry.series(
                    "admission_admitted_total")
                if labels.get("pipeline") == "p_bench_serve"),
            "shed": sum(
                m.value for labels, m in registry.series(
                    "admission_shed_total")
                if labels.get("pipeline") == "p_bench_serve"),
            "rejected": sum(
                m.value for labels, m in registry.series(
                    "admission_rejected_total")
                if labels.get("pipeline") == "p_bench_serve"),
        }

    def peer_pinned(self) -> bool:
        peer_host = getattr(self._call_rt, "peer", None)
        return peer_host is not None and \
            peer_host.pinned(f"{self.serving.topic_path}/in")


class PE_BenchImageSource:
    """Source element: a fixed synthetic camera frame per pipeline frame
    (BASELINE config 4's gstreamer ingest stand-in: ingest cost on this
    machine is negligible next to the device hop)."""

    def __init__(self, runtime, name, definition, pipeline=None):
        self.name = name
        self.definition = definition
        rng = np.random.default_rng(7)
        self._image = rng.integers(0, 255, (DETECT_IMAGE, DETECT_IMAGE, 3),
                                   dtype=np.uint8)

    def start_stream(self, stream) -> None:
        pass

    def stop_stream(self, stream) -> None:
        pass

    def process_frame(self, frame, **_):
        from aiko_services_tpu.pipeline import FrameOutput
        return FrameOutput(True, {"image": self._image})


DETECT_IMAGE = 256
DETECT_PRESET = os.environ.get("AIKO_BENCH_DETECT_PRESET", "detector_r18")
DETECT_BATCH = 32
DETECT_WIRE = os.environ.get("AIKO_BENCH_DETECT_WIRE", "dct8")
DETECT_FRAMES = int(os.environ.get("AIKO_BENCH_DETECT_FRAMES", "512"))
# in-flight rounds during the pipeline detect bench (uploads of rounds
# k+1..k+d cover round k's compute + result sync on thin links)
DEPTH = int(os.environ.get("AIKO_BENCH_DEPTH", "4"))


def bench_detect_device():
    """Device-resident detect: the same uint8→normalize→detect program
    PE_Detect serves, input already on device, queue kept full.  Walks
    a batch ladder (the round time is fixed-cost dominated, so bigger
    batches are near-free) and returns (best_fps, mfu, best_batch)."""
    from aiko_services_tpu.models.detector import (
        DETECTOR_PRESETS, detect, detector_init)
    config = DETECTOR_PRESETS[DETECT_PRESET]
    params = detector_init(jax.random.PRNGKey(0), config)
    peak, _ = device_peak_flops()
    best = (0.0, None, 0)
    for batch in (DETECT_BATCH, 4 * DETECT_BATCH, 8 * DETECT_BATCH):
        images = jax.random.randint(
            jax.random.PRNGKey(3), (batch, DETECT_IMAGE,
                                    DETECT_IMAGE, 3), 0, 256,
            jnp.int32).astype(jnp.uint8)

        def forward(params, raw):
            return detect(params, config=config,
                          images=raw.astype(jnp.float32) / 255.0,
                          score_threshold=0.3)

        compiled = compile_with_retry(forward, params, images)
        elapsed = measure_compiled(compiled, params, images, chain=8)
        flops = compiled_flops(compiled)
        mfu = (flops / elapsed / peak) if (peak and flops) else None
        fps = batch / elapsed
        if fps > best[0]:
            best = (fps, mfu, batch)
    return best


def bench_detect():
    """BASELINE's second headline: video → PE_Detect → PE_Tracker
    frames/sec/chip.  Saturation throughput: DETECT_FRAMES frames pushed
    through the batched detector as fast as they complete."""
    from aiko_services_tpu.compute import ComputeRuntime
    from aiko_services_tpu.event import EventEngine
    from aiko_services_tpu.pipeline import Pipeline, \
        parse_pipeline_definition
    from aiko_services_tpu.process import ProcessRuntime
    from aiko_services_tpu.transport.memory import (MemoryBroker,
                                                    MemoryMessage)

    engine = EventEngine()
    broker = MemoryBroker()

    def transport_factory(on_message, lwt_topic, lwt_payload, lwt_retain):
        return MemoryMessage(on_message=on_message, broker=broker,
                             lwt_topic=lwt_topic, lwt_payload=lwt_payload,
                             lwt_retain=lwt_retain)

    runtime = ProcessRuntime(name="bench_detect", engine=engine,
                             transport_factory=transport_factory)
    runtime.initialize()
    ComputeRuntime(runtime, "compute")
    definition = parse_pipeline_definition({
        "version": 0, "name": "p_detect", "runtime": "jax",
        "graph": ["(PE_BenchImageSource (PE_Detect (PE_Tracker)))"],
        "parameters": {
            "PE_Detect.preset": DETECT_PRESET,
            "PE_Detect.image_size": DETECT_IMAGE,
            "PE_Detect.max_batch": DETECT_BATCH,
            "PE_Detect.pipelined": True,
            "PE_Detect.max_wait": 0.05,
            "PE_Detect.max_in_flight": DEPTH,
            # DCT wire: 4x fewer bytes over the tunnel (the r03 detect
            # number was wire-bound at raw uint8; opt-in like mu-law)
            "PE_Detect.wire": DETECT_WIRE,
        },
        "elements": [
            {"name": "PE_BenchImageSource", "input": [],
             "output": [{"name": "image"}]},
            {"name": "PE_Detect", "input": [{"name": "image"}],
             "output": [{"name": "boxes"}, {"name": "scores"},
                        {"name": "classes"}]},
            {"name": "PE_Tracker", "input": [{"name": "boxes"}],
             "output": [{"name": "tracks"}]},
        ],
    })
    pipeline = Pipeline(runtime, definition, stream_lease_time=0,
                        element_classes={
                            "PE_BenchImageSource": PE_BenchImageSource})
    completed = [0]
    pipeline.add_frame_handler(lambda frame: completed.__setitem__(
        0, completed[0] + 1))
    streams = DETECT_BATCH
    for i in range(streams):
        pipeline.create_stream(f"v{i}", lease_time=0)

    def post_round():
        for i in range(streams):
            pipeline.post("process_frame", f"v{i}", {})

    post_round()                                  # warmup batch: compile
    engine.run_until(lambda: completed[0] >= streams, timeout=600.0)

    completed[0] = 0
    target = DETECT_FRAMES

    # closed loop at DEPTH rounds in flight: uploads of rounds k+1..k+d
    # cover round k's compute + result sync
    posted = [0]

    def pump() -> None:
        while posted[0] < target and \
                posted[0] - completed[0] < DEPTH * streams:
            post_round()
            posted[0] += streams

    timer = engine.add_timer_handler(pump, 0.002)
    start = time.perf_counter()
    finished = engine.run_until(lambda: completed[0] >= target,
                                timeout=600.0)
    elapsed = time.perf_counter() - start
    engine.remove_timer_handler(timer)
    if not finished:
        raise RuntimeError(
            f"detect bench stalled: {completed[0]}/{target} frames in "
            f"{elapsed:.0f}s — refusing to report a bogus fps")
    return completed[0] / elapsed


LLAMA_PRESET = os.environ.get("AIKO_BENCH_LLAMA_PRESET", "1b")
# Workload-sized KV allocation (serving._fit_caches) removed the old
# 128-slot capacity edge: 256 slots measured 9.3k tok/s and stay safe
# even if EVERY context grew to max_seq (8.6 GB KV + 2.5 GB weights);
# 512 measured 10.3k but only fits while contexts stay short — an
# unattended bench must not be able to OOM, so 256 is the default.
LLAMA_SLOTS = int(os.environ.get("AIKO_BENCH_LLAMA_SLOTS", "256"))
# 64 steps/sync = one device round per 64-token generation cycle: the
# tunnel's ~115 ms dispatch+sync cost amortizes over the whole cycle
# (retire-aligned rounds make the tail waste <2%, measured)
LLAMA_STEPS_PER_SYNC = int(os.environ.get("AIKO_BENCH_LLAMA_SPS", "64"))
# int8 end-to-end KV cache (ISSUE 7): the decode step is HBM-bound and
# the KV read is its second-largest byte, so the rung runs int8 by
# default — set AIKO_BENCH_LLAMA_KV=native for the bf16 A/B.
LLAMA_KV_DTYPE = os.environ.get("AIKO_BENCH_LLAMA_KV", "int8")
# self-speculative decoding: k drafts per slot per verify step via
# prompt lookup (serving.ContinuousDecoder speculate_k).  Off by
# default — random-weight bench models emit near-random continuations,
# so the drafter's accept rate measures the MACHINERY cost, not the
# real-text win; the rung reports llama_accept_rate either way.
LLAMA_SPEC_K = int(os.environ.get("AIKO_BENCH_LLAMA_SPEC", "0"))
# paged KV block pool (ISSUE 15): the slot caches run as a refcounted
# block pool + per-slot tables by default — prefix hits alias instead
# of copying, harvest is refcount-only, disagg installs land once.
# AIKO_BENCH_LLAMA_PAGED=off A/Bs the dense slot cache (greedy output
# is bit-identical either way; the copy-bytes fields are the delta).
LLAMA_PAGED = os.environ.get("AIKO_BENCH_LLAMA_PAGED", "on") \
    .lower() not in ("off", "0", "false", "")
# pool/prefix block size as a first-class knob so the r06 sweep can
# score 32 vs 64 (copy/scatter count vs partial-hit granularity)
LLAMA_BLOCK = int(os.environ.get("AIKO_BENCH_LLAMA_BLOCK", "32"))
# fused pallas decode kernel (ISSUE 16): AIKO_BENCH_LLAMA_KERNEL=on
# swaps the paged path's gather+einsum attention for the block-table-
# native kernel (ops/paged_attention.py) so BENCH_r06 can A/B the
# gather deletion on hardware.  Paged-only: combine with
# AIKO_BENCH_LLAMA_PAGED=on (the default) and any
# AIKO_BENCH_LLAMA_BLOCK; greedy output is bit-identical either way.
LLAMA_KERNEL = os.environ.get("AIKO_BENCH_LLAMA_KERNEL", "off") \
    .lower() in ("on", "1", "true")


def _apply_llama_kernel_toggle() -> None:
    """Latch the decode-attention toggle BEFORE decoder construction —
    serving reads ATTENTION_IMPL once, at __init__ (builder cache keys
    include the kernel flag, so both variants coexist in-process)."""
    if LLAMA_KERNEL:
        from aiko_services_tpu import serving
        serving.ATTENTION_IMPL = "paged_kernel"


def _llama_decoder_opts() -> dict:
    _apply_llama_kernel_toggle()
    return {
        "kv_cache_dtype": None if LLAMA_KV_DTYPE in
        ("", "native", "bf16") else LLAMA_KV_DTYPE,
        "speculate_k": LLAMA_SPEC_K,
        "paged_kv": LLAMA_PAGED,
        "kv_block": LLAMA_BLOCK,
    }


def _llama_pool_fields(decoder, prefix: str) -> dict:
    """Pool-occupancy bench surface (ISSUE 15): capacity, live blocks,
    bytes, and the copy counters the paged path zeroes."""
    fields = {
        f"{prefix}_kv_paged": bool(decoder.paged),
        f"{prefix}_kernel": bool(decoder.paged
                                 and decoder.paged_kernel),
        f"{prefix}_kv_block": decoder.kv_block,
        f"{prefix}_prefix_copy_bytes":
            decoder.stats["prefix_copy_bytes"],
        f"{prefix}_harvest_copy_bytes":
            decoder.stats["harvest_copy_bytes"],
    }
    if decoder.paged:
        pool = decoder.pool
        fields |= {
            f"{prefix}_pool_blocks": pool.num_blocks - 1,
            f"{prefix}_pool_blocks_used": pool.used_blocks(),
            f"{prefix}_pool_occupancy": round(pool.occupancy(), 4),
            f"{prefix}_pool_bytes": pool.nbytes(),
            f"{prefix}_pool_cow_copies": pool.stats["cow_copies"],
        }
    return fields


def bench_llama(window: float):
    """BASELINE config 5's serving leg: ContinuousDecoder on the largest
    llama preset that fits one chip.  Closed loop (a completed request
    immediately resubmits) for `window` seconds.  Returns a dict:
    tokens/sec/chip, mean slot occupancy, prefill/decode wall split,
    and an approximate MFU (2·N_matmul_params FLOPs per token)."""
    import dataclasses as _dc

    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init
    from aiko_services_tpu.serving import ContinuousDecoder

    base = LLAMA_PRESETS[LLAMA_PRESET]
    config = _dc.replace(base, dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    # single prefill bucket: a second (64) bucket was measured to LOSE —
    # admit groups re-pad their width to pow2 anyway, so splitting a
    # full-batch refill into two groups adds positions AND a compile
    # per (bucket, width) variant inside the measurement window
    decoder = ContinuousDecoder(params, config, max_slots=LLAMA_SLOTS,
                                max_seq=1024, prefill_buckets=(128,),
                                steps_per_sync=LLAMA_STEPS_PER_SYNC,
                                name="bench", **_llama_decoder_opts())
    rng = np.random.default_rng(11)
    generated = [0]
    submitted = [0]

    def submit_one():
        if LLAMA_SPEC_K:
            # n-gram structure the prompt-lookup drafter can exploit: a
            # tiled motif — pure-random prompts would measure only the
            # always-miss floor
            motif = rng.integers(1, config.vocab,
                                 size=int(rng.integers(4, 9)))
            prompt = np.tile(motif, 16)[
                :int(rng.integers(16, 120))].tolist()
        else:
            prompt = rng.integers(
                1, config.vocab,
                size=int(rng.integers(16, 120))).tolist()
        request_id = f"r{submitted[0]}"
        submitted[0] += 1
        decoder.submit(request_id, prompt, 64,
                       lambda rid, tokens: on_done(tokens))

    def on_done(tokens):
        generated[0] += len(tokens)
        if time.perf_counter() < deadline:
            submit_one()

    # warmup: compile prefill widths + the decode step before timing.
    # TWO pumps since the decode-first rework: the first round
    # dispatches admits only (nothing is decodable yet), the second
    # compiles + runs the scan
    deadline = time.perf_counter() + 3600.0
    for _ in range(2 * LLAMA_SLOTS):
        submit_one()
    decoder.pump()
    decoder.pump()
    for key in decoder.stats:
        decoder.stats[key] = 0 if isinstance(decoder.stats[key], int) \
            else 0.0
    # SLO sample deques too: warmup TTFTs include compile time and
    # would contaminate the measured percentiles (the mergeable
    # sketches follow the same rule)
    decoder.ttft_samples.clear()
    decoder.itl_samples.clear()
    decoder.gap_samples.clear()
    decoder.clear_slo_sketches()
    # phase profiler likewise: warmup rounds are compile-dominated and
    # would swamp the attribution the lat_llama_phase_* fields report
    decoder.profiler.reset()
    generated[0] = 0

    start = time.perf_counter()
    deadline = start + window
    while time.perf_counter() < deadline or not decoder.idle:
        decoder.pump()
        if decoder.idle and time.perf_counter() >= deadline:
            break
    elapsed = time.perf_counter() - start

    tokens_per_sec = generated[0] / elapsed if elapsed > 0 else 0.0
    # pure-device chained step: the SAME compiled step the serving loop
    # runs, chained K rounds with one final sync, on fresh buffers at
    # the serving shape — separates device compute from the tunnel's
    # per-round dispatch+sync so the artifact carries both (r4 verdict
    # item 2: the roofline claim must be checkable from the artifact
    # alone)
    device_step_ms = None
    try:
        from aiko_services_tpu.serving import measure_device_step
        device_step_ms = measure_device_step(decoder,
                                             LLAMA_STEPS_PER_SYNC)
    except Exception as exc:
        print(f"llama device-step probe failed: {exc!r}",
              file=sys.stderr)
    slo = decoder.slo_stats()
    # prefill dispatches ride BETWEEN decode scans (decode-first pump):
    # prefill_s is the host-side dispatch wall, decode_s the scan
    # dispatch→sync wall — prefill device time only leaks into decode_s
    # as spillover the host gap could not hide (prefill_budget bounds it)
    prefill_s = decoder.stats["prefill_s"]
    decode_s = decoder.stats["decode_s"]
    split = prefill_s / (prefill_s + decode_s) \
        if prefill_s + decode_s > 0 else 0.0
    # decode FLOPs/token ≈ 2 × matmul params (embedding lookup excluded;
    # attention-over-KV is <2% extra at seq ≤1024 for this geometry)
    import jax as _jax
    matmul_params = sum(
        int(np.prod(leaf.shape))
        for path, leaf in _jax.tree_util.tree_leaves_with_path(params)
        if "embed" not in str(path[0]))
    peak, _ = device_peak_flops()
    mfu = (tokens_per_sec * 2.0 * matmul_params / peak) if peak else None
    # decode is BANDWIDTH-bound: the honest utilization lens is HBM
    # bytes actually streamed (weights + capped KV read, modeled by the
    # decoder per round) over the decode wall time, vs the chip's spec
    # bandwidth.  llama_mfu stays for cross-round comparability.
    membw = device_peak_membw()
    steps = max(decoder.stats["steps"], 1)
    bw_util = (decoder.stats["bytes_moved"] / decode_s / membw) \
        if (membw and decode_s > 0) else None
    # decode-round phase attribution (ISSUE 11): where each round's
    # wall time went, per phase, so the roofline gap is attributed
    # rather than just measured — lat_llama_phase_attributed is the
    # fraction of round wall covered by NAMED phases (acceptance:
    # >= 0.9 on the CPU smoke)
    # sketch-derived SLO percentiles (ISSUE 12): the r06 artifact
    # quotes THESE — mergeable across serving runtimes, with the worst
    # requests' ids as exemplars behind every percentile.  The legacy
    # llama_ttft_* fields (np.percentile over the sample deque) stay
    # for cross-round comparability; the two must agree within the
    # sketch's 1% relative error plus the deque's 8192-sample bound.
    sketch_slo = decoder.slo_sketch_stats()
    sketch_fields = {}
    for kind in ("ttft", "itl"):
        for suffix in ("p50", "p95"):
            value = sketch_slo[f"{kind}_{suffix}_ms"]
            if value is not None:
                sketch_fields[f"lat_llama_{kind}_{suffix}_ms"] = \
                    round(value, 2)
    if sketch_fields:
        sketch_fields["lat_llama_slo_source"] = (
            "serving_ttft/itl_seconds mergeable sketches "
            "(alpha=0.01, exemplar-attributed)")
    phase = decoder.profiler.phase_stats()
    phase_fields = sketch_fields | {
        "lat_llama_phase_attributed": round(phase["attributed_frac"],
                                            4),
        "lat_llama_phase_rounds": phase["rounds"],
    }
    for phase_name, entry in sorted(phase["phases"].items()):
        phase_fields[f"lat_llama_phase_{phase_name}_ms"] = \
            round(entry["ms_per_round"], 3)
        if "gb_per_s" in entry:
            phase_fields[f"lat_llama_phase_{phase_name}_gbps"] = \
                round(entry["gb_per_s"], 2)
    return phase_fields | {
        "llama_tokens_per_sec": round(tokens_per_sec, 1),
        "llama_occupancy": round(decoder.mean_occupancy(), 3),
        "llama_prefill_frac": round(split, 3),
        "llama_completed": decoder.stats["completed"],
        "llama_wasted_frac": round(decoder.wasted_fraction(), 4),
        # decode_s is the scan dispatch→sync wall ONLY since the
        # decode-first rework: prefill dispatches ride between scans
        # and execute in the host's sync gap, so the split below stops
        # aliasing (prefill spillover a gap can't hide still lands in
        # decode_s — prefill_budget bounds it).  The roofline row is
        # the HBM floor for the modeled bytes (weights + sized KV
        # read) at spec bandwidth — the irreducible cost
        "llama_decode_step_ms": round(decode_s * 1000.0 / steps, 3),
        "llama_decode_s": round(decode_s, 3),
        "llama_prefill_s": round(prefill_s, 3),
        "llama_tokens_decode": decoder.stats["tokens_decode"],
        "llama_tokens_prefill": decoder.stats["tokens_prefill"],
        "llama_kv_cache_dtype": "int8" if decoder.kv_int8 else "bf16",
        "llama_kv_cache_bytes": decoder.kv_cache_bytes(),
        "llama_config": f"{LLAMA_PRESET} bf16, {LLAMA_SLOTS} slots, "
                        f"{LLAMA_STEPS_PER_SYNC} steps/sync, "
                        f"off-path prefill, "
                        f"kv={'int8' if decoder.kv_int8 else 'bf16'}"
                        + (f", paged block {LLAMA_BLOCK}"
                           if LLAMA_PAGED else ", dense kv")
                        + (f", spec_k={LLAMA_SPEC_K}"
                           if LLAMA_SPEC_K else ""),
    } | _llama_pool_fields(decoder, "lat_llama") \
        | ({} if not LLAMA_SPEC_K else {
        "llama_spec_k": LLAMA_SPEC_K,
        "llama_accept_rate": round(decoder.accept_rate(), 4),
        "llama_accepted_per_step": round(
            decoder.stats["accepted_per_step"], 3),
    }) | ({} if device_step_ms is None else {
        # device compute per DECODE step (chained, one sync) vs the
        # serving round above.  Post-rework the gap is tunnel
        # launch/sync plus whatever prefill spillover the host gap
        # could not hide — admit compute no longer rides the round by
        # construction (r05 measured ~9.2 ms/step of it)
        "llama_device_step_ms": round(device_step_ms, 3),
        "llama_overhead_ms_per_step": round(
            max(0.0, decode_s * 1000.0 / steps - device_step_ms), 3),
        "llama_overhead_note": "overhead = tunnel launch/sync + "
                               "prefill spillover past the host gap "
                               "(prefill dispatches between scans; "
                               "see llama_prefill_s / "
                               "llama_tokens_prefill)",
    }) | ({} if slo["ttft_p50_ms"] is None else {
        # measured per-request latency SLOs (serving.slo_stats):
        # TTFT submit→first burst; ITL per-request mean; stall = worst
        # inter-burst gap (what chunked prefill bounds)
        "llama_ttft_p50_ms": round(slo["ttft_p50_ms"], 1),
        "llama_ttft_p95_ms": round(slo["ttft_p95_ms"], 1),
        "llama_itl_p50_ms": round(slo["itl_p50_ms"], 2)
        if slo["itl_p50_ms"] is not None else None,
        "llama_itl_p95_ms": round(slo["itl_p95_ms"], 2)
        if slo["itl_p95_ms"] is not None else None,
        "llama_stall_p95_ms": round(slo["stall_p95_ms"], 1)
        if slo["stall_p95_ms"] is not None else None,
        "llama_slo_note": "closed-loop saturation (2x "
                          "oversubscription): ttft measures queue "
                          "depth; itl null = whole generation lands "
                          "in one 64-step sync burst — see "
                          "llama_int_* for the interactive config",
    }) | ({} if membw is None else {
        "llama_roofline_step_ms": round(
            decoder.stats["bytes_moved"] / steps / membw * 1000.0, 2),
    }) | ({} if mfu is None else {"llama_mfu": round(mfu, 4)}) \
        | ({} if bw_util is None else {"llama_hbm_bw_util":
                                       round(bw_util, 3)})


def bench_llama_interactive(window: float = 12.0):
    """Interactive-config llama SLOs: the saturation bench above keeps a
    2× closed-loop backlog and syncs 64 steps at once, so TTFT measures
    queue depth and ITL is a single burst (unobservable by design).
    This section measures the INTERACTIVE operating point instead:
    fewer slots, 8 steps/sync, Poisson arrivals at ~60% of measured
    capacity — real TTFT and inter-token latency percentiles from the
    serving engine's own per-request timestamps."""
    import dataclasses as _dc

    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init
    from aiko_services_tpu.serving import ContinuousDecoder

    slots, sps, max_new = 64, 8, 64
    base = LLAMA_PRESETS[LLAMA_PRESET]
    config = _dc.replace(base, dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    decoder = ContinuousDecoder(params, config, max_slots=slots,
                                max_seq=1024, prefill_buckets=(128,),
                                steps_per_sync=sps, name="bench_int",
                                **_llama_decoder_opts())
    rng = np.random.default_rng(23)

    def submit_one(index):
        prompt = rng.integers(
            1, config.vocab, size=int(rng.integers(16, 120))).tolist()
        decoder.submit(f"i{index}", prompt, max_new, lambda *_: None)

    # warmup: trickle submissions so EVERY pow2 admit width (1, 2, 4,
    # ... slots) compiles before the measured window — a width first
    # seen mid-measurement would land its compile stall straight into
    # the TTFT/stall percentiles
    count_warm = 0
    for width in [1, 1, 2, 4, 8, 16, 32][:slots.bit_length()] + [slots]:
        for _ in range(width):
            submit_one(count_warm)
            count_warm += 1
        decoder.pump()
    while not decoder.idle:
        decoder.pump()
    decoder.ttft_samples.clear()
    decoder.itl_samples.clear()
    decoder.gap_samples.clear()
    decoder.clear_slo_sketches()

    # ~60% load keeps queues short so TTFT measures admission+prefill,
    # not backlog.  Prior: a round of `sps` steps costs ~sps*6ms device
    # + ~115ms tunnel sync on this machine → ~20ms/step effective at
    # sps=8 (measured 50 req/s ran at ~104% load and queued)
    rate = 0.6 * slots / (max_new * 0.020)
    start = time.monotonic()
    deadline = start + window
    next_arrival = start
    count = count_warm
    while time.monotonic() < deadline or not decoder.idle:
        now = time.monotonic()
        while next_arrival <= now and now < deadline:
            submit_one(count)
            count += 1
            next_arrival += float(rng.exponential(1.0 / rate))
        decoder.pump()
    slo = decoder.slo_stats()
    if slo["ttft_p50_ms"] is None:
        return {}
    fields = {
        "llama_int_config": f"{LLAMA_PRESET} bf16, {slots} slots, "
                            f"{sps} steps/sync, poisson "
                            f"{rate:.0f} req/s, kv="
                            f"{'int8' if decoder.kv_int8 else 'bf16'}"
                            + (f", spec_k={LLAMA_SPEC_K}"
                               if LLAMA_SPEC_K else ""),
        "llama_int_ttft_p50_ms": round(slo["ttft_p50_ms"], 1),
        "llama_int_ttft_p95_ms": round(slo["ttft_p95_ms"], 1),
    }
    for key, field in (("itl_p50_ms", "llama_int_itl_p50_ms"),
                       ("itl_p95_ms", "llama_int_itl_p95_ms"),
                       ("stall_p95_ms", "llama_int_stall_p95_ms")):
        if slo[key] is not None:
            fields[field] = round(slo[key], 2)
    return fields


# prefix/KV reuse cache on the conversation rung (ISSUE 13): block
# size in tokens, or "off" to A/B the cold path (every turn re-prefills
# its whole history — the pre-PR 13 behavior).
# prefix cache on/off for the conversation rung; a NUMERIC value still
# sets the block size (PR 13 compat) — otherwise AIKO_BENCH_LLAMA_BLOCK
# is the block knob for cache and pool alike (ISSUE 15)
LLAMA_PREFIX = os.environ.get("AIKO_BENCH_LLAMA_PREFIX", "on")

# host KV tier on the conversation rung (ISSUE 17): attach a
# HostBlockStore and, after the measured window, run an idle/revive
# phase — every live session's history demotes to host RAM (the
# SessionTable wheel's shape) and then revives with one more turn, so
# the rung reports how much resident history the host tier carries and
# how much of the promotion H2D overlapped the admit wait.  "off"
# keeps the rung single-tier (the pre-17 behavior).
LLAMA_HOST_KV = os.environ.get("AIKO_BENCH_LLAMA_HOST_KV", "on")


def bench_llama_conversation(window: float = 10.0):
    """Multi-turn conversation rung (ISSUE 13): a seeded multi-session
    dialog over one ContinuousDecoder with the prefix/KV reuse cache.
    Each arriving session carries a pre-existing 400-token transcript
    (the "returning session" case — shared system prompt + its own
    history), every turn re-submits the WHOLE history, and sessions
    retire after a fixed turn count so fresh arrivals keep entering the
    measured window: turn 1 re-prefills the transcript COLD, turns 2+
    longest-match everything but the new user text — both populations
    flow continuously at comparable prompt lengths.  Emits cached/cold
    TTFT percentiles from the PR 12 mergeable sketches (the ttft
    sketch's prefill label splits the populations) and the block hit
    rate; AIKO_BENCH_LLAMA_PREFIX=off A/Bs the cold path under the
    identical workload."""
    import dataclasses as _dc

    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init
    from aiko_services_tpu.serving import ContinuousDecoder, PrefixKVCache

    base = LLAMA_PRESETS[LLAMA_PRESET]
    config = _dc.replace(base, dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    prefix_off = LLAMA_PREFIX.lower() in ("off", "0", "false", "")
    block = int(LLAMA_PREFIX) if LLAMA_PREFIX.isdigit() \
        else LLAMA_BLOCK
    cache = None if prefix_off else PrefixKVCache(
        block_tokens=block, max_bytes=2 << 30, name="bench_conv")
    store = None
    if cache is not None and LLAMA_HOST_KV.lower() not in (
            "off", "0", "false", ""):
        from aiko_services_tpu.serving_tiered import HostBlockStore
        store = HostBlockStore(max_bytes=8 << 30,
                               name="bench_conv_host")
        cache.attach_host_store(store)
    _apply_llama_kernel_toggle()
    slots, sps, max_new = 16, 8, 32
    transcript, turns_per_session, user_len = 600, 6, 24
    decoder = ContinuousDecoder(params, config, max_slots=slots,
                                max_seq=1024, prefill_buckets=(64,),
                                steps_per_sync=sps, prefill_chunk=64,
                                prefix_cache=cache, name="bench_conv",
                                paged_kv=LLAMA_PAGED, kv_block=block)
    # KV memory ledger (ISSUE 20): per-tenant/per-tier attribution —
    # the rung reports footprint beside throughput
    from aiko_services_tpu.observe.ledger import KVMemoryLedger
    ledger = KVMemoryLedger(name="bench_conv")
    decoder.attach_ledger(ledger)
    rng = np.random.default_rng(31)
    sessions: dict = {}
    turns_done = [0]
    session_seq = [0]
    deadline = time.perf_counter() + 3600.0

    def new_session():
        sid = f"s{session_seq[0]}"
        session_seq[0] += 1
        # a PRIVATE seeded transcript per session (the restored-from-
        # state-plane shape): nothing of it is cached yet, so turn 1 is
        # a genuinely cold full-history prefill and the cold/cached
        # populations split cleanly — a shared system prompt would make
        # even turn 1 a partial hit and blur the A/B (shared-prefix
        # reuse is scored by the hit-rate field and the parity tests)
        history = rng.integers(1, config.vocab,
                               size=transcript).tolist()
        sessions[sid] = {"history": history, "turns": 0}
        return sid

    def submit_turn(sid):
        state = sessions[sid]
        user = rng.integers(1, config.vocab, size=user_len).tolist()
        prompt = state["history"] + user

        def on_done(_rid, generated):
            state["history"] = prompt + list(generated)
            state["turns"] += 1
            turns_done[0] += 1
            if time.perf_counter() >= deadline:
                return
            if state["turns"] >= turns_per_session:
                del sessions[sid]       # retired; a fresh cold
                submit_turn(new_session())   # arrival replaces it
            else:
                submit_turn(sid)

        decoder.submit(f"{sid}.t{state['turns']}", prompt, max_new,
                       on_done)

    # warmup: one full session generation — turn 1 compiles the cold
    # admit / extend widths, turns 2+ the prefix-copy widths and the
    # cached extends; measured percentiles must not carry compile
    # stalls
    for _ in range(8):
        submit_turn(new_session())
    while turns_done[0] < 8 * turns_per_session:
        decoder.pump()
    decoder.ttft_samples.clear()
    decoder.itl_samples.clear()
    decoder.gap_samples.clear()
    decoder.clear_slo_sketches()
    decoder.profiler.reset()
    hit0 = (0, 0) if cache is None else (cache.stats["hit_tokens"],
                                         cache.stats["miss_tokens"])

    start = time.perf_counter()
    deadline = start + window
    measured0 = turns_done[0]
    while time.perf_counter() < deadline or not decoder.idle:
        decoder.pump()
        if decoder.idle and time.perf_counter() >= deadline:
            break

    turns = turns_done[0] - measured0
    if cache is None:
        hit_rate = 0.0
    else:
        hits = cache.stats["hit_tokens"] - hit0[0]
        misses = cache.stats["miss_tokens"] - hit0[1]
        hit_rate = hits / (hits + misses) if hits + misses else 0.0
    fields = {
        "lat_llama_conv_config":
            f"{LLAMA_PRESET} bf16, {slots} slots, {sps} steps/sync, "
            f"8 concurrent sessions x {turns_per_session} turns, "
            f"{transcript}-token restored transcript, "
            f"{user_len}-token turns, "
            f"prefix=" + ("off" if prefix_off else f"block{block}")
            + (", paged" if LLAMA_PAGED else ", dense"),
        "lat_llama_conv_sessions": session_seq[0],
        "lat_llama_conv_turns": turns,
        "lat_llama_conv_prefix_hit_rate": round(hit_rate, 4),
    } | _llama_pool_fields(decoder, "lat_llama_conv")
    # the ISSUE 15 acceptance surface: KV bytes a prefix hit copies
    # into the slot — paged aliasing drops this to ZERO (dense: the
    # whole pow2-padded chain per hit)
    admits = max(1, decoder.stats["prefix_admits"])
    fields["lat_llama_conv_copy_bytes_per_hit"] = \
        decoder.stats["prefix_copy_bytes"] // admits
    if cache is not None:
        fields["lat_llama_conv_prefix_blocks"] = len(cache)
        fields["lat_llama_conv_prefix_bytes"] = cache.bytes_used
    for label in ("cold", "cached"):
        slo = decoder.slo_sketch_stats(prefill=label)
        for suffix in ("p50", "p95"):
            value = slo[f"ttft_{suffix}_ms"]
            if value is not None:
                fields[f"lat_llama_conv_ttft_{label}_{suffix}_ms"] = \
                    round(value, 2)
    if store is not None:
        # idle/revive phase (ISSUE 17): every live session goes idle —
        # its whole history demotes to the host tier (device blocks
        # freed) — then revives with one more turn.  The revive's
        # prompt chain must come back via promotion, and the
        # admission-probe prefetch should land most of it BEFORE the
        # admit round (the overlap ratio).
        live = list(sessions)
        for sid in live:
            cache.session_store("", sid, sessions[sid]["history"])
        cache.demote_sessions([("", sid) for sid in live])
        fields["lat_llama_conv_resident_sessions"] = len(live)
        fields["lat_llama_conv_host_bytes"] = store.bytes_used
        promoted0 = cache.stats["promoted"]
        revived0 = turns_done[0]
        revive_start = time.perf_counter()
        for sid in live:
            submit_turn(sid)
        while turns_done[0] < revived0 + len(live):
            decoder.pump()
        fields["lat_llama_conv_revive_wall_s"] = round(
            time.perf_counter() - revive_start, 3)
        fields["lat_llama_conv_promotes"] = \
            cache.stats["promoted"] - promoted0
        pstats = cache.promoter.stats
        fields["lat_llama_conv_promote_overlap_ratio"] = round(
            (pstats["installs_async"] + pstats["installs_wait"]) /
            max(1, pstats["installs"]), 4)
        cache.promoter.stop()
    # ledger attribution fields (ISSUE 20): live per-tier bytes at rung
    # end, the pinned (non-evictable) share of the device tier, and the
    # integrated footprint each session cost (byte-seconds amortised
    # over every session the rung ran)
    ledger.audit()
    mem_device = ledger.device_bytes()
    fields["lat_llama_conv_mem_device_bytes"] = mem_device
    fields["lat_llama_conv_mem_host_bytes"] = ledger.host_bytes()
    pinned = sum(ledger.pinned_bytes(t) for t in ledger.tenants())
    fields["lat_llama_conv_mem_pinned_ratio"] = \
        round(pinned / mem_device, 4) if mem_device else 0.0
    fields["lat_llama_conv_mem_byteseconds_per_session"] = \
        round(ledger.byte_seconds() / max(1, session_seq[0]), 1)
    return fields


# disaggregated prefill/decode serving rung (ISSUE 14): "off" skips,
# anything else runs the two-pool plane plus a colocated A/B under the
# identical workload.
LLAMA_DISAGG = os.environ.get("AIKO_BENCH_LLAMA_DISAGG", "1")


def bench_llama_disagg(window: float = 8.0):
    """Two-pool serving rung (ISSUE 14): a role-tagged prefill runtime
    computes prompt KV and ships it over the peer data plane to the
    decode decoder (serving_disagg.DisaggHarness), while closed-loop
    decode streams measure inter-token latency with and without a
    concurrent cold-prefill burst.  The colocated A/B runs the SAME
    seeded workload on one decoder — the burst's chunk extends ride
    its decode rounds, which is exactly the ITL dilation the split
    removes.  Greedy parity is asserted inside the rung: a probe
    prompt's tokens must be BIT-IDENTICAL disaggregated vs colocated
    (the KV-transfer carries the donor decoder's exact bytes)."""
    import dataclasses as _dc

    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init
    from aiko_services_tpu.serving_disagg import DisaggHarness

    if LLAMA_DISAGG.lower() in ("off", "0", "false", ""):
        return {}
    base = LLAMA_PRESETS[LLAMA_PRESET]
    config = _dc.replace(base, dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    block, slots, prefill_slots = 32, 16, 4
    # transfer timeout generous: a CPU-smoke jit compile inside a
    # transfer's wall must not trip the fallback ladder mid-rung (the
    # ladder has its own chaos tests; the rung wants 0 fallbacks)
    kwargs = dict(block_tokens=block, max_slots=slots,
                  prefill_slots=prefill_slots, steps_per_sync=4,
                  prefill_buckets=(64,), prefill_chunk=64,
                  transfer_timeout=60.0,
                  decoder_opts=_llama_decoder_opts())
    probe = np.random.default_rng(7).integers(
        1, config.vocab, size=200).tolist()

    def probe_tokens(harness):
        done = {}
        harness.submit("probe", probe, 16,
                       lambda rid, t: done.update({rid: t}))
        harness.run_until(lambda: "probe" in done, timeout=300.0)
        return done.get("probe")

    coloc = DisaggHarness(params, config, disagg=False, **kwargs)
    coloc_probe = probe_tokens(coloc)
    coloc_out = coloc.measure(window=window, burst_every=0.4)
    coloc.stop()

    disagg = DisaggHarness(params, config, disagg=True, **kwargs)
    if not disagg.wait_discovered(30.0):
        disagg.stop()
        return {"lat_llama_disagg_error": "prefill pool never "
                                          "discovered"}
    # KV memory ledger on the decode side (ISSUE 20): attribution of
    # the landed transfers' device bytes
    from aiko_services_tpu.observe.ledger import KVMemoryLedger
    ledger = KVMemoryLedger(name="bench_disagg")
    disagg.decoder.attach_ledger(ledger)
    disagg_probe = probe_tokens(disagg)
    disagg_out = disagg.measure(window=window, burst_every=0.4)
    transfers = dict(disagg.prefill.stats)
    ledger.audit()
    mem_fields = {
        "lat_llama_disagg_mem_device_bytes": ledger.device_bytes(),
        "lat_llama_disagg_mem_byte_seconds":
            round(ledger.byte_seconds(), 1),
        # every shipped chain lands through an instrumented alloc —
        # the event count is the decode side's install traffic
        "lat_llama_disagg_mem_alloc_events":
            int(ledger.stats["alloc"]),
    }
    disagg.stop()

    parity = disagg_probe == coloc_probe and disagg_probe is not None
    fields = {
        "lat_llama_disagg_config":
            f"{LLAMA_PRESET} bf16, decode {slots} slots / prefill "
            f"{prefill_slots} slots, block {block}, chunk 64, "
            f"peer-shipped int8-layout KV, colocated A/B same seed",
        "lat_llama_disagg_parity": bool(parity),
        "lat_llama_disagg_transfers": disagg_out.get("transfers", 0),
        "lat_llama_disagg_transfer_bytes":
            disagg_out.get("transfer_bytes", 0),
        "lat_llama_disagg_handle_hit_rate":
            disagg_out.get("handle_hit_rate", 0.0),
        "lat_llama_disagg_local_fallbacks":
            disagg_out.get("local_fallbacks", 0),
        "lat_llama_disagg_lost": disagg_out["lost"],
        "lat_llama_coloc_lost": coloc_out["lost"],
        "lat_llama_disagg_prefill_blocks_shipped":
            transfers.get("blocks_shipped", 0),
        # paged install surface (ISSUE 15): with the pool on, the
        # shipped chain lands ONCE (wire -> pool scatter) and the
        # admit is a table edit — install copy bytes drop to zero
        "lat_llama_disagg_kv_paged": bool(disagg.decoder.paged),
        "lat_llama_disagg_install_copy_bytes":
            disagg.decoder.stats["prefix_copy_bytes"],
        "lat_llama_disagg_transfer_batched":
            transfers.get("batched_envelopes", 0),
        # chunk streaming (ISSUE 17): blocks shipped while the donor
        # was still prefilling, and the wall-clock the client spent
        # overlapped with donor compute instead of waiting on it
        "lat_llama_disagg_chunk_streamed":
            disagg_out.get("chunk_streamed", 0),
        "lat_llama_disagg_chunk_installs":
            disagg_out.get("chunk_installs", 0),
        "lat_llama_disagg_chunk_dropped":
            disagg_out.get("chunk_dropped", 0),
        "lat_llama_disagg_transfer_overlap_s":
            disagg_out.get("transfer_overlap_s", 0.0),
    } | mem_fields
    for key, label in (("transfer_p50_ms", "transfer_p50_ms"),
                       ("transfer_p95_ms", "transfer_p95_ms")):
        if disagg_out.get(key) is not None:
            fields[f"lat_llama_disagg_{label}"] = disagg_out[key]
    for mode, out in (("disagg", disagg_out), ("coloc", coloc_out)):
        for key in ("itl_p50_baseline_ms", "itl_p95_baseline_ms",
                    "itl_p50_burst_ms", "itl_p95_burst_ms",
                    "stall_p95_baseline_ms", "stall_p95_burst_ms"):
            value = out.get(key)
            if value is not None:
                fields[f"lat_llama_{mode}_{key}"] = round(value, 3)
    return fields


# -- low-latency operating point ---------------------------------------------
# The <150 ms p50 budget is ARCHITECTURALLY unreachable at 5 s chunks
# (a full chunk must exist before it can be posted).  This section runs
# the same serving path at sub-second chunks with per-frame deadlines
# (deadline-aware batch admission) and reports p50/p95 decomposed into
# queue / wire / compute.  Two explicitly-labeled configurations:
#   * wire: open-loop real-time streams through the full pipeline and
#     the host→device wire (tunnel-honest);
#   * device-resident: the same fused program with resident input, the
#     number a host-attached chip gives (queue model: uniform arrivals
#     into back-to-back batch rounds wait round/2 on average).
LAT_CHUNK_S = float(os.environ.get("AIKO_BENCH_LAT_CHUNK", "0.5"))
LAT_TOKENS = 8                    # ~tokens utterable in half a second
LAT_BATCH = int(os.environ.get("AIKO_BENCH_LAT_BATCH", "48"))
LAT_DEADLINE_MS = 140.0
LAT_POOL = 64                     # device-resident distinct chunks
# device-resident measured rungs (ascending, stops at first failure)
LAT_DEV_RUNGS = tuple(int(x) for x in os.environ.get(
    "AIKO_BENCH_LAT_DEV_RUNGS", "200,400,600,800").split(","))
# wire rungs: adaptive around the 200-stream target (descend to find
# the true operating point when 200 fails, ascend when it passes)
LAT_WIRE_DESCEND = (120, 80, 40)
LAT_WIRE_ASCEND = (280, 360)
LAT_WINDOW = float(os.environ.get("AIKO_BENCH_LAT_WINDOW", "10"))
# wire rung (binary envelope path) knobs: the serving batch is larger
# than the device-resident rung's because the tunnel's fixed per-batch
# dispatch cost dominates the wire path — bigger batches amortize it;
# max_wait scales accordingly so batches actually fill under load
WIRE_BATCH = int(os.environ.get("AIKO_BENCH_WIRE_BATCH", "0")) or \
    2 * LAT_BATCH
WIRE_WAIT = float(os.environ.get("AIKO_BENCH_WIRE_WAIT", "0.2"))
WIRE_COALESCE = int(os.environ.get("AIKO_BENCH_WIRE_COALESCE", "32"))


def _measured_latency_loop(compiled, params, pool, n_streams: int,
                           window: float, process: str,
                           tunnel_floor: float, frames: int):
    """The REAL closed loop, measured end to end (round-4 verdict item
    1): an arrival process (uniform phases or Poisson) submits into the
    actual BatchingScheduler (deadline-aware admission LIVE, service
    EWMA fed back), which dispatches the compiled fused program over
    DEVICE-RESIDENT payloads (a [pool, samples] buffer gathered by
    index on device — only the [batch] index vector crosses the wire);
    a sync worker thread (the production pipelined-results pattern)
    collects batches and stamps per-frame latencies enqueue→result.

    Every reported number is a per-frame timestamp difference; nothing
    is a queue formula.  Deadlines are arrival + budget + the measured
    tunnel dispatch floor: the floor is a bench-machine artifact
    host-attached TPUs do not pay, and charging it against the 140 ms
    slack would collapse admission into a batch-of-1 storm (the same
    accounting as the ex-floor report field).

    Returns a dict of measured fields, or None when the rung could not
    sustain the arrival rate."""
    import threading
    from collections import deque as _deque

    from aiko_services_tpu.ops.batching import (BatchingScheduler,
                                                ShapeBuckets)

    rng = np.random.default_rng(17)
    latencies: list = []
    in_flight: _deque = _deque()
    completed = [0]
    stop = [False]

    def process_batch(bucket, items):
        idx = np.fromiter((item.payload for item in items), np.int32,
                          len(items))
        if len(idx) < LAT_BATCH:
            # static shape: pad with repeats — wasted lanes, same
            # compiled program
            idx = np.concatenate([idx, np.zeros(LAT_BATCH - len(idx),
                                                np.int32)])
        out = compiled(params, pool, jnp.asarray(idx))
        in_flight.append((items, out, time.monotonic(), bucket))
        return None                        # sync worker owns delivery

    scheduler = BatchingScheduler(
        process_batch, ShapeBuckets([frames]), max_batch=LAT_BATCH,
        max_wait=0.08,
        dispatch_gate=lambda: len(in_flight) < DEPTH)

    def syncer():
        while not stop[0] or in_flight:
            if not in_flight:
                time.sleep(0.0005)
                continue
            items, out, dispatched, bucket = in_flight.popleft()
            np.asarray(jax.tree_util.tree_leaves(out)[0])
            now = time.monotonic()
            scheduler.observe_service_time(bucket, now - dispatched)
            for item in items:
                latencies.append(now - item.enqueue_time)
            completed[0] += len(items)

    worker = threading.Thread(target=syncer, daemon=True)
    worker.start()
    budget = LATENCY_BUDGET + tunnel_floor
    bailed = False
    start = time.monotonic()
    deadline = start + window
    submitted = 0
    if process == "poisson":
        next_arrival = start + float(rng.exponential(
            LAT_CHUNK_S / n_streams))
    else:
        phases = [start + i * LAT_CHUNK_S / n_streams
                  for i in range(n_streams)]
        import heapq as _heapq
        _heapq.heapify(phases)
    try:
        while True:
            now = time.monotonic()
            if process == "poisson":
                while next_arrival <= now and now < deadline:
                    scheduler.submit(
                        f"p{submitted}", int(rng.integers(0, LAT_POOL)),
                        frames, lambda *_: None,
                        deadline=next_arrival + budget)
                    submitted += 1
                    next_arrival += float(rng.exponential(
                        LAT_CHUNK_S / n_streams))
            else:
                while phases and phases[0] <= now:
                    when = _heapq.heappop(phases)
                    scheduler.submit(
                        f"u{submitted}", int(rng.integers(0, LAT_POOL)),
                        frames, lambda *_: None, deadline=when + budget)
                    submitted += 1
                    if when + LAT_CHUNK_S < deadline:
                        _heapq.heappush(phases, when + LAT_CHUNK_S)
            scheduler.drain()
            if now >= deadline and scheduler.pending() == 0:
                break
            # falling behind by > 6 full batches of queued work on top
            # of the in-flight depth = not sustaining; bail early
            if scheduler.pending() > 6 * LAT_BATCH:
                bailed = True
                break
            time.sleep(0.0005)
        scheduler.drain(force=True)
        drain_start = time.monotonic()
        while completed[0] < submitted and \
                time.monotonic() - drain_start < 30.0:
            time.sleep(0.002)
    finally:
        stop[0] = True
        worker.join(timeout=60.0)
    drain_time = time.monotonic() - drain_start
    sustained = not bailed and completed[0] >= submitted and \
        drain_time <= 2.0 and scheduler.pending() == 0
    ordered = sorted(latencies) or [float("inf")]
    p50 = ordered[len(ordered) // 2]
    p95 = ordered[int(0.95 * (len(ordered) - 1))]
    print(f"measured[{process}] n={n_streams}: submitted={submitted} "
          f"done={completed[0]} p50={p50*1000:.0f}ms "
          f"p95={p95*1000:.0f}ms mean_batch="
          f"{scheduler.mean_batch_size():.1f} "
          f"deadline_fires={scheduler.stats['deadline_dispatches']} "
          f"drain={drain_time:.1f}s sustained={sustained}",
          file=sys.stderr)
    if not sustained:
        return None
    return {
        "streams": n_streams,
        "p50_ms": round(p50 * 1000.0, 1),
        "p95_ms": round(p95 * 1000.0, 1),
        "p50_ex_floor_ms": round((p50 - tunnel_floor) * 1000.0, 1),
        "p95_ex_floor_ms": round((p95 - tunnel_floor) * 1000.0, 1),
        "frames": completed[0],
        "mean_batch": round(scheduler.mean_batch_size(), 1),
        "deadline_dispatches": scheduler.stats["deadline_dispatches"],
    }


def bench_latency():
    from aiko_services_tpu.ops.audio import (WHISPER_HOP,
                                             log_mel_spectrogram,
                                             mulaw_decode)

    frames = int(LAT_CHUNK_S * FRAMES_PER_SECOND)
    config = dataclasses.replace(WHISPER_PRESETS[PRESET],
                                 n_audio_ctx=frames // 2,
                                 n_text_ctx=LAT_TOKENS + 8,
                                 dtype=jnp.bfloat16)
    params = whisper_init(jax.random.PRNGKey(0), config)

    def fused(params, pool, idx):
        pcm = pool[idx]                       # device-side gather
        audio = mulaw_decode(pcm)
        mel = log_mel_spectrogram(audio, num_mels=config.n_mels)
        return greedy_decode(params, config, mel.astype(config.dtype),
                             max_tokens=LAT_TOKENS, kv_quant=KV_QUANT)

    pool = jax.random.randint(
        jax.random.PRNGKey(3), (LAT_POOL, frames * WHISPER_HOP), 0,
        256, jnp.int32).astype(jnp.uint8)     # resident on device
    idx0 = jnp.arange(LAT_BATCH, dtype=jnp.int32) % LAT_POOL
    compiled = compile_with_retry(fused, params, pool, idx0)
    # chain=1 includes the tunnel's fixed dispatch+sync cost; chained
    # amortizes it out (= device compute); a trivial-program round
    # trip MEASURES that floor so the artifact shows the arithmetic
    compute_round = measure_compiled(compiled, params, pool, idx0,
                                     chain=1)
    compute_chained = measure_compiled(compiled, params, pool, idx0,
                                       chain=8)
    trivial = compile_with_retry(lambda x: (x + 1,), jnp.zeros(8))
    tunnel_floor = measure_compiled(trivial, jnp.zeros(8), chain=1)
    print(f"latency calib: {compute_round*1000:.1f} ms/round "
          f"(chained {compute_chained*1000:.1f}, tunnel floor "
          f"{tunnel_floor*1000:.1f}) @ batch {LAT_BATCH}, "
          f"chunk {LAT_CHUNK_S}s", file=sys.stderr)

    # device-resident configuration, MEASURED (replaces r4's modeled
    # round/2 queue): real arrivals → live deadline-aware scheduler →
    # compiled program over device-resident payloads → per-frame
    # timestamps.  Ascending rungs; Poisson arrivals re-measured at the
    # best uniform rung (burstier queue, same capacity).
    best_uniform = None
    for rung in LAT_DEV_RUNGS:
        fields = _measured_latency_loop(compiled, params, pool, rung,
                                        LAT_WINDOW, "uniform",
                                        tunnel_floor, frames)
        if fields is None:
            break
        best_uniform = fields
    poisson = None
    if best_uniform is not None:
        poisson = _measured_latency_loop(
            compiled, params, pool, best_uniform["streams"], LAT_WINDOW,
            "poisson", tunnel_floor, frames)
    # device-only baseline at the WIRE rung's batch shape, so
    # lat_wire_overhead_ms subtracts a same-shape compute round (the
    # wire rung batches bigger to amortize the fixed per-batch tunnel
    # cost)
    if WIRE_BATCH == LAT_BATCH:
        wire_round_chained = compute_chained
    else:
        idx_wire = jnp.arange(WIRE_BATCH, dtype=jnp.int32) % LAT_POOL
        compiled_wire = compile_with_retry(fused, params, pool, idx_wire)
        wire_round_chained = measure_compiled(compiled_wire, params,
                                              pool, idx_wire, chain=8)
        print(f"wire-batch baseline: {wire_round_chained*1000:.1f} ms "
              f"chained @ batch {WIRE_BATCH}", file=sys.stderr)
        del compiled_wire
    del compiled, pool, params

    result = {
        "lat_chunk_s": LAT_CHUNK_S,
        "lat_batch": LAT_BATCH,
        "lat_compute_round_ms": round(compute_chained * 1000.0, 1),
        "lat_tunnel_floor_ms": round(tunnel_floor * 1000.0, 1),
    }
    dev_met = False
    if best_uniform is not None:
        dev_met = (best_uniform["p50_ex_floor_ms"] <=
                   LATENCY_BUDGET * 1000.0 and
                   best_uniform["streams"] >= 200)
        result |= {
            "lat_dev_streams": best_uniform["streams"],
            "lat_dev_p50_ms": best_uniform["p50_ms"],
            "lat_dev_p95_ms": best_uniform["p95_ms"],
            "lat_dev_p50_ex_floor_ms": best_uniform["p50_ex_floor_ms"],
            "lat_dev_p95_ex_floor_ms": best_uniform["p95_ex_floor_ms"],
            "lat_dev_frames": best_uniform["frames"],
            "lat_dev_mean_batch": best_uniform["mean_batch"],
            "lat_dev_deadline_dispatches":
                best_uniform["deadline_dispatches"],
            "lat_dev_label": f"device-resident {LAT_CHUNK_S}s chunks, "
                             f"MEASURED closed loop (uniform arrivals, "
                             f"live deadline-aware scheduler, per-frame"
                             f" timestamps); budget decided on p50 with"
                             f" the measured tunnel dispatch floor "
                             f"subtracted (reported both ways)",
        }
        if poisson is not None:
            result |= {
                "lat_dev_poisson_p50_ms": poisson["p50_ms"],
                "lat_dev_poisson_p95_ms": poisson["p95_ms"],
                "lat_dev_poisson_p50_ex_floor_ms":
                    poisson["p50_ex_floor_ms"],
            }
    result["lat_dev_budget_met"] = bool(dev_met)
    # wire-cost arithmetic: bytes one chunk ships per wire mode, and
    # the tunnel bandwidth at which the wire path would saturate the
    # device-resident capacity (item: quantify environmental vs
    # recoverable)
    chunk_bytes_mulaw = frames * WHISPER_HOP          # uint8 codes
    dev_capacity = LAT_BATCH / compute_chained        # chunks/s
    result |= {
        "lat_wire_bytes_per_chunk_mulaw": chunk_bytes_mulaw,
        "lat_wire_bytes_per_chunk_int16": 2 * chunk_bytes_mulaw,
        "lat_wire_breakeven_MBps": round(
            dev_capacity * chunk_bytes_mulaw / 1e6, 1),
    }

    # wire configuration: the FULL wire path, real-time arrivals —
    # caller pipeline -> binary envelope over the indexed MemoryBroker
    # (zero-copy µ-law codes, burst coalescing) -> serving pipeline ->
    # batched device program -> coalesced binary replies.  Adaptive
    # ladder around the 200-stream target: when 200 fails, DESCEND to
    # find the wire path's true operating point (how many streams it
    # CAN sustain within budget on this machine — r4 only recorded the
    # failing rung); when it passes, ascend.
    bench = WirePipelineBench(WIRE_BATCH, max_wait=WIRE_WAIT,
                              chunk_seconds=LAT_CHUNK_S,
                              max_tokens=LAT_TOKENS,
                              deadline_ms=LAT_DEADLINE_MS,
                              coalesce_frames=WIRE_COALESCE)
    bench.warmup(WIRE_BATCH)
    program = bench.compute.programs["whisper_asr.PE_WhisperASR"]

    def run_wire_rung(n):
        # per-rung decomposition must not blend samples from warmup or
        # earlier rungs — clear the rolling collections and snapshot
        # cumulative counters
        program.scheduler.recent_waits.clear()
        program.recent_service.clear()
        bench.round_sketch.clear()
        deadline_before = program.scheduler.stats["deadline_dispatches"]
        wire_before = bench.wire_counters()
        ok, p50, done, mean_batch = bench.measure(
            n, PIPELINE_SECONDS, drain_budget=2.0)
        ordered = sorted(bench._latencies) or [float("inf")]
        p95 = ordered[int(0.95 * (len(ordered) - 1))]
        waits = sorted(program.scheduler.recent_waits) or [0.0]
        queue_p50 = waits[len(waits) // 2]
        service = sorted(s for _, s in program.recent_service) or [0.0]
        service_p50 = service[len(service) // 2]
        # retry-aware coalescing telemetry straight from the metrics
        # registry — the counters the runtime itself increments
        wire_after = bench.wire_counters()
        envelopes = wire_after["envelopes"] - wire_before["envelopes"]
        wire_frames = wire_after["frames"] - wire_before["frames"]
        wire_retries = wire_after["retries"] - wire_before["retries"]
        # the SAME percentiles re-derived from the mergeable sketch
        # (ISSUE 12) — fleet-aggregatable, exemplar-attributed; must
        # agree with the list-based numbers within the sketch's 1%
        # relative error
        sketch_q = bench.round_sketch_quantiles()
        return {
            "lat_wire_streams": n,
            "lat_wire_sustained": bool(ok),
            "lat_wire_p50_ms": round(p50 * 1000.0, 1),
            "lat_wire_p95_ms": round(p95 * 1000.0, 1),
            "lat_wire_round_p50_ms":
                None if sketch_q["p50_ms"] is None
                else round(sketch_q["p50_ms"], 1),
            "lat_wire_round_p95_ms":
                None if sketch_q["p95_ms"] is None
                else round(sketch_q["p95_ms"], 1),
            "lat_queue_p50_ms": round(queue_p50 * 1000.0, 1),
            "lat_service_p50_ms": round(service_p50 * 1000.0, 1),
            # wire = in-flight service minus the device-only round at
            # the SAME batch shape
            "lat_wire_overhead_ms": round(
                max(0.0, service_p50 - wire_round_chained) * 1000.0, 1),
            "lat_mean_batch": round(mean_batch, 1),
            "lat_deadline_dispatches":
                program.scheduler.stats["deadline_dispatches"] -
                deadline_before,
            "lat_wire_envelopes": envelopes,
            "lat_wire_retries": wire_retries,
            "lat_wire_frames_per_envelope": round(
                wire_frames / envelopes, 2) if envelopes else 0.0,
            # data-plane split accounting (ISSUE 6): envelopes on the
            # direct peer channel vs broker-routed messages this rung
            "lat_wire_peer_envelopes":
                wire_after["peer_sent"] - wire_before["peer_sent"],
            "lat_wire_broker_routed":
                wire_after["broker_routed"] - wire_before["broker_routed"],
            "lat_wire_peer_pinned": bench.peer_pinned(),
            # overload-control verdicts this rung (ISSUE 9): the gate
            # is live on the serving pipeline; shed/rejected are 0
            # unless AIKO_BENCH_WIRE_DEADLINE_S arms shed-early
            "lat_wire_admitted":
                wire_after["admitted"] - wire_before["admitted"],
            "lat_wire_shed": wire_after["shed"] - wire_before["shed"],
            "lat_wire_rejected":
                wire_after["rejected"] - wire_before["rejected"],
            "lat_wire_budget_met": bool(
                ok and p50 <= LATENCY_BUDGET and n >= 200),
        }

    def within_budget(fields):
        return fields["lat_wire_sustained"] and \
            fields["lat_wire_p50_ms"] <= LATENCY_BUDGET * 1000.0

    first = run_wire_rung(200)
    wire_fields = first
    if within_budget(first):
        for n in LAT_WIRE_ASCEND:
            fields = run_wire_rung(n)
            if not within_budget(fields):
                break
            wire_fields = fields
    else:
        # record the target-rung failure, then find the real capacity
        result |= {"lat_wire200_p50_ms": first["lat_wire_p50_ms"],
                   "lat_wire200_p95_ms": first["lat_wire_p95_ms"],
                   "lat_wire200_sustained":
                       first["lat_wire_sustained"]}
        for n in LAT_WIRE_DESCEND:
            fields = run_wire_rung(n)
            wire_fields = fields
            if within_budget(fields):
                break
        wire_fields["lat_wire_max_within_budget"] = \
            wire_fields["lat_wire_streams"] \
            if within_budget(wire_fields) else 0
    del bench
    result |= wire_fields
    result |= {
        "lat_wire_batch": WIRE_BATCH,
        "lat_wire_round_chained_ms": round(
            wire_round_chained * 1000.0, 1),
        "lat_wire_path": "binary envelope over registrar-negotiated "
                         "PEER channel (broker = discovery/control + "
                         "fallback): caller pipeline -> direct channel "
                         "(zero-copy µ-law uint8, coalesced) -> serving "
                         "pipeline -> device; replies coalesced on the "
                         "same channel",
    }
    met_wire = result.get("lat_wire_budget_met", False)
    result["latency_budget_met"] = bool(met_wire or dev_met)
    result["latency_budget_config"] = (
        "wire" if met_wire else ("device-resident" if dev_met
                                 else "none"))
    return result


def _detect_wire_bytes(wire: str) -> int:
    """Bytes one detect frame ships over the host→device wire."""
    if wire == "dct8":
        from aiko_services_tpu.ops.image_wire import dct8_wire_bytes
        return dct8_wire_bytes(DETECT_IMAGE, DETECT_IMAGE)
    return DETECT_IMAGE * DETECT_IMAGE * 3          # raw uint8


def _hbm_in_use() -> str:
    try:
        stats = jax.devices()[0].memory_stats() or {}
        return f"{stats.get('bytes_in_use', 0) / 1e9:.2f} GB"
    except Exception:
        return "n/a"


def bench_state():
    """Session state plane rung (ISSUE 10): the open-loop session load
    generator across cardinality rungs — pure control plane (no
    device), so it runs identically on the TPU host and the CPU smoke.
    lat_state_p95_flat is the headline verdict: handler p95 must not
    grow a knee as live-session cardinality steps 1k → 100k."""
    from aiko_services_tpu.state.loadgen import (LoadConfig,
                                                 run_session_load)
    rungs = tuple(int(r) for r in os.environ.get(
        "AIKO_BENCH_STATE_RUNGS", "1000,10000,100000").split(",") if r)
    report = run_session_load(LoadConfig(rungs=rungs))
    first, last = report["rungs"][0], report["rungs"][-1]
    return {
        "lat_state_rungs": list(rungs),
        "lat_state_sustained_sessions": report["sustained_sessions"],
        "lat_state_peak_sessions": last["peak_sessions"],
        "lat_state_sessions_per_s": last["sessions_per_wall_s"],
        "lat_state_ops_per_s": last["ops_per_wall_s"],
        "lat_state_handler_p95_ms": last["handler_p95_ms"],
        "lat_state_handler_p95_ms_first": first["handler_p95_ms"],
        "lat_state_handler_mean_us": last["handler_mean_us"],
        "lat_state_handler_mean_us_first": first["handler_mean_us"],
        "lat_state_p95_ratio": report["flat"]["p95_ratio"],
        "lat_state_p95_flat": report["flat"]["ok"],
        "lat_state_lease_churn_per_s":
            last["lease_churn_per_virtual_s"],
        "lat_state_delta_bytes": last["delta_bytes"],
        "lat_state_max_expiry_batch": last["max_expiry_batch"],
        "lat_state_budgets_enforced": report["budgets"]["ok"],
        "lat_state_shed": report["budgets"]["flood_shed"],
        "lat_state_demoted": report["budgets"]["flood_demoted"],
        "lat_state_leaked_timers": report["drain"]["leaked_timers"],
        "lat_state_ok": report["ok"],
    }


def main() -> None:
    debug = "--debug" in sys.argv
    if debug:
        from aiko_services_tpu.ops import attention as attn_mod
        attn_mod.dispatch_stats.update(flash=0, xla=0)

    # llama first: the 1b preset at 128 slots needs ~12 GB HBM, which
    # only fits while nothing else has allocated; its own buffers are
    # dropped before the ASR/detect sections run.  Window floored at
    # 30 s: the serving cycle is ~1 s and short windows let cold-start
    # and tunnel variance swing the number +/-30% (12 s measured 4.8k
    # where three 30 s runs measured 7.3-7.4k tok/s)
    try:
        llama = bench_llama(max(PIPELINE_SECONDS, 30.0))
        print(f"llama serving: {llama}", file=sys.stderr)
    except Exception as exc:
        llama = {}
        print(f"llama bench failed: {exc!r}", file=sys.stderr)
    try:
        llama |= bench_llama_interactive()
        print(f"llama interactive SLOs: "
              f"{ {k: v for k, v in llama.items() if '_int_' in k} }",
              file=sys.stderr)
    except Exception as exc:
        print(f"llama interactive bench failed: {exc!r}",
              file=sys.stderr)
    try:
        llama |= bench_llama_conversation()
        print(f"llama conversation (prefix reuse): "
              f"{ {k: v for k, v in llama.items() if '_conv_' in k} }",
              file=sys.stderr)
    except Exception as exc:
        print(f"llama conversation bench failed: {exc!r}",
              file=sys.stderr)
    try:
        llama |= bench_llama_disagg()
        print(f"llama disaggregated two-pool: "
              f"{ {k: v for k, v in llama.items() if 'disagg' in k or '_coloc_' in k} }",
              file=sys.stderr)
    except Exception as exc:
        print(f"llama disagg bench failed: {exc!r}", file=sys.stderr)
    import gc
    gc.collect()
    jax.clear_caches()
    gc.collect()
    print(f"hbm after llama section: {_hbm_in_use()}", file=sys.stderr)

    config, params, model_times, (model_streams, model_latency,
                                  model_batch), model_mfu = model_ladder()

    # device-resident fused-program number: the "chip sustains X" claim
    # (a failed section reports absent fields, not zeros — same policy
    # as detect/llama below)
    try:
        (chip_streams, chip_round, chip_mfu, chip_batch,
         chip_phases) = bench_chip_asr(config, params,
                                       max(model_times))
        print(f"chip (device-resident μ-law fused): "
              f"{chip_streams:.0f} streams @ batch {chip_batch}, "
              f"{chip_round * 1000:.0f} ms/round"
              + (f", mfu={chip_mfu:.3f}" if chip_mfu else "")
              + (f", phases={chip_phases}" if chip_phases else ""),
              file=sys.stderr)
    except Exception as exc:
        chip_streams = chip_round = chip_mfu = None
        chip_batch = 0
        chip_phases = {}
        print(f"chip asr bench failed: {exc!r}", file=sys.stderr)
    del params

    # pipeline batch = the largest measured geometry (pad_batch means
    # the device always runs the full batch shape, so bigger amortizes
    # every per-batch cost); frontend picked empirically (see _FRONTENDS)
    batch = max(model_times)
    # the serving program compiles lazily inside warmup(), so the
    # transient-tunnel retry has to wrap the whole probe, not a
    # compile call site
    def run_with_fresh_bench(make):
        for attempt in (1, 2):
            instance = make()
            try:
                instance.warmup(batch)
                return instance
            except Exception as exc:
                del instance
                if attempt == 2 or not _transient_compile_error(exc):
                    raise
                print(f"pipeline warmup failed transiently ({exc!r}); "
                      f"retrying", file=sys.stderr)
                time.sleep(5.0)

    rounds = {}
    for frontend in _FRONTENDS:
        probe = run_with_fresh_bench(lambda: PipelineBench(batch,
                                                           frontend))
        rounds[frontend] = probe.measure_round(batch)
        del probe            # frees the probe's device params/runtime
        print(f"frontend={frontend}: {rounds[frontend]:.2f}s per "
              f"{batch}-batch round", file=sys.stderr)
    frontend = min(rounds, key=rounds.get)
    t_round = rounds[frontend]
    # serial capacity floor; the pipelined path can beat it (uploads
    # overlap compute), so the ladder searches above it too
    capacity = batch / t_round * CHUNK_SECONDS
    print(f"frontend={frontend} capacity≈{capacity:.0f} streams "
          f"(serial floor)", file=sys.stderr)
    # final bench: wait ≈ one device round so batches FILL under load
    # instead of firing sparse (pad_batch burns full-batch device time
    # either way)
    wait = min(2.0, max(0.1, 0.75 * t_round))
    drain_budget = max(2.0, 2.5 * t_round + wait)
    bench = run_with_fresh_bench(
        lambda: PipelineBench(batch, frontend, max_wait=wait))
    sustained, p50, frames, mean_batch, verified, rung_attempts = \
        bench_pipeline(bench, capacity, drain_budget)
    asr_program = bench.compute.programs["whisper_asr.PE_WhisperASR"]
    depth_peak = (asr_program.in_flight or {}).get("peak", 0)
    # drop the pipeline stack's device buffers (the program closure
    # holds the ASR params) before the remaining sections
    del asr_program, bench

    # low-latency operating point: sub-second chunks + deadline-aware
    # admission — the configuration the <150 ms budget is met at
    try:
        latency = bench_latency()
        print(f"latency section: {latency}", file=sys.stderr)
    except Exception as exc:
        latency = {}
        print(f"latency bench failed: {exc!r}", file=sys.stderr)

    # session state plane: control-plane only (no device buffers to
    # collide with the sections around it)
    try:
        state_fields = bench_state()
        print(f"state plane: {state_fields}", file=sys.stderr)
    except Exception as exc:
        state_fields = {}
        print(f"state bench failed: {exc!r}", file=sys.stderr)

    # independent sections run after the headline: a stalled section
    # must not discard the already-measured ASR numbers — report
    # without its fields instead
    try:
        detect_fps = bench_detect()
        print(f"detect: {detect_fps:.1f} frames/sec/chip "
              f"({DETECT_PRESET}@{DETECT_IMAGE})", file=sys.stderr)
    except Exception as exc:
        detect_fps = None
        print(f"detect bench failed: {exc!r}", file=sys.stderr)
    try:
        detect_device_fps, detect_mfu, detect_device_batch = \
            bench_detect_device()
        print(f"detect device-resident: {detect_device_fps:.0f} fps "
              f"@ batch {detect_device_batch}"
              + (f", mfu={detect_mfu:.3f}" if detect_mfu else ""),
              file=sys.stderr)
    except Exception as exc:
        detect_device_fps, detect_mfu = None, None
        detect_device_batch = 0
        print(f"detect device bench failed: {exc!r}", file=sys.stderr)

    if debug:
        from aiko_services_tpu.ops import attention as attn_mod
        stats = attn_mod.dispatch_stats
        if not stats["xla"] > 0:
            raise RuntimeError(
                f"expected XLA attention at seq 250 geometry, "
                f"got {stats}")
        if stats["flash"] != 0:
            raise RuntimeError(
                f"flash must not fire below seq "
                f"{attn_mod.FLASH_MIN_SEQ}: {stats}")
        print(f"debug: attention dispatch {stats}", file=sys.stderr)

    peak, device_kind = device_peak_flops()
    print(json.dumps({
        "metric":
            "whisper_small_pipeline_realtime_streams_per_chip_sustained",
        "value": round(sustained, 2),
        "unit": "streams",
        "vs_baseline": round(sustained / 1.0, 2),
        "sustained_verified": bool(verified),
        "rung_attempts": {str(k): v for k, v in rung_attempts.items()},
        "pipeline_p50_ms": round(p50 * 1000.0, 1),
        # met when ANY declared configuration holds >=200 streams under
        # 150 ms p50 — the headline 5s-chunk rung, or the latency
        # section's sub-second configs (see latency_budget_config)
        "latency_budget_met": bool(
            (p50 <= LATENCY_BUDGET and sustained >= 200) or
            latency.get("latency_budget_met", False)),
        "pipeline_frames": frames,
        "mean_device_batch": round(mean_batch, 1),
        "frontend": frontend,
        "wire": "mulaw8" if frontend == "audio" else "mel-f32",
        "batch_round_ms": round(t_round * 1000.0, 1),
        "in_flight_depth": DEPTH,
        "in_flight_peak": depth_peak,
        "model_streams": round(model_streams, 2),
        "model_p50_ms": round(model_latency * 1000.0, 1),
        "device_batch": batch,
        "device_kind": device_kind,
        "peak_tflops_assumed": round(peak / 1e12, 1) if peak else None,
    } | ({} if chip_streams is None else {
        "chip_sustained_streams": round(chip_streams, 1),
        "chip_round_ms": round(chip_round * 1000.0, 1),
        "chip_batch": chip_batch,
    } | chip_phases) | ({} if model_mfu is None else {
        "model_mfu": round(model_mfu, 4)})
      | ({} if chip_mfu is None else {
        "chip_mfu": round(chip_mfu, 4)})
      | ({} if detect_fps is None else {
        "detect_fps_per_chip": round(detect_fps, 1),
        "detect_config": f"{DETECT_PRESET}@{DETECT_IMAGE}px"
                         f"→tracker, batch {DETECT_BATCH}, "
                         f"wire {DETECT_WIRE}",
    }) | ({} if detect_device_fps is None else {
        "detect_fps_device": round(detect_device_fps, 1),
        "detect_device_batch": detect_device_batch,
        # wire-cost arithmetic (r4 verdict item 6): bytes one camera
        # frame ships per wire mode, and the tunnel bandwidth at which
        # the pipeline leg would saturate the device — pins how much of
        # the pipeline/device gap is environmental
        "detect_wire_bytes_dct8": _detect_wire_bytes("dct8"),
        "detect_wire_bytes_raw": DETECT_IMAGE * DETECT_IMAGE * 3,
        "detect_breakeven_MBps": round(
            detect_device_fps * _detect_wire_bytes(DETECT_WIRE) / 1e6,
            1),
    }) | ({} if detect_mfu is None else {
        "detect_mfu": round(detect_mfu, 4),
    }) | state_fields | {k: v for k, v in latency.items()
                         if k != "latency_budget_met"} | llama))


if __name__ == "__main__":
    main()
