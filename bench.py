# Benchmark: Whisper-small streaming ASR throughput on one chip.
#
# The BASELINE.md headline metric is "speech pipeline real-time-factor":
# how many concurrent real-time audio streams one chip sustains.  The
# reference wraps faster-whisper on CUDA, single stream, tensors
# serialized through an MQTT broker (reference: examples/speech/
# speech_elements.py:174-250); it publishes no numbers, so the implied
# baseline is 1.0 (one real-time stream — what its pipeline sustains by
# construction, SURVEY.md §6).
#
# Measures: batched greedy decode (encoder + KV-cache token scan) over a
# batch of CHUNK_SECONDS-second utterances in bfloat16 on the flagship
# Whisper-small geometry.  streams = audio-seconds decoded per wall-second.
#
# Prints ONE JSON line:
#   {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

from __future__ import annotations

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from aiko_services_tpu.models import WhisperConfig, whisper_init
from aiko_services_tpu.models.whisper import greedy_decode

CHUNK_SECONDS = 5.0           # streaming chunk size (audio_io.py-style)
FRAMES_PER_SECOND = 100       # whisper log-mel frame rate
BATCH_LADDER = (16, 32, 64)   # candidate batch sizes
LATENCY_BUDGET = 0.150        # north-star p50 bound (BASELINE.md)
MAX_TOKENS = 24               # tokens decoded per 5 s chunk (typical speech)
REPEATS = 5


def measure(config, params, batch: int) -> float:
    """Per-batch decode wall time with hard host-transfer sync
    (block_until_ready does not synchronize through the TPU tunnel)."""
    frames = config.n_audio_ctx * 2
    mel = jax.random.normal(jax.random.PRNGKey(1),
                            (batch, frames, config.n_mels), jnp.bfloat16)
    decode = jax.jit(lambda params, mel: greedy_decode(
        params, config, mel, max_tokens=MAX_TOKENS))
    np.asarray(decode(params, mel)[0])        # compile + warmup
    start = time.perf_counter()
    for _ in range(REPEATS):
        np.asarray(decode(params, mel)[0])
    return (time.perf_counter() - start) / REPEATS


def main() -> None:
    frames = int(CHUNK_SECONDS * FRAMES_PER_SECOND)
    config = WhisperConfig(dim=768, num_heads=12, enc_layers=12,
                           dec_layers=12, n_audio_ctx=frames // 2,
                           n_text_ctx=MAX_TOKENS + 8, dtype=jnp.bfloat16)
    params = whisper_init(jax.random.PRNGKey(0), config)

    # largest batch whose chunk-decode latency stays inside the latency
    # budget wins; throughput is then latency-bounded concurrent streams
    best_streams, best_latency, best_batch = 0.0, None, None
    for batch in BATCH_LADDER:
        elapsed = measure(config, params, batch)
        streams = batch * CHUNK_SECONDS / elapsed
        if elapsed <= LATENCY_BUDGET and streams > best_streams:
            best_streams, best_latency, best_batch = (streams, elapsed,
                                                      batch)
        if elapsed > LATENCY_BUDGET:
            break                             # latency grows with batch
    if best_batch is None:                    # nothing met the budget
        batch = BATCH_LADDER[0]
        best_latency = measure(config, params, batch)
        best_streams = batch * CHUNK_SECONDS / best_latency

    print(json.dumps({
        "metric": "whisper_small_realtime_streams_per_chip_p50_under_150ms",
        "value": round(best_streams, 2),
        "unit": "streams",
        "vs_baseline": round(best_streams / 1.0, 2),
    }))


if __name__ == "__main__":
    main()
