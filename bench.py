# Benchmark: Whisper-small streaming ASR throughput on one chip.
#
# The BASELINE.md headline metric is "speech pipeline real-time-factor":
# how many concurrent real-time audio streams one chip sustains.  The
# reference wraps faster-whisper on CUDA, single stream, tensors
# serialized through an MQTT broker (reference: examples/speech/
# speech_elements.py:174-250); it publishes no numbers, so the implied
# baseline is 1.0 (one real-time stream — what its pipeline sustains by
# construction, SURVEY.md §6).
#
# Measures: batched greedy decode (encoder + KV-cache token scan) over a
# batch of CHUNK_SECONDS-second utterances in bfloat16 on the flagship
# Whisper-small geometry.  streams = audio-seconds decoded per wall-second.
#
# Prints ONE JSON line:
#   {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

from __future__ import annotations

import json
import time

import numpy as np

import jax
import jax.numpy as jnp

from aiko_services_tpu.models import WhisperConfig, whisper_init
from aiko_services_tpu.models.whisper import greedy_decode

CHUNK_SECONDS = 5.0           # streaming chunk size (audio_io.py-style)
FRAMES_PER_SECOND = 100       # whisper log-mel frame rate
BATCH = 32                    # concurrent streams per device step
MAX_TOKENS = 24               # tokens decoded per 5 s chunk (typical speech)
REPEATS = 5


def main() -> None:
    frames = int(CHUNK_SECONDS * FRAMES_PER_SECOND)
    config = WhisperConfig(dim=768, num_heads=12, enc_layers=12,
                           dec_layers=12, n_audio_ctx=frames // 2,
                           n_text_ctx=MAX_TOKENS + 8, dtype=jnp.bfloat16)
    params = whisper_init(jax.random.PRNGKey(0), config)
    mel = jax.random.normal(jax.random.PRNGKey(1),
                            (BATCH, frames, config.n_mels), jnp.bfloat16)

    decode = jax.jit(lambda params, mel: greedy_decode(
        params, config, mel, max_tokens=MAX_TOKENS))

    tokens, lengths = decode(params, mel)     # compile + warmup
    np.asarray(tokens)

    # hard sync each iteration via host transfer: block_until_ready does
    # not reliably synchronize through the remote-TPU tunnel
    start = time.perf_counter()
    for _ in range(REPEATS):
        tokens, lengths = decode(params, mel)
        np.asarray(tokens)
    elapsed = (time.perf_counter() - start) / REPEATS

    audio_seconds = BATCH * CHUNK_SECONDS
    streams = audio_seconds / elapsed         # concurrent real-time streams
    print(json.dumps({
        "metric": "whisper_small_concurrent_realtime_streams_per_chip",
        "value": round(streams, 2),
        "unit": "streams",
        "vs_baseline": round(streams / 1.0, 2),
    }))


if __name__ == "__main__":
    main()
