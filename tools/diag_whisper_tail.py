# diagnostic harness: the console readout is the product
# graft: disable-file=lint-print
# Diagnose the whisper decode tail's HBM efficiency (r5, verdict item 3
# follow-through) with the same slope method that cracked the llama
# decode scan (serving.py KV_WRITE="block" — see its header comment):
#
#   1. decode-tail step time vs n_audio_ctx at the bench geometry
#      (whisper-small bf16, batch 256): the slope is the effective
#      cross-KV read bandwidth (bytes/frame is exact arithmetic), the
#      intercept is the fixed per-step cost (weights read + ~170 small
#      ops on [B,1,768] activations + self-KV);
#   2. the fused-program ladder extended to batch 512 (the bench stops
#      at 4x base = 256, which WON its ladder — meaning scaling hadn't
#      flattened when the ladder ran out).
#
# Usage (on the TPU machine, nothing else running — one CPU core):
#   python tools/diag_whisper_tail.py [--skip-512]
#
# Timing discipline per .claude/skills/verify: chained device programs
# with a forced host transfer per measurement (block_until_ready does
# not reliably sync through the axon tunnel).

from __future__ import annotations

import dataclasses
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from aiko_services_tpu.models import whisper_init  # noqa: E402
from aiko_services_tpu.models.whisper import (  # noqa: E402
    WHISPER_PRESETS, encode, greedy_decode_from_audio,
    precompute_cross_kv)

BATCH = 256
MAX_TOKENS = 24
SPEC_GBPS = 819.0  # v5e


from diag_membw import timed_chain as timed  # noqa: E402  shared harness


# The achievable-bandwidth ceiling lives in tools/diag_membw.py (the
# two-point rep fit: ~730-750 GB/s measured r5).  A chain=4 sum probe
# lived here first and reported ~150 GB/s — it was timing the ~108 ms
# tunnel dispatch floor, not the read.
ACHIEVABLE_GBPS = 740.0


def tail_config(n_audio_ctx):
    return dataclasses.replace(
        WHISPER_PRESETS["small"], n_audio_ctx=n_audio_ctx,
        n_text_ctx=MAX_TOKENS + 8, dtype=jnp.bfloat16)


def tail_step_ms(params, config, batch=BATCH):
    """Decode tail only: from precomputed audio features, run
    precompute_cross_kv + the 24-step greedy scan.  The cross-KV
    projection is subtracted via a second program that stops there."""
    audio = jnp.zeros((batch, config.n_audio_ctx, config.dim),
                      jnp.bfloat16)

    def tail(params, audio):
        tokens, lengths, score = greedy_decode_from_audio(
            params, config, audio, max_tokens=MAX_TOKENS)
        return jnp.sum(lengths) + jnp.sum(score, dtype=jnp.float32)

    def kv_only(params, audio):
        kv = precompute_cross_kv(params, config, audio)
        return sum(jnp.sum(leaf, dtype=jnp.float32)
                   for leaf in jax.tree_util.tree_leaves(kv))

    t_tail = timed(jax.jit(tail), params, audio)
    t_kv = timed(jax.jit(kv_only), params, audio)
    return (t_tail - t_kv) * 1000.0 / MAX_TOKENS


def cross_kv_bytes_per_frame(config, batch=BATCH):
    # K + V, every decoder layer, bf16
    return batch * config.dec_layers * 2 * config.dim * 2


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.device_kind}", flush=True)

    gbps = ACHIEVABLE_GBPS
    print(f"achievable-read reference: {gbps:.0f} GB/s "
          f"(tools/diag_membw.py two-point fit)", flush=True)

    ctxs = (125, 250, 375, 500)
    steps = []
    params = None
    for ctx in ctxs:
        config = tail_config(ctx)
        if params is None:
            params = whisper_init(jax.random.PRNGKey(0), config)
        ms = tail_step_ms(params, config)
        steps.append(ms)
        print(f"n_audio_ctx {ctx}: tail step {ms:.2f} ms", flush=True)

    # least-squares slope/intercept of step-ms vs ctx
    x = np.array(ctxs, float)
    y = np.array(steps, float)
    slope_ms, intercept_ms = np.polyfit(x, y, 1)
    bpf = cross_kv_bytes_per_frame(tail_config(250))
    eff_gbps = bpf / (slope_ms / 1000.0) / 1e9
    print(f"slope {slope_ms * 1000:.2f} us/frame, intercept "
          f"{intercept_ms:.2f} ms/step", flush=True)
    print(f"cross-KV bytes/frame {bpf} -> effective read bandwidth "
          f"{eff_gbps:.0f} GB/s ({eff_gbps / gbps:.0%} of achievable, "
          f"{eff_gbps / SPEC_GBPS:.0%} of spec)", flush=True)
    print(f"fixed per-step cost {intercept_ms:.2f} ms vs cross-KV read "
          f"at ctx 250: {250 * slope_ms:.2f} ms", flush=True)

    if "--skip-512" not in sys.argv:
        # does the fused ladder keep scaling past 256?
        from aiko_services_tpu.ops.audio import (WHISPER_HOP,
                                                 log_mel_spectrogram,
                                                 mulaw_decode)
        config = tail_config(250)
        samples = config.n_audio_ctx * 2 * WHISPER_HOP

        def fused(params, pcm):
            audio = mulaw_decode(pcm)
            mel = log_mel_spectrogram(audio, num_mels=config.n_mels)
            tokens, lengths, _ = greedy_decode_from_audio(
                params, config,
                encode(params, config, mel.astype(config.dtype)),
                max_tokens=MAX_TOKENS)
            return jnp.sum(lengths)

        jfused = jax.jit(fused)
        for batch in (256, 512):
            codes = jax.random.randint(
                jax.random.PRNGKey(2), (batch, samples), 0, 256,
                jnp.int32).astype(jnp.uint8)
            try:
                seconds = timed(jfused, params, codes)
            except Exception as exc:
                print(f"batch {batch}: failed {exc!r}", flush=True)
                break
            streams = batch * 5.0 / seconds
            print(f"batch {batch}: round {seconds * 1000:.0f} ms -> "
                  f"{streams:.0f} device-resident streams", flush=True)


if __name__ == "__main__":
    main()
