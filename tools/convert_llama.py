#!/usr/bin/env python3
# conversion CLI: progress goes to the console by design
# graft: disable-file=lint-print
"""Convert a HuggingFace Llama checkpoint directory to this framework's
flat-npz weight scheme.

Usage:
    python tools/convert_llama.py /path/to/llama-hf out_dir/

Input directory layout (what `huggingface-cli download meta-llama/...`
produces): model.safetensors / model-0000N-of-*.safetensors /
pytorch_model.bin, plus tokenizer files.  Output: out_dir/weights.npz
with '/'-joined tree paths into models/llama.py's param tree
(loadable via elements.speech.load_flat_npz), and tokenizer files
copied through for models/tokenizer.load_tokenizer.

Two real transformations beyond renaming:
  * torch Linear stores [out, in]; this framework stores [in, out] → T;
  * HF attention was trained with the rotate_half RoPE convention
    (pairs (i, i + D/2)); models/layers.apply_rope rotates interleaved
    pairs (2i, 2i+1).  Q/K projection OUTPUT rows are permuted per head
    so the checkpoint works under the interleaved convention:
    new[2i] = old[i], new[2i+1] = old[i + D/2].

Runs fully offline; torch-cpu suffices.  Reference parity: the
reference's LLM hop is an HTTP request to an external server
(examples/speech/speech_elements.py:155-172) — it never loads weights;
here real Llama checkpoints serve through PE_LlamaAgent/serving.py.
"""

import argparse
import glob
import os
import shutil
import sys

import numpy as np


def load_state_dict(model_dir: str) -> dict:
    shards = sorted(glob.glob(os.path.join(model_dir,
                                           "model*.safetensors")))
    if shards:
        from safetensors import safe_open
        state = {}
        for shard in shards:
            with safe_open(shard, framework="np") as handle:
                for key in handle.keys():
                    state[key] = handle.get_tensor(key)
        return state
    torch_path = os.path.join(model_dir, "pytorch_model.bin")
    if os.path.exists(torch_path):
        import torch
        state = torch.load(torch_path, map_location="cpu",
                           weights_only=True)
        return {k: v.float().numpy() for k, v in state.items()}
    raise FileNotFoundError(
        f"no model*.safetensors or pytorch_model.bin in {model_dir}")


def permute_rope_rows(weight: np.ndarray, num_heads: int) -> np.ndarray:
    """Reorder a [H*D, in] projection's output rows from rotate_half to
    interleaved RoPE pairing, per head."""
    out_dim, in_dim = weight.shape
    head_dim = out_dim // num_heads
    half = head_dim // 2
    per_head = weight.reshape(num_heads, head_dim, in_dim)
    interleaved = np.empty_like(per_head)
    interleaved[:, 0::2] = per_head[:, :half]
    interleaved[:, 1::2] = per_head[:, half:]
    return interleaved.reshape(out_dim, in_dim)


def convert(state: dict, num_heads: int, num_kv_heads: int) -> dict:
    out = {}
    out["embed/table"] = state["model.embed_tokens.weight"]
    layer_indices = sorted({
        int(key.split(".")[2]) for key in state
        if key.startswith("model.layers.")})
    for i in layer_indices:
        hf = f"model.layers.{i}"
        mine = f"layers/{i}"
        out[f"{mine}/ln_attn/scale"] = \
            state[f"{hf}.input_layernorm.weight"]
        out[f"{mine}/ln_mlp/scale"] = \
            state[f"{hf}.post_attention_layernorm.weight"]
        out[f"{mine}/attn/q/w"] = permute_rope_rows(
            state[f"{hf}.self_attn.q_proj.weight"], num_heads).T
        out[f"{mine}/attn/k/w"] = permute_rope_rows(
            state[f"{hf}.self_attn.k_proj.weight"], num_kv_heads).T
        out[f"{mine}/attn/v/w"] = state[f"{hf}.self_attn.v_proj.weight"].T
        out[f"{mine}/attn/o/w"] = state[f"{hf}.self_attn.o_proj.weight"].T
        out[f"{mine}/gate/w"] = state[f"{hf}.mlp.gate_proj.weight"].T
        out[f"{mine}/up/w"] = state[f"{hf}.mlp.up_proj.weight"].T
        out[f"{mine}/down/w"] = state[f"{hf}.mlp.down_proj.weight"].T
    out["ln_out/scale"] = state["model.norm.weight"]
    if "lm_head.weight" in state:
        out["lm_head/w"] = state["lm_head.weight"].T
    else:   # tied embeddings (llama-3.2 style)
        out["lm_head/w"] = state["model.embed_tokens.weight"].T
    return out


def read_head_config(model_dir: str):
    """Head counts from the checkpoint's own config.json — wrong manual
    flags would produce a shape-valid but silently garbage RoPE
    permutation."""
    config_path = os.path.join(model_dir, "config.json")
    if not os.path.exists(config_path):
        return None, None
    import json
    with open(config_path, encoding="utf-8") as handle:
        config = json.load(handle)
    heads = config.get("num_attention_heads")
    return heads, config.get("num_key_value_heads", heads)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("model_dir")
    parser.add_argument("out_dir")
    parser.add_argument("--num-heads", type=int, default=None,
                        help="attention heads (default: read from the "
                             "checkpoint's config.json)")
    parser.add_argument("--num-kv-heads", type=int, default=None,
                        help="KV heads (default: read from config.json)")
    args = parser.parse_args()

    config_heads, config_kv = read_head_config(args.model_dir)
    num_heads = args.num_heads or config_heads
    num_kv_heads = args.num_kv_heads or config_kv
    if not num_heads or not num_kv_heads:
        config_path = os.path.join(args.model_dir, "config.json")
        reason = ("has no num_attention_heads/num_key_value_heads "
                  "entries" if os.path.exists(config_path)
                  else "does not exist")
        parser.error(f"{config_path} {reason}: pass "
                     f"--num-heads/--num-kv-heads explicitly")
    state = load_state_dict(args.model_dir)
    flat = convert(state, num_heads, num_kv_heads)
    os.makedirs(args.out_dir, exist_ok=True)
    np.savez(os.path.join(args.out_dir, "weights.npz"),
             **{k: np.asarray(v, np.float32) for k, v in flat.items()})
    for name in ("tokenizer.json", "tokenizer_config.json", "vocab.json",
                 "merges.txt"):
        src = os.path.join(args.model_dir, name)
        if os.path.exists(src):
            shutil.copy(src, os.path.join(args.out_dir, name))
    print(f"wrote {len(flat)} tensors to "
          f"{os.path.join(args.out_dir, 'weights.npz')}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
