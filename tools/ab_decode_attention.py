# A/B the decode-attention inner loop IN-PROGRAM (serving._build_step,
# the exact compiled step the ContinuousDecoder runs): two_pass
# (score/weight einsums) vs online (flash-style single sweep) vs vpu
# (broadcast-multiply reductions).  Microbenchmark wins do not survive
# program context (measured on the int8-KV lever: +35% isolated, -24%
# fused), so the only number that counts is the chained full-step time
# at the serving shape.
#
#   python tools/ab_decode_attention.py [preset] [slots] [cache_t]

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def measure(impl: str, preset: str, slots: int, cache_t: int,
            num_steps: int = 64, chains: int = 4,
            kv_write: str = "select") -> float:
    from aiko_services_tpu import serving
    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init

    # ATTENTION_IMPL only affects the "select" step (the block-KV scan
    # hardcodes the two-pass einsums) — force the KV mode so the
    # labels mean what they say
    serving.KV_WRITE = kv_write
    serving.ATTENTION_IMPL = impl
    config = dataclasses.replace(LLAMA_PRESETS[preset],
                                 dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    step = serving._build_step(config)
    shape = (slots, config.num_kv_heads, cache_t, config.head_dim)
    k = [jnp.zeros(shape, config.dtype)
         for _ in range(config.num_layers)]
    v = [jnp.zeros(shape, config.dtype)
         for _ in range(config.num_layers)]
    tokens = jnp.ones((slots,), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    budgets = jnp.full((slots,), 1 << 30, jnp.int32)

    def chain(rounds):
        nonlocal tokens, lengths, k, v
        out = None
        for _ in range(rounds):
            out = step(params, tokens, lengths, active, budgets, k, v,
                       num_steps=num_steps, eos=-1)
            _, _, tokens, lengths, k, v = out
        np.asarray(out[0][-1])            # one sync for the chain
    chain(1)                               # compile + warm
    best = float("inf")
    for _ in range(3):
        start = time.perf_counter()
        chain(chains)
        best = min(best, (time.perf_counter() - start) /
                   (chains * num_steps))
    return best * 1000.0


def main() -> None:
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    cache_t = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    cases = [("two_pass", "select"), ("online", "select"),
             ("vpu", "select"), ("two_pass", "block")]
    for impl, kv_write in cases:
        label = f"{impl}/{kv_write}"
        try:
            ms = measure(impl, preset, slots, cache_t,
                         kv_write=kv_write)
            print(f"{label:17s}: {ms:.3f} ms/step "
                  f"({preset}, {slots} slots, cache {cache_t})")
        except Exception as exc:
            print(f"{label:17s}: FAILED {exc!r}")
        jax.clear_caches()


if __name__ == "__main__":
    main()
