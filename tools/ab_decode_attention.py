# A/B harness: the console comparison table is the product
# graft: disable-file=lint-print
# A/B the decode-attention inner loop IN-PROGRAM (serving._build_step,
# the exact compiled step the ContinuousDecoder runs): two_pass
# (score/weight einsums) vs online (flash-style single sweep) vs vpu
# (broadcast-multiply reductions), plus the paged-pool pair —
# gather-oracle vs the fused pallas kernel (ISSUE 16) — so BENCH_r06
# can price the gather deletion at the serving shape.  Microbenchmark
# wins do not survive program context (measured on the int8-KV lever:
# +35% isolated, -24% fused), so the only number that counts is the
# chained full-step time at the serving shape.
#
# Any case that errors is reported AND fails the run (exit 1): PR 7's
# signature change silently broke all four cases for a whole bench
# round because the harness swallowed the exceptions.
#
#   python tools/ab_decode_attention.py [preset] [slots] [cache_t]

from __future__ import annotations

import dataclasses
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def measure(impl: str, preset: str, slots: int, cache_t: int,
            num_steps: int = 64, chains: int = 4,
            kv_write: str = "select") -> float:
    from aiko_services_tpu import serving
    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init

    # ATTENTION_IMPL only affects the "select" step (the block-KV scan
    # hardcodes the two-pass einsums) — force the KV mode so the
    # labels mean what they say
    serving.KV_WRITE = kv_write
    serving.ATTENTION_IMPL = impl
    config = dataclasses.replace(LLAMA_PRESETS[preset],
                                 dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    step = serving._build_step(config)
    shape = (slots, config.num_kv_heads, cache_t, config.head_dim)
    k = [jnp.zeros(shape, config.dtype)
         for _ in range(config.num_layers)]
    v = [jnp.zeros(shape, config.dtype)
         for _ in range(config.num_layers)]
    tokens = jnp.ones((slots,), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    budgets = jnp.full((slots,), 1 << 30, jnp.int32)

    def chain(rounds):
        nonlocal tokens, lengths, k, v
        out = None
        for _ in range(rounds):
            out = step(params, tokens, lengths, active, budgets, k, v,
                       num_steps=num_steps, eos=-1)
            _, _, tokens, lengths, k, v = out
        np.asarray(out[0][-1])            # one sync for the chain
    chain(1)                               # compile + warm
    best = float("inf")
    for _ in range(3):
        start = time.perf_counter()
        chain(chains)
        best = min(best, (time.perf_counter() - start) /
                   (chains * num_steps))
    return best * 1000.0


def measure_paged(kernel: bool, preset: str, slots: int, cache_t: int,
                  num_steps: int = 64, chains: int = 4,
                  block_tokens: int = 32) -> float:
    """Chained paged-step time: gather oracle (kernel=False) vs the
    fused pallas kernel reading pool blocks through the table."""
    from aiko_services_tpu import serving_paged
    from aiko_services_tpu.models.llama import LLAMA_PRESETS, llama_init

    config = dataclasses.replace(LLAMA_PRESETS[preset],
                                 dtype=jnp.bfloat16, max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)
    step = serving_paged._paged_step_for(config, kernel)
    nb = -(-cache_t // block_tokens)
    pool_shape = (1 + slots * nb, config.num_kv_heads, block_tokens,
                  config.head_dim)
    k = [jnp.zeros(pool_shape, config.dtype)
         for _ in range(config.num_layers)]
    v = [jnp.zeros(pool_shape, config.dtype)
         for _ in range(config.num_layers)]
    # block 0 is the pool's null block; each slot owns a contiguous run
    tables = (1 + jnp.arange(slots * nb, dtype=jnp.int32)
              ).reshape(slots, nb)
    tokens = jnp.ones((slots,), jnp.int32)
    lengths = jnp.zeros((slots,), jnp.int32)
    active = jnp.ones((slots,), bool)
    budgets = jnp.full((slots,), 1 << 30, jnp.int32)

    def chain(rounds):
        nonlocal tokens, lengths, k, v
        out = None
        for _ in range(rounds):
            out = step(params, tokens, lengths, active, budgets, k, v,
                       tables, num_steps=num_steps, eos=-1,
                       t_cap=cache_t)
            _, _, tokens, lengths, k, v = out
        np.asarray(out[0][-1])
    chain(1)
    best = float("inf")
    for _ in range(3):
        start = time.perf_counter()
        chain(chains)
        best = min(best, (time.perf_counter() - start) /
                   (chains * num_steps))
    return best * 1000.0


def main() -> None:
    preset = sys.argv[1] if len(sys.argv) > 1 else "1b"
    slots = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    cache_t = int(sys.argv[3]) if len(sys.argv) > 3 else 256
    cases = [
        ("two_pass/select",
         lambda: measure("two_pass", preset, slots, cache_t)),
        ("online/select",
         lambda: measure("online", preset, slots, cache_t)),
        ("vpu/select",
         lambda: measure("vpu", preset, slots, cache_t)),
        ("two_pass/block",
         lambda: measure("two_pass", preset, slots, cache_t,
                         kv_write="block")),
        ("paged/gather",
         lambda: measure_paged(False, preset, slots, cache_t)),
        ("paged/kernel",
         lambda: measure_paged(True, preset, slots, cache_t)),
    ]
    failed = []
    for label, case in cases:
        try:
            ms = case()
            print(f"{label:17s}: {ms:.3f} ms/step "
                  f"({preset}, {slots} slots, cache {cache_t})")
        except Exception as exc:
            print(f"{label:17s}: FAILED {exc!r}")
            failed.append(label)
        jax.clear_caches()
    if failed:
        print(f"FAILED cases: {', '.join(failed)}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
