# A/B harness: the console comparison table is the product
# graft: disable-file=lint-print
# In-program A/B of weight-only int8 serving (W8A16,
# layers.quantize_linear_tree) at the bench's llama geometry: 1b bf16,
# 256 slots, closed loop.  Decode serving streams the full weight set
# every step (2.47 GB of the ~4.6 GB step read), so halving weight
# bytes is the largest single lever left after the r5 block-KV scan —
# IF the int8 convert fuses in the real program the way the isolated
# probes (tools/diag_attn_patterns.py mha1q) and the cross-KV fold
# (tools/ab_cross_kv.py) measured.
#
# Prints tok/s + pure-device chained step time per mode, plus greedy
# token parity on a fixed prompt set.

from __future__ import annotations

import dataclasses
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")

from aiko_services_tpu.models.llama import (  # noqa: E402
    LLAMA_PRESETS, llama_init)
from aiko_services_tpu.serving import (  # noqa: E402
    ContinuousDecoder, measure_device_step)

SLOTS = 256
WINDOW = float(os.environ.get("AB_W8_WINDOW", "20"))
# AB_MODE selects the serving variant under test vs the plain decoder:
#   w8   — weight-only int8 (weight_quant=True); measured r5: a wash
#   fuse — fused qkv + gate_up projections (fuse_projections=True)
MODE = os.environ.get("AB_MODE", "w8")
MODE_KWARG = {"w8": "weight_quant", "fuse": "fuse_projections"}[MODE]


def build(params, config, enabled):
    return ContinuousDecoder(params, config, max_slots=SLOTS,
                             max_seq=1024, prefill_buckets=(128,),
                             steps_per_sync=64,
                             **{MODE_KWARG: enabled},
                             name=f"{MODE}_{int(enabled)}")


def closed_loop(decoder, rng):
    generated = [0]
    submitted = [0]
    deadline = [time.perf_counter() + 3600.0]

    def submit_one():
        prompt = rng.integers(
            1, decoder.config.vocab,
            size=int(rng.integers(16, 120))).tolist()
        request_id = f"r{submitted[0]}"
        submitted[0] += 1
        decoder.submit(request_id, prompt, 64,
                       lambda rid, tokens: on_done(tokens))

    def on_done(tokens):
        generated[0] += len(tokens)
        if time.perf_counter() < deadline[0]:
            submit_one()

    for _ in range(2 * SLOTS):          # warmup: compile + fill
        submit_one()
    decoder.pump()
    decoder.pump()        # second round compiles the decode scan
                          # (round 1 dispatches admits only since the
                          # decode-first rework)
    # same post-warmup reset protocol as bench.bench_llama (the
    # canonical closed-loop methodology this tool mirrors): compile
    # time must not contaminate stats or SLO percentiles
    for key in decoder.stats:
        decoder.stats[key] = 0 if isinstance(decoder.stats[key], int) \
            else 0.0
    decoder.ttft_samples.clear()
    decoder.itl_samples.clear()
    decoder.gap_samples.clear()
    generated[0] = 0
    start = time.perf_counter()
    deadline[0] = start + WINDOW
    while time.perf_counter() < deadline[0] or not decoder.idle:
        decoder.pump()
    elapsed = time.perf_counter() - start
    return generated[0] / elapsed


def parity(params, config, n=32):
    """Greedy outputs for n fixed prompts under both modes."""
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, config.vocab,
                            size=int(rng.integers(8, 100))).tolist()
               for _ in range(n)]
    outs = {}
    for wq in (False, True):
        decoder = build(params, config, wq)
        done = {}
        for i, prompt in enumerate(prompts):
            decoder.submit(f"p{i}", prompt, 32,
                           lambda rid, toks, i=i: done.setdefault(i,
                                                                  toks))
        for _ in range(600):
            if len(done) == n:
                break
            decoder.pump()
        if len(done) != n:
            raise RuntimeError(f"only {len(done)}/{n} completed")
        outs[wq] = done
        del decoder
    total = match = 0
    for i in range(n):
        a, b = outs[False][i], outs[True][i]
        k = min(len(a), len(b))
        match += sum(x == y for x, y in zip(a[:k], b[:k]))
        total += k
    return match / max(total, 1)


def main():
    base = LLAMA_PRESETS[os.environ.get("AB_W8_PRESET", "1b")]
    config = dataclasses.replace(base, dtype=jnp.bfloat16,
                                 max_seq_len=1024)
    params = llama_init(jax.random.PRNGKey(0), config)

    for wq in (False, True):
        decoder = build(params, config, wq)
        tps = closed_loop(decoder, np.random.default_rng(11))
        step_ms = measure_device_step(decoder)
        print(f"{MODE_KWARG}={wq}: {tps:,.0f} tok/s"
              + (f", device step {step_ms:.2f} ms"
                 if step_ms is not None else ""), flush=True)
        del decoder

    print(f"token parity (32 fixed prompts, 32 tokens): "
          f"{parity(params, config):.4f}", flush=True)


if __name__ == "__main__":
    main()
